"""Self-calibrating link/tier transfer-cost model (NetKV, PAPERS.md).

The KV scheduler's PR 9 scoring treats every byte of cache reuse as
free: a peer pull over DCN scores the same as blocks already hot in
HBM. This module makes the heterogeneity measurable so routing can
minimize **predicted TTFT = queue_wait + transfer + prefill** instead
of maximizing raw overlap:

* **Worker side** — each worker owns a :class:`TransferCostModel` and
  folds the transfer plane's *own observed timings* into per-link-class
  bandwidth/latency EWMAs: h2d restores ("host"), disk promotions
  ("disk"), peer prefix pulls ("peer"), same-slice device→device
  handoffs ("ici"), cross-host streamed/bulk sends ("dcn"), plus an
  observed prefill token throughput (roofline-seedable, corrected by
  measured chunk timings exactly like the planner's ``CapacityModel``).
  The estimates ship in ``load_metrics`` → ``WorkerLoad`` so the router
  sees every candidate's calibration — nothing is configured, nothing
  is guessed twice.

* **Router side** — :func:`predict_worker_ttft_ms` converts one
  candidate's per-tier overlap depths (``OverlapScores.device_scores``
  + the PR 9 tier-inclusive overlay) into milliseconds using that
  candidate's advertised link speeds: device blocks cost ~0, host/disk
  blocks cost restore time, peer-held continuations cost pull time over
  the observed link (ICI class when the serving peer shares the
  candidate's slice), and missing blocks cost modeled prefill. Returns
  ``None`` while the candidate is cold (< ``min_obs`` observations, or
  geometry/throughput unadvertised) — the scheduler then falls back to
  the overlap scoring wholesale, so a half-calibrated fleet never mixes
  incomparable score scales.

Link classes are deliberately coarse (class, not per-peer-edge): the
estimate is an EWMA over whatever traffic the class actually carried,
which is the same granularity the placement decision needs.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field
from typing import Optional

#: the link classes a worker can observe, slowest-to-fastest in a
#: typical deployment: cross-host TCP (dcn), peer prefix pulls (peer —
#: also TCP today, but measured separately because serve-side export
#: stacking dominates small pulls), local disk promotion (disk), host
#: h2d restore (host), same-slice device→device handoff (ici)
LINK_CLASSES = ("dcn", "peer", "disk", "host", "ici")


@dataclass
class LinkEstimate:
    """EWMA bandwidth + per-op latency for one link class."""

    gbps: float = 0.0  # gigaBYTES/s (effective, includes per-op setup)
    lat_ms: float = 0.0  # per-op latency floor (wall minus bytes/bw)
    n: int = 0
    last_ts: float = 0.0


class TransferCostModel:
    """One worker's (or one test's) calibration state. Thread-safe: the
    observation sites span the event loop, the device executor and the
    offload executor threads."""

    #: one sample can move an EWMA by at most this factor in either
    #: direction (restart clamp): a worker restarted into a congested
    #: minute — or one absurd timer reading — must not repoint routing
    #: by orders of magnitude before the EWMA has evidence
    SAMPLE_CLAMP = 8.0

    def __init__(
        self,
        block_bytes: int = 0,
        alpha: float = 0.25,
        min_obs: int = 4,
        obs_ttl_s: float = 900.0,
        prefill_seed_tok_s: float = 0.0,
        corr_bounds: tuple[float, float] = (0.25, 4.0),
        clock=None,
    ):
        self.block_bytes = int(block_bytes)
        self.alpha = alpha
        self.min_obs = min_obs
        self.obs_ttl_s = obs_ttl_s
        #: roofline-style seed (tokens/s one prefill replica sustains);
        #: 0 = unseeded, the pure observation EWMA serves instead
        self.prefill_seed_tok_s = float(prefill_seed_tok_s)
        self.corr_bounds = corr_bounds
        self._clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self._links: dict[str, LinkEstimate] = {}
        # observed prefill throughput: EWMA tok/s + a multiplicative
        # correction on the seed (CapacityModel's observed/modeled fold)
        self._prefill = LinkEstimate()
        self._prefill_corr = 1.0
        self.obs_total = 0

    # ---------------- observation (worker side) ----------------

    def _fold(self, est: LinkEstimate, sample: float, now: float) -> None:
        if est.n == 0 or now - est.last_ts > self.obs_ttl_s:
            # first sample, or the estimate went stale (worker idled
            # through a topology change): restart the EWMA rather than
            # averaging across two different worlds
            est.gbps = sample
            est.n = 1 if est.n == 0 else est.n
        else:
            lo = est.gbps / self.SAMPLE_CLAMP
            hi = est.gbps * self.SAMPLE_CLAMP
            s = min(max(sample, lo), hi)
            est.gbps = (1 - self.alpha) * est.gbps + self.alpha * s
        est.last_ts = now

    def observe(self, link: str, nbytes: int, wall_s: float) -> None:
        """Fold one measured transfer (``nbytes`` moved in ``wall_s``)
        into the link class's estimate. Bad samples are dropped, never
        raised — observation sites sit on serving paths."""
        if nbytes <= 0 or wall_s <= 0 or link not in LINK_CLASSES:
            return
        sample_gbps = nbytes / wall_s / 1e9
        now = self._clock()
        with self._lock:
            est = self._links.setdefault(link, LinkEstimate())
            had = est.n
            self._fold(est, sample_gbps, now)
            if had:
                est.n = had + 1
            # latency floor: the part of the wall the bandwidth model
            # doesn't explain (connection setup, executor hop, ack)
            modeled_ms = nbytes / (est.gbps * 1e9) * 1e3
            lat_sample = max(wall_s * 1e3 - modeled_ms, 0.0)
            est.lat_ms = (
                lat_sample if had == 0
                else (1 - self.alpha) * est.lat_ms + self.alpha * lat_sample
            )
            self.obs_total += 1

    def observe_prefill(self, tokens: int, wall_s: float) -> None:
        """Fold one measured prefill chunk (device time) into the
        throughput model — the correction term on the roofline seed."""
        if tokens <= 0 or wall_s <= 0:
            return
        tok_s = tokens / wall_s
        now = self._clock()
        with self._lock:
            est = self._prefill
            had = est.n
            self._fold(est, tok_s, now)
            if had:
                est.n = had + 1
            if self.prefill_seed_tok_s > 0:
                lo, hi = self.corr_bounds
                sample = tok_s / self.prefill_seed_tok_s
                self._prefill_corr = min(
                    hi,
                    max(lo, (1 - self.alpha) * self._prefill_corr
                        + self.alpha * sample),
                )
            self.obs_total += 1

    # ---------------- queries ----------------

    def _fresh(self, est: LinkEstimate) -> bool:
        return (
            est.n > 0
            and (self.obs_ttl_s <= 0
                 or self._clock() - est.last_ts <= self.obs_ttl_s)
        )

    def link_gbps(self, link: str) -> Optional[float]:
        """Observed effective bandwidth for one link class, or None when
        the class was never observed or the last observation aged out
        (``obs_ttl_s`` — a dead link must stop informing routing)."""
        with self._lock:
            est = self._links.get(link)
            if est is None or not self._fresh(est):
                return None
            return est.gbps

    def transfer_ms(self, link: str, nbytes: int) -> Optional[float]:
        with self._lock:
            est = self._links.get(link)
            if est is None or not self._fresh(est) or est.gbps <= 0:
                return None
            return est.lat_ms + nbytes / (est.gbps * 1e9) * 1e3

    def prefill_tok_s(self) -> Optional[float]:
        """Corrected prefill throughput: seed × observed correction when
        roofline-seeded, else the pure observation EWMA (None until the
        first chunk lands)."""
        with self._lock:
            if self.prefill_seed_tok_s > 0:
                return self.prefill_seed_tok_s * self._prefill_corr
            if self._prefill.n == 0 or not self._fresh(self._prefill):
                return None
            return self._prefill.gbps  # tok/s rides the same EWMA slot

    # ---------------- export (load_metrics -> WorkerLoad) ----------------

    def counters(self) -> dict:
        """The worker's advertised calibration: folded into
        ``engine.load_metrics`` and scraped into ``WorkerLoad`` so the
        router prices this worker with its own measurements. Latency
        floors ride alongside the bandwidths — a 1-block restore is
        dominated by per-op setup, not bytes/bw."""
        with self._lock:
            links = {
                name: round(est.gbps, 6)
                for name, est in self._links.items()
                if self._fresh(est)
            }
            lats = {
                name: round(est.lat_ms, 4)
                for name, est in self._links.items()
                if self._fresh(est)
            }
        tok_s = self.prefill_tok_s()
        return {
            "kv_cost_obs_total": self.obs_total,
            "kv_link_gbps": links,
            "kv_link_lat_ms": lats,
            "kv_prefill_tok_s": round(tok_s, 3) if tok_s else 0.0,
        }


# ---------------- router-side scoring ----------------


def tail_adjusted_ttft_ms(
    pred_ms: float, tail_ms: Optional[float]
) -> float:
    """Price a candidate at its measured tail (the autopilot's
    tail-aware routing loop, docs/autopilot.md).

    ``pred_ms`` is the calibration model's prediction — built from
    EWMA *means*, so a bimodal worker (periodic GC, a noisy co-tenant,
    a wedged executor firing every few seconds) averages its stalls
    away and keeps winning the argmin. ``tail_ms`` is the worker's
    windowed measured tail (p99 of queue-wait + prefill over the last
    window, :class:`~dynamo_tpu.autopilot.tails.TailTracker`): what a
    request routed there actually risks paying. The effective score is
    the max of the two — the model's structural terms (transfer legs,
    overlap) still differentiate healthy candidates, but no candidate
    may score better than its own recent tail says it serves. None
    (no window evidence — cold or idle worker) changes nothing."""
    if tail_ms is None:
        return pred_ms
    return max(pred_ms, tail_ms)


def _restore_gbps(link_gbps: dict) -> Optional[float]:
    """Effective local-tier restore bandwidth for a candidate: the
    router can't see how a chain splits between host DRAM and disk, so
    it prices the whole lower-tier run at the SLOWER of the two
    advertised classes — conservative, and exact once the disk tier is
    empty or absent."""
    speeds = [link_gbps[k] for k in ("host", "disk") if link_gbps.get(k)]
    return min(speeds) if speeds else None


def link_leg_ms(
    link_gbps: dict, link_lat_ms: dict, link: str, nbytes: int
) -> Optional[float]:
    """One transfer leg from a candidate's advertised calibration:
    per-op latency floor + bytes over bandwidth. None when the class
    was never observed."""
    g = link_gbps.get(link)
    if not g:
        return None
    return (link_lat_ms or {}).get(link, 0.0) + nbytes / (g * 1e9) * 1e3


def restore_leg_ms(
    link_gbps: dict, link_lat_ms: dict, nbytes: int
) -> Optional[float]:
    """The local-tier restore leg (slower of host/disk, see
    :func:`_restore_gbps`), latency floor included."""
    g = _restore_gbps(link_gbps)
    if g is None:
        return None
    lat = max(
        (link_lat_ms or {}).get(k, 0.0)
        for k in ("host", "disk")
        if link_gbps.get(k)
    )
    return lat + nbytes / (g * 1e9) * 1e3


def predict_worker_ttft_ms(
    load,
    overlaps,
    isl_blocks: int,
    pending: int = 0,
    min_obs: int = 4,
    peer_slice_fp: str = "",
    peer_wire_bytes: int = 0,
) -> Optional[float]:
    """Predicted TTFT (ms) for routing one ``isl_blocks``-block prompt
    to ``load``'s worker, from the candidate's advertised calibration:

        queue_wait = requests that must clear a slot before this one
                     × one modeled prompt prefill
        restore    = (tier-inclusive − device) overlap blocks over the
                     candidate's observed host/disk restore link
        pull       = the continuation a deeper peer holds, over the
                     observed peer link (ICI class when ``peer_slice_fp``
                     matches the candidate's slice), plus its restore
                     leg; an unobserved pull link prices as recompute
        prefill    = remaining blocks at the corrected prefill tok/s

    The sum is scaled by ``1 + busy_slot_fraction`` (co-location
    interference: in-flight work timeshares the chips even before the
    queue term engages — the continuous load-spreading the legacy
    scorer's gamma term provided).

    The pull term is an estimate against the DEEPEST other chain; the
    hint that actually fires may name a different (nearest-adequate)
    peer or none (``KvScheduler.choose_peer``). The divergence is
    bounded and conservative — choose_peer only ever picks a peer whose
    predicted cost beats recompute, and recompute is exactly this
    term's fallback pricing — so the argmin ranks candidates on a
    pessimistic but consistently-scaled view.

    Returns None while the candidate is cold: fewer than ``min_obs``
    observations, block geometry unadvertised, throughput unobserved,
    or a needed restore link never measured — the scheduler falls back
    to overlap scoring for the whole decision (cold-start contract)."""
    if load.cost_obs < min_obs or load.block_bytes <= 0 or load.block_size <= 0:
        return None
    tok_s = load.prefill_tok_s
    if not tok_s or tok_s <= 0:
        return None
    w = load.worker_id
    # restore/pull legs move TIER/WIRE bytes: the quantized per-block
    # size when the worker advertises a --kv-quant codec (half the
    # bytes -> half the predicted leg), the full width otherwise
    bs = load.block_size
    bb = load.wire_bytes_per_block
    isl = max(isl_blocks, 1)
    tier = min(overlaps.scores.get(w, 0), isl)
    dev = min(overlaps.device(w), tier)
    restore = tier - dev
    # deepest chain any OTHER worker holds: the continuation past this
    # candidate's own tiers is pullable over the fleet prefix cache
    peer_depth = max(
        (min(ov, isl) for w2, ov in overlaps.scores.items() if w2 != w),
        default=0,
    )
    peer_extra = max(peer_depth - tier, 0)
    missing = max(isl - tier - peer_extra, 0)

    def prefill_ms(blocks: int) -> float:
        return blocks * bs / tok_s * 1e3

    link_gbps = load.link_gbps or {}
    link_lat = getattr(load, "link_lat_ms", None) or {}
    ms = 0.0
    # queue: how many in-flight/queued requests must finish before a
    # slot frees for this one, each modeled at one prompt's prefill
    ahead = max(
        load.active_requests + load.waiting + pending + 1 - load.total_slots,
        0,
    )
    ms += ahead * prefill_ms(isl)
    if restore > 0:
        leg = restore_leg_ms(link_gbps, link_lat, restore * bb)
        if leg is None:
            return None  # a tiered candidate that never restored is cold
        ms += leg
    if peer_extra > 0:
        link = (
            "ici"
            if peer_slice_fp and load.slice_fp
            and peer_slice_fp == load.slice_fp
            else "peer"
        )
        # the WIRE leg moves bytes at the serving peer's codec width
        # (peers serve their stored form); the landing/restore leg is
        # this candidate's own tier width
        pull = link_leg_ms(
            link_gbps, link_lat,
            link if link_gbps.get(link) else "peer",
            peer_extra * (peer_wire_bytes or bb),
        )
        land = restore_leg_ms(link_gbps, link_lat, peer_extra * bb)
        if pull is not None and land is not None:
            ms += pull + land
        else:
            # never pulled / never restored: price the continuation as
            # recompute — conservative, and exactly what the worker
            # will do if the pull keeps failing
            missing += peer_extra
    ms += prefill_ms(missing)
    # co-location interference: below slot saturation the queue term is
    # zero, but every in-flight/pending request still timeshares the
    # chips our prefill runs on — scale by the busy-slot fraction so a
    # burst of cold prompts spreads across calibrated workers instead
    # of piling onto whichever advertises the highest tok/s (the load
    # spreading the legacy scorer's gamma term provided)
    share = (
        (load.active_requests + load.waiting + pending)
        / max(load.total_slots, 1)
    )
    return ms * (1.0 + share)

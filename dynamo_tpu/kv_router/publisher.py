"""Worker-side KV event publication + router-side metrics aggregation.

Re-design of lib/llm/src/kv_router/{publisher,metrics_aggregator,scoring}.rs:

  * :class:`KvEventPublisher` — hooks the engine's BlockAllocator
    stored/removed callbacks and publishes RouterEvents on the component's
    ``kv_events`` subject,
  * :class:`KvPrefetchListener` — the other direction: consumes the
    router's ``kv-prefetch`` hints addressed to this worker and hands
    the block-hash chain to the engine's host-tier prefetch
    (engine.prefetch_hint), so restores start before requests arrive,
  * :class:`KvMetricsAggregator` — periodically scrapes every worker
    instance's stats endpoint (the engine's ``load_metrics``) into
    :class:`ProcessedEndpoints` for the scheduler.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Optional

from .protocols import (
    KV_EVENT_SUBJECT,
    KV_PREFETCH_SUBJECT,
    KvCacheEvent,
    KvPrefetchHint,
    RouterEvent,
    StoredBlock,
)
from .scheduler import ProcessedEndpoints, WorkerLoad

logger = logging.getLogger(__name__)


class KvEventPublisher:
    """ref publisher.rs:33-73."""

    def __init__(self, drt, component, worker_id: int):
        self.drt = drt
        self.subject = component.event_subject(KV_EVENT_SUBJECT)
        self.worker_id = worker_id
        self._ids = itertools.count(1)

    def publish(self, event: KvCacheEvent) -> None:
        ev = RouterEvent(self.worker_id, event, next(self._ids))
        self.drt.bus.publish(self.subject, ev.to_bytes())

    # -- allocator callback adapters --
    def on_stored(self, block, parent_hash: Optional[int]) -> None:
        self.publish(
            KvCacheEvent.stored(
                parent_hash,
                [StoredBlock(block_hash=block.seq_hash, tokens_hash=block.local_hash)],
            )
        )

    def on_removed(self, block_hashes: list[int]) -> None:
        self.publish(KvCacheEvent.removed(block_hashes))

    def attach(self, allocator) -> None:
        allocator.on_stored = self.on_stored
        allocator.on_removed = self.on_removed


class KvPrefetchListener:
    """Worker-side prefetch-hint consumer: subscribes the component's
    ``kv-prefetch`` subject, filters hints addressed to this worker, and
    drives the engine's router-hinted host-tier prefetch. Hints are
    advisory — any failure is logged and dropped (the request still
    serves correctly, it just pays the cold restore)."""

    def __init__(self, drt, component, worker_id: int, engine):
        self.drt = drt
        self.subject = component.event_subject(KV_PREFETCH_SUBJECT)
        self.worker_id = worker_id
        self.engine = engine
        self.hints_received = 0
        self.blocks_prefetched = 0
        self._task: Optional[asyncio.Task] = None
        self._sub = None

    async def start(self) -> "KvPrefetchListener":
        sub = self.drt.bus.subscribe(self.subject)
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._sub = sub
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()

    async def _consume(self, sub) -> None:
        async for msg in sub:
            try:
                hint = KvPrefetchHint.from_bytes(msg.payload)
                if hint.worker_id != self.worker_id:
                    continue
                self.hints_received += 1
                n = await self.engine.prefetch_hint(
                    [(l, s) for l, s in hint.blocks]
                )
                self.blocks_prefetched += n
            except Exception:  # noqa: BLE001 — hints are advisory
                logger.debug("prefetch hint failed", exc_info=True)


class KvMetricsAggregator:
    """ref metrics_aggregator.rs:27-109 collect_endpoints_task."""

    def __init__(self, drt, component, interval: float = 1.0):
        self.drt = drt
        self.component = component
        self.interval = interval
        self.endpoints = ProcessedEndpoints([])
        # last-known load per instance: a worker that misses one scrape
        # window (1s stats timeout on a starved box) keeps its previous
        # snapshot — with its ORIGINAL ts, so the scheduler's load_ttl_s
        # ages it out if it stays silent — instead of vanishing from the
        # routing view for a tick. Departed workers (discovery key gone)
        # still drop immediately.
        self._known: dict[int, WorkerLoad] = {}
        self._task: Optional[asyncio.Task] = None
        #: completed-scrape signal: counter + waiter futures resolved at
        #: the end of every _collect_once. Lets tests (and watchers)
        #: synchronize on SCRAPES OBSERVED rather than wall time — on a
        #: starved box the scrape loop stretches, and a fixed-duration
        #: poll times out while the aggregator has simply not run yet
        #: (the test_kv_routed_serving flake, CHANGES.md PR 5).
        self.scrapes_total = 0
        self._scrape_waiters: list[asyncio.Future] = []

    async def start(self) -> "KvMetricsAggregator":
        await self._collect_once()
        self._task = self.drt.runtime.spawn(self._loop())
        return self

    async def next_scrape(self, timeout: Optional[float] = None) -> int:
        """Resolve after the NEXT completed scrape (an event, not a
        timer); returns the new ``scrapes_total``. With ``timeout``,
        falls through after that many seconds even if no scrape landed —
        callers decide whether a starved loop is an error."""
        fut = asyncio.get_running_loop().create_future()
        self._scrape_waiters.append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            if fut in self._scrape_waiters:
                self._scrape_waiters.remove(fut)
        return self.scrapes_total

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self._collect_once()
            except Exception:  # noqa: BLE001
                logger.exception("metrics scrape failed")

    async def _collect_once(self) -> None:
        import time as _time

        stats = await self.component.scrape_stats(include_missing=True)
        now = _time.monotonic()
        merged: dict[int, WorkerLoad] = {}
        for s in stats:
            d = s.get("data")
            if d is None:
                # discovered but slow: retain the last-known load (stale
                # ts and all) rather than dropping a live worker
                prev = self._known.get(s["instance_id"])
                if prev is not None:
                    merged[s["instance_id"]] = prev
                continue
            merged[s["instance_id"]] = (
                WorkerLoad(
                    worker_id=s["instance_id"],
                    kv_active_blocks=d.get("kv_active_blocks", 0),
                    kv_total_blocks=max(d.get("kv_total_blocks", 1), 1),
                    active_requests=d.get("request_active_slots", 0),
                    total_slots=max(d.get("request_total_slots", 1), 1),
                    waiting=d.get("num_requests_waiting", 0),
                    offload_blocks_resident=d.get(
                        "offload_blocks_resident", 0),
                    offload_d2h_flush_async=d.get("d2h_flush_async", 0),
                    offload_prefetch_hits=d.get("h2d_prefetch_hits", 0),
                    offload_restore_hidden_frac=d.get(
                        "restore_latency_hidden_frac", 0.0),
                    draining=d.get("draining", 0),
                    drains_total=d.get("drains_total", 0),
                    migration_resumes=d.get("migration_resumes", 0),
                    kv_stream_deliveries=d.get("streamed_deliveries", 0),
                    kv_bulk_deliveries=d.get("bulk_deliveries", 0),
                    kv_stream_segments=d.get("kv_stream_segments", 0),
                    mixed_steps=d.get("mixed_steps", 0),
                    mixed_prefill_segments=d.get("mixed_prefill_segments", 0),
                    requests_total=d.get("requests_total", 0),
                    tokens_generated=d.get("tokens_generated", 0),
                    prompt_tokens_total=d.get("prompt_tokens_total", 0),
                    loop_stalls=d.get("san_loop_stalls", 0),
                    loop_stall_max_ms=d.get("san_loop_stall_max_ms", 0.0),
                    lock_hold_max_ms=d.get("san_lock_hold_max_ms", 0.0),
                    writers_leaked=d.get("san_writers_leaked", 0),
                    # stamped at scrape time: the scheduler ages these
                    # out (load_ttl_s) instead of trusting a dead
                    # worker's last report forever
                    ts=now,
                )
            )
        self._known = merged
        self.endpoints = ProcessedEndpoints(list(merged.values()))
        self.scrapes_total += 1
        waiters, self._scrape_waiters = self._scrape_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(self.scrapes_total)

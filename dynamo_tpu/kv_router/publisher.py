"""Worker-side KV event publication + router-side metrics aggregation.

Re-design of lib/llm/src/kv_router/{publisher,metrics_aggregator,scoring}.rs:

  * :class:`KvEventPublisher` — hooks the engine's BlockAllocator
    stored/removed/demoted callbacks (and the offload tier's last-tier
    drop queue) and publishes RouterEvents on the component's
    ``kv_events`` subject,
  * :class:`KvPrefetchListener` — the other direction: consumes the
    router's ``kv-prefetch`` hints addressed to this worker, pulls
    peer-held prefix continuations over the transfer plane when the
    hint names a deeper peer (fleet prefix cache), and hands the
    block-hash chain to the engine's host-tier prefetch
    (engine.prefetch_hint), so restores start before requests arrive,
  * :class:`KvPeerServer` — the serve side of those pulls: answers
    ``kv-peer-fetch`` requests addressed to this worker by pushing the
    chain's host/disk-resident blocks to the requester's connect-back
    address (disagg/transfer.py framing + ack),
  * :class:`KvMetricsAggregator` — periodically scrapes every worker
    instance's stats endpoint (the engine's ``load_metrics``) into
    :class:`ProcessedEndpoints` for the scheduler.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import uuid
from typing import Optional

import numpy as np

from .protocols import (
    KV_EVENT_SUBJECT,
    KV_PEER_FETCH_SUBJECT,
    KV_PREFETCH_SUBJECT,
    KvCacheEvent,
    KvPeerFetchRequest,
    KvPrefetchHint,
    RouterEvent,
    StoredBlock,
)
from .scheduler import ProcessedEndpoints, WorkerLoad

logger = logging.getLogger(__name__)

#: wall bound on one peer prefix pull (bus negotiation + TCP push):
#: past this the hinted request is probably already being served, so
#: the puller abandons the delivery and lets admission recompute
PEER_PULL_TIMEOUT_S = 20.0


class KvEventPublisher:
    """ref publisher.rs:33-73."""

    def __init__(self, drt, component, worker_id: int):
        self.drt = drt
        self.subject = component.event_subject(KV_EVENT_SUBJECT)
        self.worker_id = worker_id
        self._ids = itertools.count(1)

    def publish(self, event: KvCacheEvent) -> None:
        ev = RouterEvent(self.worker_id, event, next(self._ids))
        self.drt.bus.publish(self.subject, ev.to_bytes())

    # -- allocator callback adapters --
    def on_stored(self, block, parent_hash: Optional[int]) -> None:
        self.publish(
            KvCacheEvent.stored(
                parent_hash,
                [StoredBlock(block_hash=block.seq_hash, tokens_hash=block.local_hash)],
            )
        )

    def on_removed(self, block_hashes: list[int]) -> None:
        self.publish(KvCacheEvent.removed(block_hashes))

    def on_demoted(self, block_hashes: list[int]) -> None:
        self.publish(KvCacheEvent.demoted(block_hashes))

    def attach(self, allocator, offload=None) -> None:
        """Wire the allocator's events; with an ``offload`` manager the
        residency story becomes tiered: device evictions publish
        ``demoted`` (the worker still holds the KV, one tier down —
        the router keeps the radix entry, which is what lets peers pull
        it), and the true ``removed`` fires from the offload manager's
        last-tier drop queue (OffloadManager.flush_dropped)."""
        allocator.on_stored = self.on_stored
        allocator.on_removed = self.on_removed
        if offload is not None:
            allocator.on_demoted = self.on_demoted
            offload.on_dropped = self.on_removed


class KvPrefetchListener:
    """Worker-side prefetch-hint consumer: subscribes the component's
    ``kv-prefetch`` subject, filters hints addressed to this worker, and
    drives the engine's router-hinted host-tier prefetch. Hints are
    advisory — any failure is logged and dropped (the request still
    serves correctly, it just pays the cold restore).

    Fleet prefix cache: a hint naming a ``peer_worker_id`` whose chain
    runs deeper than this worker's local coverage triggers a peer pull
    first — a ``kv-peer-fetch`` negotiation on the bus answered by the
    peer pushing the blocks to this listener's transfer server, landed
    in the HOST tier, then promoted to device by the very same
    ``engine.prefetch_hint`` restore that serves locally-offloaded
    chains. Every failure mode (peer dead, timeout, partial serve,
    miss) degrades to exactly what would have happened without the
    peer: recompute."""

    def __init__(self, drt, component, worker_id: int, engine,
                 transfer=None, peer_pull: bool = True,
                 pull_timeout: float = PEER_PULL_TIMEOUT_S):
        self.drt = drt
        self.subject = component.event_subject(KV_PREFETCH_SUBJECT)
        self.fetch_subject = component.event_subject(KV_PEER_FETCH_SUBJECT)
        self.worker_id = worker_id
        self.engine = engine
        self.hints_received = 0
        self.blocks_prefetched = 0
        self.peer_pulls = 0
        self.peer_pull_blocks = 0
        self.peer_pull_failures = 0
        # PRESERVE-style weight pre-stage (hint.model): requests
        # forwarded to the engine hook, and failures swallowed there —
        # a broken pre-stage must never cost the KV prefetch
        self.prestage_requests = 0
        self.prestage_failures = 0
        self.pull_timeout = pull_timeout
        self.peer_pull = peer_pull
        # connect-back target for peer pushes: the disagg decode role
        # shares its existing KvTransferServer; otherwise the listener
        # owns a lightweight one, started lazily with it
        self._transfer = transfer
        self._own_transfer = False
        self._task: Optional[asyncio.Task] = None
        self._sub = None
        # one task per hint: a dead peer's pull waits out its timeout
        # WITHOUT head-of-line blocking every later hint's restore (the
        # same hazard KvPeerServer spawns per serve for). Pulls beyond
        # the cap skip the peer and go straight to the local restore;
        # the restores themselves serialize (one h2d pipe, and the
        # engine's prefetch path was written for one caller at a time)
        self._hint_tasks: set[asyncio.Task] = set()
        self._restore_lock = asyncio.Lock()
        self._active_pulls = 0
        self.max_concurrent_pulls = 8

    def _pull_ready(self) -> bool:
        off = getattr(self.engine, "offload", None)
        return (
            self.peer_pull
            and self._transfer is not None
            and off is not None
            and off.mirror is None
        )

    async def start(self) -> "KvPrefetchListener":
        off = getattr(self.engine, "offload", None)
        if (
            self.peer_pull
            and self._transfer is None
            and off is not None
            and off.mirror is None  # same gate as _pull_ready: a mirror
            # engine never pulls, so don't bind a dead connect-back
            # socket + server task per mirror worker
        ):
            from ..disagg.transfer import KvTransferServer

            self._transfer = KvTransferServer()
            await self._transfer.start()
            self._own_transfer = True
        sub = self.drt.bus.subscribe(self.subject)
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._sub = sub
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()
        for t in list(self._hint_tasks):
            t.cancel()
        if self._own_transfer and self._transfer is not None:
            await self._transfer.close()

    async def _consume(self, sub) -> None:
        async for msg in sub:
            try:
                hint = KvPrefetchHint.from_bytes(msg.payload)
                if hint.worker_id != self.worker_id:
                    continue
                self.hints_received += 1
                t = asyncio.get_running_loop().create_task(
                    self._handle_hint(hint)
                )
                self._hint_tasks.add(t)
                t.add_done_callback(self._hint_tasks.discard)
            except Exception:  # noqa: BLE001 — hints are advisory
                logger.debug("prefetch hint failed", exc_info=True)

    async def _handle_hint(self, hint: KvPrefetchHint) -> None:
        try:
            if hint.model:
                # fire-and-forget, never awaited inline: a SLOW weight
                # pre-stage (the whole point once multi-model staging is
                # real) must not delay the prefix restore it rides with,
                # and a failing/fault-killed one is swallowed inside
                # _pre_stage — either way the KV work below is unaffected
                t = asyncio.get_running_loop().create_task(
                    self._pre_stage(hint.model)
                )
                self._hint_tasks.add(t)
                t.add_done_callback(self._hint_tasks.discard)
            blocks = [(l, s) for l, s in hint.blocks]
            if (
                hint.peer_worker_id is not None
                and self._pull_ready()
                # gate on PULLS in flight, not hint tasks — peer-less
                # hints and tasks merely queued on the restore lock must
                # not lock later hints out of their pulls
                and self._active_pulls < self.max_concurrent_pulls
            ):
                self._active_pulls += 1
                try:
                    await self._maybe_pull(hint, blocks)
                finally:
                    self._active_pulls -= 1
            async with self._restore_lock:
                n = await self.engine.prefetch_hint(blocks)
            self.blocks_prefetched += n
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — hints are advisory
            logger.debug("prefetch hint failed", exc_info=True)

    async def _pre_stage(self, model: str) -> None:
        """PRESERVE-style weight pre-stage: the hint named the model the
        routed request will run, so staging its weights can start before
        the request arrives — resolved through the engine's
        ``pre_stage_weights`` hook, which stages the adapter's A/B
        stacks into a device slot (engine/adapters.py) so the request
        lands on a warm adapter instead of paying the cold-load stall
        inline. Best-effort end to end, with its own faultpoint so
        tests can prove a dead pre-stage never takes the KV prefetch
        down with it."""
        from ..resilience import faultpoints

        self.prestage_requests += 1
        try:
            await faultpoints.hit("pre_stage_weights", model=model)
            fn = getattr(self.engine, "pre_stage_weights", None)
            if fn is not None:
                await fn(model)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — advisory, like the hint
            self.prestage_failures += 1
            logger.debug("weight pre-stage for %r failed", model,
                         exc_info=True)

    async def _maybe_pull(self, hint: KvPrefetchHint, blocks: list) -> None:
        """One peer prefix pull: size the remote tail from local
        coverage, negotiate over the bus, await the transfer-plane
        delivery, and land it in the host tier. Best-effort throughout."""
        chain = [s for _l, s in blocks]
        cov = self.engine.chain_coverage(chain)
        if cov >= min(hint.peer_blocks, len(chain)):
            return  # local tiers already cover what the peer offers
        tail = chain[cov:]
        request_id = f"peer-pull-{uuid.uuid4().hex}"
        fut = self._transfer.expect(request_id)
        from ..disagg.transfer import KV_QUANT_WIRE_VERSION

        req = KvPeerFetchRequest(
            peer_worker_id=hint.peer_worker_id,
            src_worker_id=self.worker_id,
            request_id=request_id,
            hashes=tail,
            connection=self._transfer.address.to_dict(),
            # this puller dequantizes (or re-quantizes to its own mode)
            # on landing, so it always accepts the quantized wire shape
            accept_quant=KV_QUANT_WIRE_VERSION,
        )
        self.peer_pulls += 1
        import time as _time

        t0 = _time.monotonic()
        try:
            self.drt.bus.publish(self.fetch_subject, req.to_bytes())
            delivery = await asyncio.wait_for(fut, self.pull_timeout)
        except Exception:  # noqa: BLE001 — dead peer / timeout / bus
            # trouble: the request recomputes, exactly as if the peer
            # never existed. The pending future is abandoned so a
            # stale late push can't land into a recycled request id.
            self.peer_pull_failures += 1
            self._transfer.abandon(request_id)
            logger.debug("peer pull %s failed; falling back to recompute",
                         request_id, exc_info=True)
            return
        if delivery.error or not delivery.hashes or delivery.k_data is None:
            self.peer_pull_failures += 1
            return
        # transfer-cost calibration: the pull's measured wall + bytes
        # feed the engine's "peer" link-class estimate — this is the
        # number the router prices this worker's future pulls with
        cost = getattr(self.engine, "cost", None)
        if cost is not None and delivery.k_data is not None:
            cost.observe(
                "peer",
                delivery.k_data.nbytes + delivery.v_data.nbytes,
                max(_time.monotonic() - t0, 1e-9),
            )
        served = [int(h) for h in delivery.hashes]
        if served != tail[: len(served)]:
            # a peer whose probe drifted from the request must not park
            # mislabeled KV in the content-addressed pool
            self.peer_pull_failures += 1
            logger.warning("peer pull %s returned a mismatched chain",
                           request_id)
            return
        # regroup (a whole-stack head-axis permutation copy) AND the
        # per-block landing copies are multi-MB host work: one executor
        # hop for both — neither belongs on the serving loop
        try:
            n = await asyncio.get_running_loop().run_in_executor(
                None, self._regroup_and_land, delivery, served
            )
        except Exception:  # noqa: BLE001 — bad peer metadata
            self.peer_pull_failures += 1
            logger.warning("peer pull %s regroup/landing failed", request_id,
                           exc_info=True)
            return
        self.peer_pull_blocks += n

    def _regroup_and_land(self, delivery, served: list) -> int:
        """Executor thread: permute a foreign kv-head ordering (same
        shared rule as the disagg delivery paths — ops/kv_rearrange.
        layout_mismatched) and park the chain in the host staging
        area. A quantized delivery regroups as-is (the codec's scales
        are kv-head-free) and lands with its scale arrays — the
        landing normalizes it to THIS worker's codec mode."""
        from ..ops.kv_rearrange import layout_mismatched, rearrange_for_decode

        k, v = delivery.k_data, delivery.v_data
        my_layout = self.engine.cfg.kv_head_layout
        my_tp = self.engine.cfg.mesh.tp if self.engine.cfg.mesh else 1
        if layout_mismatched(
            delivery.head_layout, delivery.src_tp, my_layout, my_tp
        ):
            k = rearrange_for_decode(
                k, delivery.src_tp, my_tp, delivery.head_layout, my_layout
            )
            v = rearrange_for_decode(
                v, delivery.src_tp, my_tp, delivery.head_layout, my_layout
            )
        return self.engine.offload.land_peer_chain(
            served, k, v,
            k_scales=delivery.k_scales, v_scales=delivery.v_scales,
        )


class KvPeerServer:
    """Serve side of the fleet prefix cache: consumes ``kv-peer-fetch``
    requests addressed to this worker and answers each by pushing the
    requested chain's host/disk-resident blocks to the requester's
    transfer server — the same bulk framing, layer-chunked frames and
    end-to-end ack as the disagg KV handoff (disagg/transfer.py). A
    total miss answers with an error delivery so the requester falls
    back immediately instead of waiting out its pull timeout. Serving
    is non-destructive (export reads, never takes), so a requester
    dying mid-pull leaves this worker's tiers untouched."""

    def __init__(self, drt, component, worker_id: int, engine,
                 layer_chunk: int = 4):
        self.drt = drt
        self.subject = component.event_subject(KV_PEER_FETCH_SUBJECT)
        self.worker_id = worker_id
        self.engine = engine
        self.layer_chunk = layer_chunk
        self.fetches_received = 0
        self.blocks_served = 0
        self.misses = 0
        self.serve_errors = 0
        self.serve_rejects = 0
        self._task: Optional[asyncio.Task] = None
        self._sub = None
        self._serves: set[asyncio.Task] = set()
        # a hint storm naming this worker for a hot shared prefix must
        # not stack unbounded concurrent exports (each one np.stacks a
        # multi-MB..GB KV run on the executor) — the puller side caps
        # its fan-out the same way (max_concurrent_pulls)
        self.max_concurrent_serves = 8
        # per-fetch bound on the DEVICE-tier d2h export: a serve must
        # never turn into an unbounded HBM drain under the device lock
        # (the concurrency cap above bounds the fan-out; this bounds
        # each serve's burst)
        self.max_d2h_blocks = 128

    async def start(self) -> "KvPeerServer":
        sub = self.drt.bus.subscribe(self.subject)
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._sub = sub
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()
        for t in list(self._serves):
            t.cancel()

    async def _consume(self, sub) -> None:
        async for msg in sub:
            try:
                req = KvPeerFetchRequest.from_bytes(msg.payload)
                if req.peer_worker_id != self.worker_id:
                    continue
                self.fetches_received += 1
                if len(self._serves) >= self.max_concurrent_serves:
                    # over the export cap: answer busy so the puller
                    # falls back to recompute NOW instead of waiting
                    # out its pull timeout
                    self.serve_rejects += 1
                    t = asyncio.get_running_loop().create_task(
                        self._reject(req)
                    )
                else:
                    # one task per serve: a slow requester link must not
                    # head-of-line block other peers' pulls
                    t = asyncio.get_running_loop().create_task(
                        self._serve(req)
                    )
                self._serves.add(t)
                t.add_done_callback(self._serves.discard)
            except Exception:  # noqa: BLE001 — fetches are advisory
                logger.debug("bad kv-peer-fetch request", exc_info=True)

    async def _reject(self, req: KvPeerFetchRequest) -> None:
        from ..disagg.transfer import send_kv_blocks

        try:
            await send_kv_blocks(
                req.connection, req.request_id, -1, None, None,
                error="peer-busy",
            )
        except Exception:  # noqa: BLE001 — the puller's timeout covers us
            logger.debug("peer-busy notify %s failed", req.request_id,
                         exc_info=True)

    async def _serve(self, req: KvPeerFetchRequest) -> None:
        from ..disagg.transfer import send_kv_blocks
        from ..resilience import faultpoints

        try:
            # deterministic worker-death injection for the mid-pull
            # crash tests: a kill here is a peer dying before (or
            # instead of) the push — no data, no ack, the puller's
            # timeout degrades it to recompute
            await faultpoints.hit("mid_peer_serve", request_id=req.request_id)
            off = getattr(self.engine, "offload", None)
            hashes, k, v = ([], None, None)
            ks = vs = None
            # serve at the stored codec's width only when the puller
            # advertised the capability (tolerant default 0 = legacy
            # puller = full-width bytes; the negotiation matrix of
            # docs/kv_offload.md). Without a host tier the DEVICE
            # cache's own codec (int8-with-scales) is the stored codec.
            dev_q = (
                "int8"
                if getattr(self.engine, "k_scales", None) is not None
                else "none"
            )
            serve_q = (
                (off.kv_quant if off is not None else dev_q)
                if req.accept_quant >= 1
                else "none"
            )
            # device tier first: chains living ONLY in HBM used to be
            # invisible to the fleet prefix cache — a bounded,
            # non-destructive d2h export (engine device lock + executor
            # hop) serves the hottest tier too; the host/disk export
            # continues the run past the device-resident prefix
            export_dev = getattr(self.engine, "export_device_chain", None)
            dks = dvs = None
            if export_dev is not None:
                hashes, k, v, dks, dvs = await export_dev(
                    req.hashes, max_blocks=self.max_d2h_blocks
                )
            if off is not None:

                def _export_and_merge(k=k, v=v, dks=dks, dvs=dvs,
                                      hashes=tuple(hashes)):
                    # executor thread: the lower-tier export, the
                    # device run's wire quantize, and the multi-MB
                    # merge all stay off the event loop
                    from ..engine import kvquant as _kvq

                    tail = req.hashes[len(hashes):]
                    ks = vs = None
                    if dks is not None and hashes:
                        # int8 DEVICE-codec export: ship verbatim when
                        # the negotiated wire codec matches; otherwise
                        # re-encode (the counted bounce — what used to
                        # happen silently on every device serve)
                        if serve_q == "int8":
                            ks, vs = dks, dvs
                        else:
                            k, v = _kvq.dequantize_stack(
                                k, v, dks, dvs, self.engine.cfg.model.dtype
                            )
                            self.engine.note_export_requant(len(hashes))
                            if serve_q != "none":
                                k, v, ks, vs = _kvq.quantize_stack(
                                    k, v, serve_q
                                )
                    elif serve_q != "none" and hashes:
                        k, v, ks, vs = _kvq.quantize_stack(k, v, serve_q)
                    h2, k2, v2, ks2, vs2 = off.export_chain_q(
                        list(tail), quant_ok=serve_q != "none"
                    )
                    if not h2:
                        return list(hashes), k, v, ks, vs
                    if hashes:
                        k = np.concatenate([k, k2], axis=2)
                        v = np.concatenate([v, v2], axis=2)
                        if ks2 is not None:
                            ks = np.concatenate([ks, ks2], axis=1)
                            vs = np.concatenate([vs, vs2], axis=1)
                        return list(hashes) + h2, k, v, ks, vs
                    return h2, k2, v2, ks2, vs2

                hashes, k, v, ks, vs = (
                    await asyncio.get_running_loop().run_in_executor(
                        None, _export_and_merge
                    )
                )
            elif dks is not None and hashes:
                # no host tier: the device-codec export ships verbatim
                # to a quant-capable puller, or dequantizes (counted)
                # for a legacy one
                if serve_q == "int8":
                    ks, vs = dks, dvs
                else:

                    def _dequant(k=k, v=v):
                        from ..engine import kvquant as _kvq

                        self.engine.note_export_requant(len(hashes))
                        return _kvq.dequantize_stack(
                            k, v, dks, dvs, self.engine.cfg.model.dtype
                        )

                    k, v = await asyncio.get_running_loop().run_in_executor(
                        None, _dequant
                    )
            if not hashes:
                self.misses += 1
                await send_kv_blocks(
                    req.connection, req.request_id, -1, None, None,
                    error="peer-miss",
                )
                return
            await send_kv_blocks(
                req.connection, req.request_id, -1, k, v,
                layer_chunk=self.layer_chunk,
                head_layout=self.engine.cfg.kv_head_layout,
                src_tp=self.engine.cfg.mesh.tp if self.engine.cfg.mesh else 1,
                hashes=hashes,
                kv_quant=serve_q if ks is not None else "none",
                k_scales=ks, v_scales=vs,
            )
            self.blocks_served += len(hashes)
        except Exception:  # noqa: BLE001 — serving is best-effort: the
            # puller's timeout covers us, and a FaultInjected kill must
            # look exactly like a crashed peer (no ack, no retry)
            self.serve_errors += 1
            logger.debug("peer serve %s for worker %x failed",
                         req.request_id, req.src_worker_id, exc_info=True)


class KvMetricsAggregator:
    """ref metrics_aggregator.rs:27-109 collect_endpoints_task."""

    def __init__(self, drt, component, interval: float = 1.0):
        self.drt = drt
        self.component = component
        self.interval = interval
        self.endpoints = ProcessedEndpoints([])
        # last-known load per instance: a worker that misses one scrape
        # window (1s stats timeout on a starved box) keeps its previous
        # snapshot — with its ORIGINAL ts, so the scheduler's load_ttl_s
        # ages it out if it stays silent — instead of vanishing from the
        # routing view for a tick. Departed workers (discovery key gone)
        # still drop immediately.
        self._known: dict[int, WorkerLoad] = {}
        self._task: Optional[asyncio.Task] = None
        #: completed-scrape signal: counter + waiter futures resolved at
        #: the end of every _collect_once. Lets tests (and watchers)
        #: synchronize on SCRAPES OBSERVED rather than wall time — on a
        #: starved box the scrape loop stretches, and a fixed-duration
        #: poll times out while the aggregator has simply not run yet
        #: (the test_kv_routed_serving flake, CHANGES.md PR 5).
        self.scrapes_total = 0
        self._scrape_waiters: list[asyncio.Future] = []

    async def start(self) -> "KvMetricsAggregator":
        await self._collect_once()
        self._task = self.drt.runtime.spawn(self._loop())
        return self

    async def next_scrape(self, timeout: Optional[float] = None) -> int:
        """Resolve after the NEXT completed scrape (an event, not a
        timer); returns the new ``scrapes_total``. With ``timeout``,
        falls through after that many seconds even if no scrape landed —
        callers decide whether a starved loop is an error."""
        fut = asyncio.get_running_loop().create_future()
        self._scrape_waiters.append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            if fut in self._scrape_waiters:
                self._scrape_waiters.remove(fut)
        return self.scrapes_total

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self._collect_once()
            except Exception:  # noqa: BLE001
                logger.exception("metrics scrape failed")

    async def _collect_once(self) -> None:
        import time as _time

        stats = await self.component.scrape_stats(include_missing=True)
        now = _time.monotonic()
        merged: dict[int, WorkerLoad] = {}
        for s in stats:
            d = s.get("data")
            if d is None:
                # discovered but slow: retain the last-known load (stale
                # ts and all) rather than dropping a live worker
                prev = self._known.get(s["instance_id"])
                if prev is not None:
                    merged[s["instance_id"]] = prev
                continue
            # ts stamped at scrape time: the scheduler ages these out
            # (load_ttl_s) instead of trusting a dead worker's last
            # report forever
            merged[s["instance_id"]] = WorkerLoad.from_stats(
                s["instance_id"], d, ts=now
            )
        self._known = merged
        self.endpoints = ProcessedEndpoints(list(merged.values()))
        self.scrapes_total += 1
        waiters, self._scrape_waiters = self._scrape_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(self.scrapes_total)

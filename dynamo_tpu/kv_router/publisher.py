"""Worker-side KV event publication + router-side metrics aggregation.

Re-design of lib/llm/src/kv_router/{publisher,metrics_aggregator,scoring}.rs:

  * :class:`KvEventPublisher` — hooks the engine's BlockAllocator
    stored/removed callbacks and publishes RouterEvents on the component's
    ``kv_events`` subject,
  * :class:`KvMetricsAggregator` — periodically scrapes every worker
    instance's stats endpoint (the engine's ``load_metrics``) into
    :class:`ProcessedEndpoints` for the scheduler.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Optional

from .protocols import KV_EVENT_SUBJECT, KvCacheEvent, RouterEvent, StoredBlock
from .scheduler import ProcessedEndpoints, WorkerLoad

logger = logging.getLogger(__name__)


class KvEventPublisher:
    """ref publisher.rs:33-73."""

    def __init__(self, drt, component, worker_id: int):
        self.drt = drt
        self.subject = component.event_subject(KV_EVENT_SUBJECT)
        self.worker_id = worker_id
        self._ids = itertools.count(1)

    def publish(self, event: KvCacheEvent) -> None:
        ev = RouterEvent(self.worker_id, event, next(self._ids))
        self.drt.bus.publish(self.subject, ev.to_bytes())

    # -- allocator callback adapters --
    def on_stored(self, block, parent_hash: Optional[int]) -> None:
        self.publish(
            KvCacheEvent.stored(
                parent_hash,
                [StoredBlock(block_hash=block.seq_hash, tokens_hash=block.local_hash)],
            )
        )

    def on_removed(self, block_hashes: list[int]) -> None:
        self.publish(KvCacheEvent.removed(block_hashes))

    def attach(self, allocator) -> None:
        allocator.on_stored = self.on_stored
        allocator.on_removed = self.on_removed


class KvMetricsAggregator:
    """ref metrics_aggregator.rs:27-109 collect_endpoints_task."""

    def __init__(self, drt, component, interval: float = 1.0):
        self.drt = drt
        self.component = component
        self.interval = interval
        self.endpoints = ProcessedEndpoints([])
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "KvMetricsAggregator":
        await self._collect_once()
        self._task = self.drt.runtime.spawn(self._loop())
        return self

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self._collect_once()
            except Exception:  # noqa: BLE001
                logger.exception("metrics scrape failed")

    async def _collect_once(self) -> None:
        stats = await self.component.scrape_stats()
        loads = []
        for s in stats:
            d = s.get("data") or {}
            loads.append(
                WorkerLoad(
                    worker_id=s["instance_id"],
                    kv_active_blocks=d.get("kv_active_blocks", 0),
                    kv_total_blocks=max(d.get("kv_total_blocks", 1), 1),
                    active_requests=d.get("request_active_slots", 0),
                    total_slots=max(d.get("request_total_slots", 1), 1),
                    waiting=d.get("num_requests_waiting", 0),
                )
            )
        self.endpoints = ProcessedEndpoints(loads)

"""KV-cache-aware request routing (re-design of lib/llm/src/kv_router).

Workers publish block stored/removed events; the router maintains a global
prefix index (chained block hashes -> worker sets), scores each request's
cache overlap per worker, combines it with scraped load metrics, and
routes to the best worker. This is the capability behind the reference's
"3x TTFT" headline (BASELINE.md).
"""

from .indexer import KvIndexer, OverlapScores, PrefixIndex
from .protocols import KvCacheEvent, KvPeerFetchRequest, KvPrefetchHint, RouterEvent
from .publisher import (
    KvEventPublisher,
    KvMetricsAggregator,
    KvPeerServer,
    KvPrefetchListener,
)
from .router import KvRouter
from .scheduler import KvScheduler, ProcessedEndpoints, WorkerLoad

__all__ = [
    "KvCacheEvent",
    "KvEventPublisher",
    "KvIndexer",
    "KvMetricsAggregator",
    "KvPeerFetchRequest",
    "KvPeerServer",
    "KvPrefetchHint",
    "KvPrefetchListener",
    "KvRouter",
    "KvScheduler",
    "OverlapScores",
    "PrefixIndex",
    "ProcessedEndpoints",
    "RouterEvent",
    "WorkerLoad",
]

"""Global prefix index: chained block hash -> worker residency.

Re-design of the reference's RadixTree indexer (lib/llm/src/kv_router/
indexer.rs:87-677). Because block hashes are *chained* (hash includes the
whole prefix), the radix structure is implicit: looking up a sequence's
k-th chained hash is an O(1) dict probe, and a match at depth k implies
matches at all shallower depths. The index therefore stores a flat
``hash -> node`` map with parent/child links kept only for subtree
removal and per-worker cleanup — same behavior as the reference's tree,
one less traversal.

``KvIndexer`` wraps the structure in a single consumer task fed from the
bus (ref indexer.rs:499 mpsc pattern) so appliers never contend with
lookups; ``ShardedPrefixIndex`` hash-partitions across shards for
parallelism (ref KvIndexerSharded, indexer.rs:677).
"""

from __future__ import annotations

import asyncio
import logging
from collections import OrderedDict, defaultdict
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .protocols import KV_EVENT_SUBJECT, RouterEvent

logger = logging.getLogger(__name__)


@dataclass
class OverlapScores:
    """worker_id -> number of consecutive prefix blocks resident
    (ref indexer.rs:239 OverlapScores).

    ``scores`` is tier-inclusive: a worker that demoted part of the
    chain to its host/disk tiers still counts (restore beats recompute
    — that residency is the fleet prefix cache). ``device_scores``
    holds the shallower device-only depth for workers known to have
    demoted inside their match; :meth:`device` falls back to the
    tier-inclusive score for everyone else."""

    scores: dict[int, int] = field(default_factory=dict)
    total_blocks: int = 0
    device_scores: dict[int, int] = field(default_factory=dict)

    def best(self) -> tuple[Optional[int], int]:
        if not self.scores:
            return None, 0
        wid = max(self.scores, key=lambda w: self.scores[w])
        return wid, self.scores[wid]

    def device(self, worker_id: int) -> int:
        return self.device_scores.get(
            worker_id, self.scores.get(worker_id, 0)
        )


@dataclass
class _Node:
    block_hash: int
    parent_hash: Optional[int]
    workers: set[int] = field(default_factory=set)
    children: set[int] = field(default_factory=set)


class PrefixIndex:
    def __init__(self):
        self._nodes: dict[int, _Node] = {}
        self._by_worker: dict[int, set[int]] = defaultdict(set)

    # ---- queries ----
    def find_matches(self, block_hashes: Iterable[int]) -> OverlapScores:
        """Walk the chained hashes in order; per worker, count how deep its
        residency extends (consecutive from the start)."""
        scores = OverlapScores()
        active: Optional[set[int]] = None
        n = 0
        for h in block_hashes:
            n += 1
            node = self._nodes.get(h)
            if node is None:
                break
            workers = node.workers if active is None else (node.workers & active)
            if not workers:
                break
            for w in workers:
                scores.scores[w] = scores.scores.get(w, 0) + 1
            active = set(workers)
        scores.total_blocks = n
        return scores

    @property
    def size(self) -> int:
        return len(self._nodes)

    def workers(self) -> list[int]:
        return sorted(self._by_worker)

    # ---- mutation ----
    def apply_event(self, ev: RouterEvent) -> None:
        kv = ev.event
        if kv.kind == "stored":
            parent = kv.parent_hash
            for blk in kv.blocks:
                node = self._nodes.get(blk.block_hash)
                if node is None:
                    node = self._nodes[blk.block_hash] = _Node(
                        blk.block_hash, parent
                    )
                    if parent is not None and parent in self._nodes:
                        self._nodes[parent].children.add(blk.block_hash)
                node.workers.add(ev.worker_id)
                self._by_worker[ev.worker_id].add(blk.block_hash)
                parent = blk.block_hash
        elif kv.kind == "removed":
            for h in kv.block_hashes:
                self._remove_worker_block(ev.worker_id, h)

    def _remove_worker_block(self, worker_id: int, block_hash: int) -> None:
        # iterative (explicit stack): chains reach thousands of blocks at
        # long context, far past Python's recursion limit
        stack = [block_hash]
        while stack:
            node = self._nodes.get(stack.pop())
            if node is None:
                continue
            node.workers.discard(worker_id)
            self._by_worker[worker_id].discard(node.block_hash)
            # a removed parent means the worker also dropped descendants it held
            for child in node.children:
                cnode = self._nodes.get(child)
                if cnode and worker_id in cnode.workers:
                    stack.append(child)
            if not node.workers:
                self._drop_node(node)

    def _drop_node(self, node: _Node) -> None:
        """Unlink a node and drop its whole subtree (descendants are
        unreachable in a prefix walk once the chain is broken)."""
        if node.parent_hash is not None:
            parent = self._nodes.get(node.parent_hash)
            if parent:
                parent.children.discard(node.block_hash)
        stack = [node]
        while stack:
            n = stack.pop()
            for child in n.children:
                cnode = self._nodes.get(child)
                if cnode is not None:
                    stack.append(cnode)
            for w in n.workers:  # keep per-worker bookkeeping in sync
                self._by_worker[w].discard(n.block_hash)
            self._nodes.pop(n.block_hash, None)

    def remove_worker(self, worker_id: int) -> None:
        """Full cleanup when a worker dies (ref indexer.rs:380)."""
        for h in list(self._by_worker.get(worker_id, ())):
            node = self._nodes.get(h)
            if node is None:
                continue
            node.workers.discard(worker_id)
            if not node.workers:
                # children sharing only this worker die via their own
                # by_worker entries; just unlink this node
                self._drop_node(node)
        self._by_worker.pop(worker_id, None)


class ShardedPrefixIndex:
    """Hash-partitioned by worker id: each worker's residency lives in one
    shard; queries fan out and merge (ref KvIndexerSharded)."""

    def __init__(self, shards: int = 4, factory=None):
        self._shards = [(factory or PrefixIndex)() for _ in range(shards)]

    def _shard(self, worker_id: int) -> PrefixIndex:
        return self._shards[worker_id % len(self._shards)]

    def apply_event(self, ev: RouterEvent) -> None:
        self._shard(ev.worker_id).apply_event(ev)

    def remove_worker(self, worker_id: int) -> None:
        self._shard(worker_id).remove_worker(worker_id)

    def find_matches(self, block_hashes) -> OverlapScores:
        hashes = list(block_hashes)
        merged = OverlapScores(total_blocks=len(hashes))
        for s in self._shards:
            part = s.find_matches(hashes)
            merged.scores.update(part.scores)
        return merged


def make_prefix_index(shards: int = 1, use_native: bool = True):
    """PrefixIndex factory: the C++ tree (dynamo_tpu.native, mirroring the
    reference's native Rust indexer) when its library is loaded, else the
    pure-Python twin. Behavior is identical (differential-tested)."""
    if use_native:
        from .. import native

        if native.available():
            if shards <= 1:
                return native.NativePrefixIndex()
            return ShardedPrefixIndex(
                shards, factory=native.NativePrefixIndex
            )
    return PrefixIndex() if shards <= 1 else ShardedPrefixIndex(shards)


class KvIndexer:
    """Event-plane consumer: subscribes the component's kv_events subject
    and owns a PrefixIndex behind a queue (ref KvIndexer, indexer.rs:499).

    Tier tracking rides as an overlay, not in the tree: ``demoted``
    events (block left the device cache for the worker's offload tiers)
    flip a ``(worker, hash)`` membership set instead of touching the
    index, so both the Python tree and the native C++ tree stay
    tier-blind and byte-compatible. ``find_matches`` projects the
    overlay into ``OverlapScores.device_scores``."""

    def __init__(self, drt, component, shards: int = 1, use_native: bool = True):
        self.drt = drt
        self.component = component
        self.index = make_prefix_index(shards=shards, use_native=use_native)
        self._task: Optional[asyncio.Task] = None
        self.events_applied = 0
        # (worker_id, block_hash) currently resident ONLY in the
        # worker's offload tiers; stored re-promotes, removed drops.
        # Insertion-ordered + capped: the tree's chain-cascade can
        # remove descendants a ``removed`` event never names, leaving
        # their overlay entries behind — stale entries are harmless to
        # correctness (any path that puts (w, h) back in the tree goes
        # through a stored event for exactly that pair, clearing it;
        # an orphaned entry only makes device_scores conservative) but
        # must not grow without bound on a long-lived router
        self._offloaded: "OrderedDict[tuple[int, int], None]" = OrderedDict()
        self._offloaded_cap = 1 << 18
        # per-worker event-id continuity: the publisher stamps a
        # monotonically increasing event_id, so a jump > 1 means the bus
        # dropped events and this router's radix view has silently
        # diverged from the worker's real residency until the next
        # stored/removed pair for the affected chains
        self.event_gaps = 0
        self._last_event_id: dict[int, int] = {}

    async def start(self) -> "KvIndexer":
        sub = self.drt.bus.subscribe(self.component.event_subject(KV_EVENT_SUBJECT))
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _consume(self, sub) -> None:
        # apply inline: mutation and lookups share the event loop, so a
        # separate applier task (the reference's mpsc, indexer.rs:499) would
        # only add an unbounded buffer here
        async for msg in sub:
            try:
                self.apply_event(RouterEvent.from_bytes(msg.payload))
            except Exception:  # noqa: BLE001
                logger.exception("bad kv event")

    def apply_event(self, ev: RouterEvent) -> None:
        if ev.event_id:
            last = self._last_event_id.get(ev.worker_id, 0)
            if last and ev.event_id > last + 1:
                self.event_gaps += 1
                logger.debug(
                    "kv event gap from worker %x: %d -> %d",
                    ev.worker_id, last, ev.event_id,
                )
            if ev.event_id > last:
                self._last_event_id[ev.worker_id] = ev.event_id
        kv = ev.event
        if kv.kind == "demoted":
            # overlay-only: the residency stays in the tree (the worker
            # still holds the KV), it just stops counting as device
            for h in kv.block_hashes:
                self._offloaded[(ev.worker_id, h)] = None
                self._offloaded.move_to_end((ev.worker_id, h))
            while len(self._offloaded) > self._offloaded_cap:
                # dropping the oldest entry is safe-conservative: the
                # block reads as device-resident again, which at worst
                # suppresses one redundant prefetch hint
                self._offloaded.popitem(last=False)
            self.events_applied += 1
            return
        if kv.kind == "stored":
            # a restore/commit puts the block back on device
            for blk in kv.blocks:
                self._offloaded.pop((ev.worker_id, blk.block_hash), None)
        elif kv.kind == "removed":
            for h in kv.block_hashes:
                self._offloaded.pop((ev.worker_id, h), None)
        self.index.apply_event(ev)
        self.events_applied += 1

    def find_matches(self, block_hashes) -> OverlapScores:
        hashes = list(block_hashes)
        scores = self.index.find_matches(hashes)
        if self._offloaded:
            for w, k in scores.scores.items():
                for i in range(k):
                    if (w, hashes[i]) in self._offloaded:
                        scores.device_scores[w] = i
                        break
        return scores

    def find_matches_for_tokens(self, tokens, block_size: int) -> OverlapScores:
        from ..engine.allocator import sequence_block_hashes

        hashes = [seq for _loc, seq in sequence_block_hashes(tokens, block_size)]
        return self.find_matches(hashes)

    def remove_worker(self, worker_id: int) -> None:
        self._offloaded = OrderedDict(
            (k, None) for k in self._offloaded if k[0] != worker_id
        )
        # a departed worker's event-id restarts from 1 when it rejoins —
        # carrying the old high-water would count the restart as a gap
        self._last_event_id.pop(worker_id, None)
        self.index.remove_worker(worker_id)

"""KvRouter: indexer + metrics + scheduler behind one ``schedule`` call,
plus the pipeline operator that routes preprocessed requests to workers.

Re-design of lib/llm/src/kv_router.rs:45-143 (KvRouter.schedule) and the
python router component (examples/llm/components/kv_router.py): the router
sits between the preprocessor and the worker client, computes the
request's chained block hashes, scores overlap against the global index,
and pins the request to the chosen worker with ``client.direct``.
"""

from __future__ import annotations

import logging
from typing import AsyncIterator, Optional

from .. import tracing
from ..engine.allocator import model_hash_salt, sequence_block_hashes
from ..protocols.common import PreprocessedRequest
from ..runtime.annotated import Annotated
from ..runtime.component import Client, Component
from ..runtime.engine import AsyncEngine, Context
from .indexer import KvIndexer
from .publisher import KvMetricsAggregator
from .scheduler import AllWorkersBusy, KvScheduler, SchedulerConfig

logger = logging.getLogger(__name__)


class KvRouter:
    """ref kv_router.rs:45 KvRouter{indexer, scheduler}."""

    def __init__(
        self,
        drt,
        component: Component,
        block_size: int = 16,
        config: Optional[SchedulerConfig] = None,
        indexer_shards: int = 1,
        model_name: Optional[str] = None,
    ):
        self.drt = drt
        self.component = component
        self.block_size = block_size
        # served model name, stamped into prefetch hints so workers can
        # pre-stage weights (PRESERVE) — None for single-model stacks
        # that never told the router what they serve
        self.model_name = model_name
        self.indexer = KvIndexer(drt, component, shards=indexer_shards)
        self.metrics = KvMetricsAggregator(drt, component)
        self.scheduler = KvScheduler(drt, component, config)
        # planner plane: latest disagg-ratio hint from the capacity
        # watermark events (advisory — recorded for operators/the disagg
        # router; 0 until a planner publishes)
        self.disagg_ratio_hint = 0.0
        self._watch_task = None
        self._watermark_task = None
        self._health_task = None

    async def start(self) -> "KvRouter":
        await self.indexer.start()
        await self.metrics.start()
        # prune dead workers from the index when their discovery keys vanish
        # (lease loss), ref indexer.rs:380 remove_worker wiring
        import asyncio

        watcher = self.drt.store.watch_prefix(self.component.etcd_root + "/")
        if asyncio.iscoroutine(watcher):
            watcher = await watcher
        self._watch_task = self.drt.runtime.spawn(self._watch_instances(watcher))
        # planner capacity watermarks: saturated workers stop receiving
        # new routes until the next tick clears them
        from ..planner.protocols import PLANNER_WATERMARK_SUBJECT

        sub = self.drt.bus.subscribe(
            self.component.event_subject(PLANNER_WATERMARK_SUBJECT)
        )
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._watermark_task = self.drt.runtime.spawn(
            self._consume_watermarks(sub)
        )
        # autopilot health directives: quarantined / pre-warm-held
        # workers fold into the scheduler's soft-exclusion chain the
        # same way watermarks do
        from ..autopilot.protocols import AUTOPILOT_HEALTH_SUBJECT

        hsub = self.drt.bus.subscribe(
            self.component.event_subject(AUTOPILOT_HEALTH_SUBJECT)
        )
        ready = getattr(hsub, "ready", None)
        if ready is not None:
            await ready
        self._health_task = self.drt.runtime.spawn(
            self._consume_health(hsub)
        )
        return self

    async def _consume_health(self, sub) -> None:
        from ..autopilot.protocols import HealthDirective

        async for msg in sub:
            try:
                hd = HealthDirective.from_bytes(msg.payload)
                if hd is None:
                    continue
                self.scheduler.set_autopilot_health(
                    hd.quarantined, hd.prewarm_hold
                )
            except Exception:  # noqa: BLE001 — directives are advisory
                logger.debug("bad autopilot health directive", exc_info=True)

    async def _consume_watermarks(self, sub) -> None:
        from ..planner.protocols import CapacityWatermark

        async for msg in sub:
            try:
                wm = CapacityWatermark.from_bytes(msg.payload)
                self.scheduler.set_watermarks(wm.saturated_workers)
                self.disagg_ratio_hint = wm.disagg_ratio
            except Exception:  # noqa: BLE001 — watermarks are advisory
                logger.debug("bad planner watermark", exc_info=True)

    async def _watch_instances(self, watcher) -> None:
        from ..runtime.store import EventKind

        async for ev in watcher:
            if ev.kind != EventKind.DELETE:
                continue
            try:
                lease_hex = ev.key.rsplit(":", 1)[1]
                worker_id = int(lease_hex, 16)
            except (IndexError, ValueError):
                continue
            logger.info("pruning dead worker %x from kv index", worker_id)
            self.indexer.remove_worker(worker_id)

    async def schedule(
        self, token_ids: list[int], avoid: frozenset = frozenset(),
        model: str = "",
    ) -> tuple[int, int]:
        """-> (worker_id, overlap_blocks). Raises AllWorkersBusy.

        ``model`` names the requested adapter ("" = base): it salts the
        block-hash chain into the model's namespace (the SAME chain the
        worker's allocator builds, so overlap scoring stays honest and a
        cross-model token-identical prompt scores ZERO overlap), narrows
        selection to workers advertising the model, and rides the
        prefetch hint so the worker pre-stages the adapter's weights."""
        # the BASE model's own name must hash exactly like "" — workers
        # resolve it to the unsalted base lane (engine.py generate), and
        # pre-multi-model fleets whose requests carry the served name
        # must keep their unsalted chains (no hash drift on upgrade)
        salt_name = "" if model == (self.model_name or "") else model
        pairs = sequence_block_hashes(
            token_ids, self.block_size, salt=model_hash_salt(salt_name)
        )
        hashes = [s for _l, s in pairs]
        overlaps = self.indexer.find_matches(hashes)
        # never scrape inline: the aggregator loop refreshes every interval;
        # an empty load set (cold start / all workers gone) raises
        # AllWorkersBusy and the caller falls back to round robin
        worker_id = self.scheduler.select_worker(
            self.metrics.endpoints, overlaps, len(hashes), avoid=avoid,
            model=model,
        )
        overlap = overlaps.scores.get(worker_id, 0)
        # admission hashes prompt[:-1] (the final token always recomputes
        # for fresh logits), so a prompt of exactly N full blocks can only
        # ever claim N-1 — don't hint a block the worker can't claim
        n_hint = (
            len(pairs) - 1 if token_ids and len(token_ids)
            % self.block_size == 0 else len(pairs)
        )
        # compare against the CLAIMABLE chain, on the DEVICE-tier depth:
        # a worker already holding all n_hint claimable blocks on device
        # must not be re-hinted every turn, but a chain the worker
        # demoted to host/disk still wants the hint (it triggers the
        # pre-arrival restore that hides the promotion latency)
        if overlaps.device(worker_id) < n_hint:
            # fleet prefix cache: when a PEER's radix chain covers the
            # prompt deeper than everything the routed worker holds
            # (any tier), name it in the hint — the worker pulls the
            # continuation from the peer's tiers over the transfer
            # plane before the request lands. The chooser prefers the
            # NEAREST adequate peer (same-slice ICI beats a deeper
            # chain across DCN) once the cost model is calibrated;
            # advisory, like the hint itself.
            peer_id, peer_blocks = self.scheduler.choose_peer(
                self.metrics.endpoints, overlaps, worker_id, n_hint
            )
            self.scheduler.emit_prefetch(
                worker_id, pairs[:n_hint],
                peer_worker_id=peer_id,
                peer_blocks=peer_blocks,
                # the REQUEST's model wins (adapter prestage); the
                # router-wide name is the single-model legacy fallback
                model=model or self.model_name,
            )
        return worker_id, overlap

    def request_finished(self, worker_id: int) -> None:
        self.scheduler.request_finished(worker_id)

    def remove_worker(self, worker_id: int) -> None:
        self.indexer.remove_worker(worker_id)


class KvRoutedEngine(AsyncEngine):
    """Routes PreprocessedRequests to the KV-best worker instance
    (falls back to round robin when the router can't decide)."""

    def __init__(self, router: KvRouter, client: Client):
        self.router = router
        self.client = client

    async def generate(self, request: Context) -> AsyncIterator[Annotated]:
        data = request.data
        token_ids = (
            data.token_ids
            if isinstance(data, PreprocessedRequest)
            else (data or {}).get("token_ids", [])
        )
        model = (
            data.model
            if isinstance(data, PreprocessedRequest)
            else (data or {}).get("model", "")
        ) or ""
        payload = data.to_dict() if isinstance(data, PreprocessedRequest) else data
        worker_id: Optional[int] = None
        # workers a migrating request already failed on (resilience/
        # migration.py stamps them on re-dispatch): a killed worker stays
        # leased until its TTL lapses, so routing must steer around it
        # rather than trust discovery
        avoid = frozenset(
            i for i in (request.annotations.get("migration.avoid_workers") or ())
            if isinstance(i, int)
        )
        # the routing decision is the TTFT's "route" component — recorded
        # even on the fallback paths (the time was spent either way)
        with tracing.span("router.schedule", request_id=request.id) as rt_span:
            try:
                worker_id, overlap = await self.router.schedule(
                    token_ids, avoid=avoid, model=model
                )
                rt_span.set(worker=f"{worker_id:x}", overlap_blocks=overlap)
            except AllWorkersBusy:
                rt_span.set(fallback="round_robin")
                logger.warning("all workers busy; falling back to round robin")
            except Exception:  # noqa: BLE001
                rt_span.set(fallback="round_robin", error="router_failure")
                logger.exception("router failure; falling back to round robin")
        if worker_id is None and avoid:
            # router fallback on a re-dispatch: blind round-robin may hand
            # the request straight back to the instance it is fleeing —
            # pin any live instance outside the avoid set instead
            alive = sorted(set(self.client.instance_ids()) - avoid)
            if alive:
                worker_id = alive[0]
        try:
            if worker_id is not None and worker_id in set(self.client.instance_ids()):
                # stamp the pinned instance into the request annotations:
                # the migration layer reads it back on a stream failure to
                # tell lease loss (instance gone from the store watch)
                # from a transient TCP drop (instance still live)
                request.annotations["routed_worker_id"] = worker_id
                stream = await self.client.direct(request.transfer(payload), worker_id)
            else:
                request.annotations.pop("routed_worker_id", None)
                stream = await self.client.round_robin(request.transfer(payload))
            async for item in stream:
                yield item
        finally:
            if worker_id is not None:
                self.router.request_finished(worker_id)

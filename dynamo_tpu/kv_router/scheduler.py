"""KV-aware worker selection (re-design of lib/llm/src/kv_router/
scheduler.rs:84-316).

Cost model per candidate worker, as in the reference (scheduler.rs:221-262):

  normalized_new_tokens = tokens the worker would have to prefill / isl
  load_deviation        = worker kv usage - mean kv usage
  request_load_ratio    = active requests / slots

  cost = alpha * load_deviation
       + (1 - alpha) * normalized_new_tokens
       + gamma * request_load_ratio

with a "balance mode" switch: when the kv-load standard deviation across
workers exceeds a threshold the weights flip to prioritize load (alpha
0.7) over cache overlap (alpha 0.3 otherwise). Full workers are skipped;
if every worker is saturated the scheduler reports AllWorkersBusy so the
caller can queue (ref scheduler.rs:165-174). Selected workers get an
optimistic local load bump so concurrent decisions spread out (ref
scheduler.rs:281-282).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from .indexer import OverlapScores
from .protocols import (
    KV_HIT_RATE_SUBJECT,
    KV_PREFETCH_MAX_BLOCKS,
    KV_PREFETCH_SUBJECT,
    KVHitRateEvent,
    KvPrefetchHint,
)

logger = logging.getLogger(__name__)


class AllWorkersBusy(Exception):
    pass


@dataclass
class WorkerLoad:
    worker_id: int
    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    active_requests: int = 0
    total_slots: int = 1
    waiting: int = 0
    # async offload-tier surface (engine OffloadManager.stats): scraped
    # for the fleet metrics endpoint, not used by the cost model
    offload_blocks_resident: int = 0
    offload_d2h_flush_async: int = 0
    offload_prefetch_hits: int = 0
    offload_restore_hidden_frac: float = 0.0
    # third-tier + fleet-prefix-cache surface (ISSUE 10): disk-tier
    # residency/hits, peer-pull volume, and the fraction of pulled
    # blocks whose cross-worker transfer stayed off every TTFT path
    disk_blocks_resident: int = 0
    disk_hit_blocks: int = 0
    peer_pull_blocks: int = 0
    peer_pull_hidden_frac: float = 0.0
    # resilience surface: a draining worker (SIGTERM received, lease
    # still live) must not be picked — its engine bounces new work
    draining: int = 0
    drains_total: int = 0
    migration_resumes: int = 0
    # disagg KV-handoff surface (DisaggEngine.stats): streamed (layer-
    # wise, transfer hidden behind prefill) vs bulk deliveries, plus the
    # segment volume landed through the incremental scatter path
    kv_stream_deliveries: int = 0
    kv_bulk_deliveries: int = 0
    kv_stream_segments: int = 0
    # mixed-batch fusion surface (engine stats): fused steps dispatched
    # and how many prefill SEGMENTS packed into them — segments/steps
    # near 1 under a deep prompt queue means head-of-line blocking the
    # packer should be absorbing (docs/architecture.md mixed batching)
    mixed_steps: int = 0
    mixed_prefill_segments: int = 0
    # cumulative serving counters (engine stats): the planner's
    # telemetry aggregator turns scrape-to-scrape deltas into fleet
    # arrival/throughput rates
    requests_total: int = 0
    tokens_generated: int = 0
    prompt_tokens_total: int = 0
    # runtime-sanitizer surface (dynamo_tpu.analysis.sanitizer, exported
    # through engine.load_metrics when a sanitizer is active): event-loop
    # stalls and worst lock holds observed on THIS worker — production
    # stalls become fleet gauges instead of test-time-only signals
    loop_stalls: int = 0
    loop_stall_max_ms: float = 0.0
    lock_hold_max_ms: float = 0.0
    writers_leaked: int = 0
    # monotonic stamp set at scrape time (None = constructed directly /
    # legacy producer): the scheduler discards loads older than
    # ``SchedulerConfig.load_ttl_s`` instead of trusting a dead
    # worker's last report
    ts: Optional[float] = None

    @property
    def kv_usage(self) -> float:
        return self.kv_active_blocks / max(self.kv_total_blocks, 1)

    @property
    def slot_usage(self) -> float:
        return self.active_requests / max(self.total_slots, 1)

    @property
    def saturated(self) -> bool:
        return self.active_requests >= self.total_slots and self.waiting > 0


@dataclass
class ProcessedEndpoints:
    loads: list[WorkerLoad]

    def __post_init__(self):
        self.by_id = {l.worker_id: l for l in self.loads}

    @property
    def load_avg(self) -> float:
        if not self.loads:
            return 0.0
        return sum(l.kv_usage for l in self.loads) / len(self.loads)

    @property
    def load_std(self) -> float:
        if not self.loads:
            return 0.0
        avg = self.load_avg
        return (sum((l.kv_usage - avg) ** 2 for l in self.loads) / len(self.loads)) ** 0.5

    def worker_ids(self) -> list[int]:
        return sorted(self.by_id)


@dataclass
class SchedulerConfig:
    overlap_alpha: float = 0.3  # weight on load when caches matter more
    balance_alpha: float = 0.7  # weight on load in balance mode
    balance_threshold: float = 0.2  # load-std that flips to balance mode
    gamma: float = 0.2  # request-load term
    #: discard WorkerLoad snapshots older than this (stamped at scrape
    #: time): a worker that died between scrapes keeps advertising its
    #: last (often attractive, near-idle) load until its lease TTL
    #: lapses — routing must not trust it. 0 disables the check.
    #: Default is deliberately >> the 1s scrape interval: it guards a
    #: wedged metrics plane, not one missed tick (a dead worker drops
    #: out of the very next successful scrape on its own).
    load_ttl_s: float = 30.0
    #: ignore planner capacity watermarks older than this: a planner
    #: that stopped publishing must not keep its last saturated-worker
    #: set applied to routing forever (same stale-authority guard as
    #: load_ttl_s). 0 disables the expiry.
    watermark_ttl_s: float = 30.0


class KvScheduler:
    def __init__(self, drt=None, component=None,
                 config: Optional[SchedulerConfig] = None, clock=None):
        import time as _time

        self.cfg = config or SchedulerConfig()
        self.drt = drt
        self._clock = clock or _time.monotonic
        # planner capacity watermarks: worker ids the planner currently
        # considers saturated — soft-excluded from selection (prefer any
        # unsaturated worker; fall back rather than refuse when every
        # candidate is marked)
        self.watermarked: set[int] = set()
        self._watermark_ts: Optional[float] = None
        self._hit_subject = (
            component.event_subject(KV_HIT_RATE_SUBJECT) if component else None
        )
        self._prefetch_subject = (
            component.event_subject(KV_PREFETCH_SUBJECT) if component else None
        )
        self.prefetch_hints_sent = 0
        # optimistic in-flight bumps: worker -> extra requests assumed
        self._pending: dict[int, int] = {}

    def select_worker(
        self,
        endpoints: ProcessedEndpoints,
        overlaps: OverlapScores,
        isl_blocks: int,
        avoid: frozenset = frozenset(),
    ) -> int:
        loads = [l for l in endpoints.loads]
        if not loads:
            raise AllWorkersBusy("no workers")
        if self.cfg.load_ttl_s > 0:
            now = self._clock()
            fresh = [
                l for l in loads
                if l.ts is None or now - l.ts <= self.cfg.load_ttl_s
            ]
            if not fresh:
                # every load is stale (metrics plane wedged / all
                # workers dead): refuse rather than route on fiction —
                # the caller falls back to round robin over discovery
                raise AllWorkersBusy("all worker loads stale")
            loads = fresh
        candidates = [l for l in loads if not l.saturated and not l.draining]
        if not candidates:
            raise AllWorkersBusy("all workers saturated or draining")
        # ``avoid`` carries the workers a migrating request already failed
        # on. A freshly-killed worker stays in discovery (and in the
        # metrics view) until its lease TTL lapses, and prefix affinity
        # would re-pick the corpse every time — soft-exclude: prefer any
        # other worker, but fall back rather than refuse when the avoid
        # set covers every candidate (lone-worker restarts)
        if avoid:
            preferred = [l for l in candidates if l.worker_id not in avoid]
            candidates = preferred or candidates
        # planner watermarks: workers at capacity stop receiving NEW
        # work while they drain their queues — soft, like ``avoid``,
        # so an all-saturated fleet still serves (the admission gate is
        # the component that actually sheds). A dead planner's last set
        # expires (watermark_ttl_s) instead of skewing routing forever
        if self.watermarked and self.cfg.watermark_ttl_s > 0:
            if (self._watermark_ts is None
                    or self._clock() - self._watermark_ts
                    > self.cfg.watermark_ttl_s):
                self.watermarked = set()
        if self.watermarked:
            preferred = [
                l for l in candidates if l.worker_id not in self.watermarked
            ]
            candidates = preferred or candidates

        balance_mode = endpoints.load_std > self.cfg.balance_threshold
        alpha = self.cfg.balance_alpha if balance_mode else self.cfg.overlap_alpha
        avg = endpoints.load_avg

        best_id, best_cost = None, None
        for l in candidates:
            overlap = overlaps.scores.get(l.worker_id, 0)
            new_blocks = max(isl_blocks - overlap, 0)
            norm_new = new_blocks / max(isl_blocks, 1)
            pending = self._pending.get(l.worker_id, 0)
            req_ratio = (l.active_requests + pending) / max(l.total_slots, 1)
            cost = (
                alpha * (l.kv_usage - avg)
                + (1 - alpha) * norm_new
                + self.cfg.gamma * req_ratio
            )
            if best_cost is None or cost < best_cost:
                best_id, best_cost = l.worker_id, cost

        assert best_id is not None
        self._pending[best_id] = self._pending.get(best_id, 0) + 1
        self._emit_hit_rate(best_id, isl_blocks, overlaps.scores.get(best_id, 0))
        return best_id

    def set_watermarks(self, saturated_workers) -> None:
        """Planner capacity-watermark update (full replacement — the
        planner republishes the complete set every tick, so a worker
        that cooled off clears automatically; a planner that stops
        publishing ages out via ``watermark_ttl_s``)."""
        self.watermarked = set(saturated_workers or ())
        self._watermark_ts = self._clock()

    def request_finished(self, worker_id: int) -> None:
        """Release the optimistic bump once the request lands/completes."""
        n = self._pending.get(worker_id, 0)
        if n <= 1:
            self._pending.pop(worker_id, None)
        else:
            self._pending[worker_id] = n - 1

    def emit_prefetch(
        self, worker_id: int, blocks: list,
        peer_worker_id: Optional[int] = None, peer_blocks: int = 0,
    ) -> None:
        """Ship the routed request's block-hash chain to the chosen
        worker as a prefetch hint ((tokens_hash, block_hash) pairs in
        prompt order) — fired when the worker's known device overlap
        doesn't cover the prompt, so the worker can start its host-tier
        h2d upload before the request arrives (engine.prefetch_hint).
        ``peer_worker_id`` names the peer whose radix chain covers the
        prompt deeper than the routed worker's own tiers (to depth
        ``peer_blocks``) — the worker pulls the continuation from that
        peer's host/disk tier over the transfer plane (fleet prefix
        cache). Best-effort: a lost hint only costs the overlap."""
        if self.drt is None or self._prefetch_subject is None or not blocks:
            return
        capped = blocks[:KV_PREFETCH_MAX_BLOCKS]
        hint = KvPrefetchHint(
            worker_id, [[l, s] for l, s in capped],
            peer_worker_id=peer_worker_id,
            peer_blocks=min(peer_blocks, len(capped)),
        )
        try:
            self.drt.bus.publish(self._prefetch_subject, hint.to_bytes())
            self.prefetch_hints_sent += 1
        except Exception:  # noqa: BLE001
            logger.debug("prefetch-hint publish failed", exc_info=True)

    def _emit_hit_rate(self, worker_id: int, isl_blocks: int, overlap: int) -> None:
        if self.drt is None or self._hit_subject is None:
            return
        try:
            self.drt.bus.publish(
                self._hit_subject,
                KVHitRateEvent(worker_id, isl_blocks, overlap).to_bytes(),
            )
        except Exception:  # noqa: BLE001
            logger.debug("hit-rate publish failed", exc_info=True)

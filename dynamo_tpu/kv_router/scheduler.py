"""KV-aware worker selection (re-design of lib/llm/src/kv_router/
scheduler.rs:84-316).

Cost model per candidate worker, as in the reference (scheduler.rs:221-262):

  normalized_new_tokens = tokens the worker would have to prefill / isl
  load_deviation        = worker kv usage - mean kv usage
  request_load_ratio    = active requests / slots

  cost = alpha * load_deviation
       + (1 - alpha) * normalized_new_tokens
       + gamma * request_load_ratio

with a "balance mode" switch: when the kv-load standard deviation across
workers exceeds a threshold the weights flip to prioritize load (alpha
0.7) over cache overlap (alpha 0.3 otherwise). Full workers are skipped;
if every worker is saturated the scheduler reports AllWorkersBusy so the
caller can queue (ref scheduler.rs:165-174). Selected workers get an
optimistic local load bump so concurrent decisions spread out (ref
scheduler.rs:281-282).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from .costmodel import predict_worker_ttft_ms, tail_adjusted_ttft_ms
from .indexer import OverlapScores
from .protocols import (
    KV_HIT_RATE_SUBJECT,
    KV_PREFETCH_MAX_BLOCKS,
    KV_PREFETCH_SUBJECT,
    KVHitRateEvent,
    KvPrefetchHint,
)

logger = logging.getLogger(__name__)


class AllWorkersBusy(Exception):
    pass


@dataclass
class WorkerLoad:
    worker_id: int
    kv_active_blocks: int = 0
    kv_total_blocks: int = 1
    active_requests: int = 0
    total_slots: int = 1
    waiting: int = 0
    # async offload-tier surface (engine OffloadManager.stats): scraped
    # for the fleet metrics endpoint, not used by the cost model
    offload_blocks_resident: int = 0
    offload_d2h_flush_async: int = 0
    offload_prefetch_hits: int = 0
    offload_restore_hidden_frac: float = 0.0
    # third-tier + fleet-prefix-cache surface (ISSUE 10): disk-tier
    # residency/hits, peer-pull volume, and the fraction of pulled
    # blocks whose cross-worker transfer stayed off every TTFT path
    disk_blocks_resident: int = 0
    disk_hit_blocks: int = 0
    peer_pull_blocks: int = 0
    peer_pull_hidden_frac: float = 0.0
    # disk-tier health + fleet-serve volume: corrupt entries discarded
    # as clean misses, host->disk demotions, and blocks this worker
    # served to peers from its host/disk tiers (the d2h device-tier
    # serve counts separately below) — the PR 9 chain the dynflow
    # unscraped-stat rule found dropped at this hop
    disk_corrupt_discards: int = 0
    disk_demotions: int = 0
    peer_serve_blocks: int = 0
    # resilience surface: a draining worker (SIGTERM received, lease
    # still live) must not be picked — its engine bounces new work
    draining: int = 0
    drains_total: int = 0
    drain_handoffs: int = 0
    migration_resumes: int = 0
    # elastic-reshard surface: ``resharding`` marks a live morph window
    # — the worker HOLDS work through it (requests queue, nothing
    # bounces), so unlike ``draining`` it is a SOFT exclusion: prefer
    # any non-morphing worker, fall back rather than refuse
    resharding: int = 0
    resharded_total: int = 0
    reshard_hold_ms: float = 0.0
    reshard_kv_moved_blocks: int = 0
    #: the worker's deployed tensor-parallel degree (0 = not
    #: advertised); seeds the planner's morph guard from reality
    mesh_tp: int = 0
    # disagg KV-handoff surface (DisaggEngine.stats): streamed (layer-
    # wise, transfer hidden behind prefill) vs bulk deliveries, plus the
    # segment volume landed through the incremental scatter path
    kv_stream_deliveries: int = 0
    kv_bulk_deliveries: int = 0
    kv_stream_segments: int = 0
    # mixed-batch fusion surface (engine stats): fused steps dispatched
    # and how many prefill SEGMENTS packed into them — segments/steps
    # near 1 under a deep prompt queue means head-of-line blocking the
    # packer should be absorbing (docs/architecture.md mixed batching)
    mixed_steps: int = 0
    mixed_prefill_segments: int = 0
    # cumulative serving counters (engine stats): the planner's
    # telemetry aggregator turns scrape-to-scrape deltas into fleet
    # arrival/throughput rates
    requests_total: int = 0
    tokens_generated: int = 0
    prompt_tokens_total: int = 0
    # runtime-sanitizer surface (dynamo_tpu.analysis.sanitizer, exported
    # through engine.load_metrics when a sanitizer is active): event-loop
    # stalls and worst lock holds observed on THIS worker — production
    # stalls become fleet gauges instead of test-time-only signals
    loop_stalls: int = 0
    loop_stall_max_ms: float = 0.0
    lock_holds: int = 0
    lock_hold_max_ms: float = 0.0
    writers_leaked: int = 0
    # executor pressure (sanitizer.register_executor): the deepest
    # pending-task backlog any registered executor (offload d2h/disk,
    # engine device dispatch) has reached — a wedged executor shows up
    # here before it shows up as TTFT
    executor_pending_max: int = 0
    # transfer-cost calibration (kv_router/costmodel.py): the worker's
    # observed per-link-class bandwidths, corrected prefill throughput,
    # and KV block geometry — everything the router needs to convert
    # this candidate's overlap depths into predicted milliseconds.
    # cost_obs gates cold-start: below the scheduler's cost_min_obs the
    # candidate routes on overlap like before
    cost_obs: int = 0
    link_gbps: dict = field(default_factory=dict)
    link_lat_ms: dict = field(default_factory=dict)
    prefill_tok_s: float = 0.0
    block_bytes: int = 0
    block_size: int = 0
    # tier/wire bytes per block under the worker's --kv-quant codec
    # (== block_bytes when quantization is off; 0 = not advertised,
    # pre-quant worker): restore and peer-pull legs move THESE bytes,
    # so predict/choose_peer price them with this, not the device width
    wire_block_bytes: int = 0
    # kv-quant codec activity (OffloadManager.stats): blocks encoded
    # into the quantized tiers/wire, and the bytes that saved vs full
    # width — the capacity win, observable per worker
    kv_quant_blocks: int = 0
    kv_quant_bytes_saved: int = 0
    # worst logprob drift the kv-quant quality harness recorded on this
    # worker (0 until a harness ran) — operators watch this gauge when
    # a quantized tier is enabled fleet-wide
    kv_quant_logprob_drift_max: float = 0.0
    # int8-with-scales DEVICE cache lane (kv_cache_dtype="int8",
    # models/quant.py): resident quantized pages, cumulative page
    # requantizations (appends that grew a page's absmax scale), HBM
    # bytes the lane saved vs full width, exports forced off the device
    # codec (full-width/fp8 bounce — ideally 0 with an int8 tier), and
    # the measured decode throughput of the low-precision lane
    kv_device_quant_pages: int = 0
    kv_device_requants: int = 0
    kv_device_bytes_saved: int = 0
    kv_device_export_requants: int = 0
    lowprec_tok_s: float = 0.0
    # accelerator-slice fingerprint (parallel/mesh.slice_fingerprint):
    # two workers advertising the same fp can hand KV device→device
    # over ICI — the peer chooser prices their pulls at the ici class
    slice_fp: str = ""
    # ICI fast-path + device-tier fleet-cache activity (gauges)
    ici_handoffs: int = 0
    peer_serve_d2h_blocks: int = 0
    weight_prestage_requests: int = 0
    # multi-model serving surface (engine.served_models / adapter
    # registry): the model names this worker can serve — "" is the base
    # model. An EMPTY tuple means the worker predates the advertisement
    # (or serves base only) and is treated as a wildcard so legacy
    # fleets keep routing unchanged; select_worker filters on this
    # BEFORE scoring, because no cost model makes an adapter-less
    # worker serve an adapter
    models: tuple = ()
    # adapter-prestage effectiveness: bytes of adapter weights staged
    # ahead of traffic via prefetch hints, and requests that found
    # their adapter already resident (the stall the prestage hid)
    prestage_bytes: int = 0
    prestage_hits: int = 0
    # per-model TTFT distributions (hist_ttft_ms: model name -> to_vec
    # bucket vector, "" = base): the metrics component renders these as
    # model-labelled histogram families and trace replay asserts
    # per-model p99 SLOs from them
    model_hists: dict = field(default_factory=dict)
    # SLO observatory (docs/observability.md): worker-side latency
    # distributions as serialized histogram bucket vectors
    # (observability/hist.py to_vec form, keyed queue_wait_ms /
    # prefill_ms / restore_ms / handoff_ms) — the metrics component
    # renders them as per-worker Prometheus histogram families and the
    # planner's telemetry merges them loss-free across the fleet
    hists: dict = field(default_factory=dict)
    # XLA compile ledger counters + warmup coverage: distinct program
    # buckets compiled (with total compile wall-ms), and how many of
    # the warmup-reachable buckets were actually warmed — a production
    # TTFT spike correlating with a compiles_total step IS a cold
    # bucket, attributable instead of anonymous
    xla_compiles: int = 0
    xla_compile_ms: float = 0.0
    xla_warm_buckets: int = 0
    xla_reachable_buckets: int = 0
    # autopilot actuation surface (docs/autopilot.md): control-plane
    # warmups this worker ran (and the wall they took — the compile tax
    # paid OFF the hot path), plus the worker's mirrored quarantine
    # state: currently pulled from rotation, and total times it was
    autopilot_warmups: int = 0
    autopilot_warmup_ms: float = 0.0
    autopilot_quarantined: int = 0
    autopilot_quarantines: int = 0
    # TPU device-memory telemetry: allocator view (bytes_limit == 0
    # marks the attributed-sum fallback on backends without
    # memory_stats) plus the engine's exact KV-pool/weights attribution
    hbm_bytes_in_use: int = 0
    hbm_bytes_limit: int = 0
    hbm_kv_pool_bytes: int = 0
    hbm_weights_bytes: int = 0
    # monotonic stamp set at scrape time (None = constructed directly /
    # legacy producer): the scheduler discards loads older than
    # ``SchedulerConfig.load_ttl_s`` instead of trusting a dead
    # worker's last report
    ts: Optional[float] = None

    @staticmethod
    def from_stats(worker_id: int, d: dict, ts: Optional[float] = None) -> "WorkerLoad":
        """One WorkerLoad from a worker's ``load_metrics`` payload — the
        single mapping the metrics aggregator, the bench harness and
        tests all share, so the scrape schema can't drift per consumer."""
        return WorkerLoad(
            worker_id=worker_id,
            kv_active_blocks=d.get("kv_active_blocks", 0),
            kv_total_blocks=max(d.get("kv_total_blocks", 1), 1),
            active_requests=d.get("request_active_slots", 0),
            total_slots=max(d.get("request_total_slots", 1), 1),
            waiting=d.get("num_requests_waiting", 0),
            offload_blocks_resident=d.get("offload_blocks_resident", 0),
            offload_d2h_flush_async=d.get("d2h_flush_async", 0),
            offload_prefetch_hits=d.get("h2d_prefetch_hits", 0),
            offload_restore_hidden_frac=d.get(
                "restore_latency_hidden_frac", 0.0),
            disk_blocks_resident=d.get("disk_blocks_resident", 0),
            disk_hit_blocks=d.get("disk_hit_blocks_total", 0),
            peer_pull_blocks=d.get("peer_pull_blocks_total", 0),
            peer_pull_hidden_frac=d.get("peer_pull_hidden_frac", 0.0),
            disk_corrupt_discards=d.get("disk_corrupt_discards", 0),
            disk_demotions=d.get("disk_demotions_total", 0),
            peer_serve_blocks=d.get("peer_serve_blocks_total", 0),
            draining=d.get("draining", 0),
            drains_total=d.get("drains_total", 0),
            drain_handoffs=d.get("drain_handoffs", 0),
            migration_resumes=d.get("migration_resumes", 0),
            resharding=d.get("resharding", 0),
            resharded_total=d.get("resharded_total", 0),
            reshard_hold_ms=d.get("reshard_hold_ms", 0.0),
            reshard_kv_moved_blocks=d.get("reshard_kv_moved_blocks", 0),
            mesh_tp=d.get("mesh_tp", 0),
            kv_stream_deliveries=d.get("streamed_deliveries", 0),
            kv_bulk_deliveries=d.get("bulk_deliveries", 0),
            kv_stream_segments=d.get("kv_stream_segments", 0),
            mixed_steps=d.get("mixed_steps", 0),
            mixed_prefill_segments=d.get("mixed_prefill_segments", 0),
            requests_total=d.get("requests_total", 0),
            tokens_generated=d.get("tokens_generated", 0),
            prompt_tokens_total=d.get("prompt_tokens_total", 0),
            loop_stalls=d.get("san_loop_stalls", 0),
            loop_stall_max_ms=d.get("san_loop_stall_max_ms", 0.0),
            lock_holds=d.get("san_lock_holds", 0),
            lock_hold_max_ms=d.get("san_lock_hold_max_ms", 0.0),
            writers_leaked=d.get("san_writers_leaked", 0),
            executor_pending_max=d.get("san_executor_pending_max", 0),
            cost_obs=d.get("kv_cost_obs_total", 0),
            link_gbps=dict(d.get("kv_link_gbps") or {}),
            link_lat_ms=dict(d.get("kv_link_lat_ms") or {}),
            prefill_tok_s=d.get("kv_prefill_tok_s", 0.0),
            block_bytes=d.get("kv_block_bytes", 0),
            block_size=d.get("kv_block_size", 0),
            wire_block_bytes=d.get("kv_wire_block_bytes", 0),
            kv_quant_blocks=d.get("kv_quant_blocks_total", 0),
            kv_quant_bytes_saved=d.get("kv_quant_bytes_saved_total", 0),
            kv_quant_logprob_drift_max=d.get(
                "kv_quant_logprob_drift_max", 0.0),
            kv_device_quant_pages=d.get("kv_device_quant_pages", 0),
            kv_device_requants=d.get("kv_device_requants_total", 0),
            kv_device_bytes_saved=d.get("kv_device_bytes_saved_total", 0),
            kv_device_export_requants=d.get(
                "kv_device_export_requant_total", 0),
            lowprec_tok_s=d.get("lowprec_tok_s", 0.0),
            slice_fp=str(d.get("kv_slice_fp") or ""),
            ici_handoffs=d.get("ici_handoffs", 0),
            peer_serve_d2h_blocks=d.get("peer_serve_d2h_blocks_total", 0),
            weight_prestage_requests=d.get("weight_prestage_requests", 0),
            models=tuple(d.get("served_models") or ()),
            prestage_bytes=d.get("weight_prestage_bytes", 0),
            prestage_hits=d.get("weight_prestage_hits", 0),
            model_hists=dict(d.get("hist_ttft_ms") or {}),
            hists={
                name: vec
                for name, vec in (
                    ("queue_wait_ms", d.get("hist_queue_wait_ms")),
                    ("prefill_ms", d.get("hist_prefill_ms")),
                    ("restore_ms", d.get("hist_restore_ms")),
                    ("handoff_ms", d.get("hist_handoff_ms")),
                )
                if vec
            },
            xla_compiles=d.get("xla_compiles_total", 0),
            xla_compile_ms=d.get("xla_compile_ms_total", 0.0),
            xla_warm_buckets=d.get("xla_warm_buckets", 0),
            xla_reachable_buckets=d.get("xla_reachable_buckets", 0),
            autopilot_warmups=d.get("autopilot_warmups_applied", 0),
            autopilot_warmup_ms=d.get("autopilot_warmup_ms_total", 0.0),
            autopilot_quarantined=d.get("autopilot_quarantined", 0),
            autopilot_quarantines=d.get("autopilot_quarantines_total", 0),
            hbm_bytes_in_use=d.get("hbm_bytes_in_use", 0),
            hbm_bytes_limit=d.get("hbm_bytes_limit", 0),
            hbm_kv_pool_bytes=d.get("hbm_kv_pool_bytes", 0),
            hbm_weights_bytes=d.get("hbm_weights_bytes", 0),
            ts=ts,
        )

    def serves(self, model: str) -> bool:
        """Can this worker serve ``model``? ``""`` (base traffic) is
        always servable; a worker advertising no model list is a legacy
        wildcard (pre-multi-model producer — routing must not strand
        it), and so is one whose advertisement CONTAINS ``""`` (a
        single-model engine with no configured served name accepts any
        name — the legacy contract); otherwise the name must be in the
        advertisement."""
        if not model:
            return True
        if not self.models:
            return True
        return "" in self.models or model in self.models

    @property
    def wire_bytes_per_block(self) -> int:
        """Bytes one block actually moves on this worker's tier/wire
        planes: the quantized advertisement when present, the full
        width otherwise (pre-quant workers keep their old pricing)."""
        return self.wire_block_bytes or self.block_bytes

    @property
    def kv_usage(self) -> float:
        return self.kv_active_blocks / max(self.kv_total_blocks, 1)

    @property
    def slot_usage(self) -> float:
        return self.active_requests / max(self.total_slots, 1)

    @property
    def saturated(self) -> bool:
        return self.active_requests >= self.total_slots and self.waiting > 0


@dataclass
class ProcessedEndpoints:
    loads: list[WorkerLoad]

    def __post_init__(self):
        self.by_id = {l.worker_id: l for l in self.loads}

    @property
    def load_avg(self) -> float:
        if not self.loads:
            return 0.0
        return sum(l.kv_usage for l in self.loads) / len(self.loads)

    @property
    def load_std(self) -> float:
        if not self.loads:
            return 0.0
        avg = self.load_avg
        return (sum((l.kv_usage - avg) ** 2 for l in self.loads) / len(self.loads)) ** 0.5

    def worker_ids(self) -> list[int]:
        return sorted(self.by_id)


@dataclass
class SchedulerConfig:
    overlap_alpha: float = 0.3  # weight on load when caches matter more
    balance_alpha: float = 0.7  # weight on load in balance mode
    balance_threshold: float = 0.2  # load-std that flips to balance mode
    gamma: float = 0.2  # request-load term
    #: discard WorkerLoad snapshots older than this (stamped at scrape
    #: time): a worker that died between scrapes keeps advertising its
    #: last (often attractive, near-idle) load until its lease TTL
    #: lapses — routing must not trust it. 0 disables the check.
    #: Default is deliberately >> the 1s scrape interval: it guards a
    #: wedged metrics plane, not one missed tick (a dead worker drops
    #: out of the very next successful scrape on its own).
    load_ttl_s: float = 30.0
    #: ignore planner capacity watermarks older than this: a planner
    #: that stopped publishing must not keep its last saturated-worker
    #: set applied to routing forever (same stale-authority guard as
    #: load_ttl_s). 0 disables the expiry.
    watermark_ttl_s: float = 30.0
    #: transfer-cost-aware placement (costmodel.py): score candidates by
    #: predicted TTFT = queue_wait + transfer + prefill from their own
    #: advertised link/throughput calibration. Falls back to the overlap
    #: cost above whenever ANY candidate is still cold — mixing the two
    #: score scales in one decision would be meaningless. False = the
    #: PR 9 overlap scoring unconditionally.
    cost_model: bool = True
    #: calibration observations a candidate must advertise before its
    #: predicted TTFT is trusted (cold-start gate)
    cost_min_obs: int = 4
    #: tail-aware routing (autopilot loop 1): fold each candidate's
    #: WINDOWED measured tail (p-quantile of queue-wait + prefill,
    #: differenced from the scraped cumulative histograms) into the
    #: cost-mode score as a floor — a bimodal worker is priced at its
    #: tail instead of the mean its EWMA calibration reports. Inert for
    #: workers with no window evidence (cold / idle / pre-observatory
    #: producers), so legacy fleets route unchanged.
    tail_aware: bool = True
    tail_q: float = 0.99
    tail_window_s: float = 60.0
    #: window samples the tail needs before it is trusted
    tail_min_count: int = 8
    #: ignore autopilot health directives older than this: an autopilot
    #: that stopped publishing must not keep workers quarantined or
    #: held forever (same stale-authority guard as watermark_ttl_s).
    #: 0 disables the expiry.
    autopilot_ttl_s: float = 30.0


class KvScheduler:
    def __init__(self, drt=None, component=None,
                 config: Optional[SchedulerConfig] = None, clock=None):
        import time as _time

        self.cfg = config or SchedulerConfig()
        self.drt = drt
        self._clock = clock or _time.monotonic
        # planner capacity watermarks: worker ids the planner currently
        # considers saturated — soft-excluded from selection (prefer any
        # unsaturated worker; fall back rather than refuse when every
        # candidate is marked)
        self.watermarked: set[int] = set()
        self._watermark_ts: Optional[float] = None
        # autopilot health directives (docs/autopilot.md): quarantined
        # workers (breach-rate spike) and pre-warm holds (cold XLA grid
        # compiling off the hot path) — both soft-excluded like
        # ``resharding`` workers, both full-replacement + TTL like the
        # planner watermarks above
        self.quarantined: set[int] = set()
        self.prewarm_hold: set[int] = set()
        self._autopilot_ts: Optional[float] = None
        # windowed per-worker tails from the scraped cumulative
        # histograms (autopilot tail-aware routing); imported lazily —
        # autopilot.tails needs observability.hist, whose package init
        # reaches back through kv_router to this module
        from ..autopilot.tails import TailTracker

        self.tails = TailTracker(
            window_s=self.cfg.tail_window_s, q=self.cfg.tail_q,
            min_count=self.cfg.tail_min_count, clock=self._clock,
        )
        self.route_tail_overrides = 0
        self._hit_subject = (
            component.event_subject(KV_HIT_RATE_SUBJECT) if component else None
        )
        self._prefetch_subject = (
            component.event_subject(KV_PREFETCH_SUBJECT) if component else None
        )
        self.prefetch_hints_sent = 0
        # optimistic in-flight bumps: worker -> extra requests assumed
        self._pending: dict[int, int] = {}
        # last decision's route mode + prediction (observability:
        # riders on the KVHitRateEvent -> route_predicted_ttft_ms gauge)
        self.last_predicted_ttft_ms: Optional[float] = None
        self.route_cost_decisions = 0
        self.route_overlap_decisions = 0

    def select_worker(
        self,
        endpoints: ProcessedEndpoints,
        overlaps: OverlapScores,
        isl_blocks: int,
        avoid: frozenset = frozenset(),
        model: str = "",
    ) -> int:
        loads = [l for l in endpoints.loads]
        if not loads:
            raise AllWorkersBusy("no workers")
        if self.cfg.tail_aware:
            # feed the tail windows from every scrape that flows
            # through a decision (deduped on the scrape stamp) — the
            # quarantined/held workers' tails keep updating too, so
            # their recovery is visible when they return
            for l in endpoints.loads:
                self.tails.observe(l.worker_id, l.hists, ts=l.ts)
        if model:
            # model filter comes BEFORE every score: a worker without
            # the adapter can't serve the request at any cost, and the
            # hard exclusion must not soften into the avoid/watermark
            # fallbacks below. Distinct error text — "no worker serves
            # this model" is a deployment gap, not transient pressure
            loads = [l for l in loads if l.serves(model)]
            if not loads:
                raise AllWorkersBusy(f"no worker serves model {model!r}")
        if self.cfg.load_ttl_s > 0:
            now = self._clock()
            fresh = [
                l for l in loads
                if l.ts is None or now - l.ts <= self.cfg.load_ttl_s
            ]
            if not fresh:
                # every load is stale (metrics plane wedged / all
                # workers dead): refuse rather than route on fiction —
                # the caller falls back to round robin over discovery
                raise AllWorkersBusy("all worker loads stale")
            loads = fresh
        candidates = [l for l in loads if not l.saturated and not l.draining]
        if not candidates:
            raise AllWorkersBusy("all workers saturated or draining")
        # a worker mid-morph (elastic reshard) HOLDS new work through
        # the quiesce window instead of bouncing it, so exclusion is
        # soft: route around it while it morphs, but a one-worker pool
        # still serves (its requests just wait out the hold)
        not_morphing = [l for l in candidates if not l.resharding]
        candidates = not_morphing or candidates
        # ``avoid`` carries the workers a migrating request already failed
        # on. A freshly-killed worker stays in discovery (and in the
        # metrics view) until its lease TTL lapses, and prefix affinity
        # would re-pick the corpse every time — soft-exclude: prefer any
        # other worker, but fall back rather than refuse when the avoid
        # set covers every candidate (lone-worker restarts)
        if avoid:
            preferred = [l for l in candidates if l.worker_id not in avoid]
            candidates = preferred or candidates
        # planner watermarks: workers at capacity stop receiving NEW
        # work while they drain their queues — soft, like ``avoid``,
        # so an all-saturated fleet still serves (the admission gate is
        # the component that actually sheds). A dead planner's last set
        # expires (watermark_ttl_s) instead of skewing routing forever
        if self.watermarked and self.cfg.watermark_ttl_s > 0:
            if (self._watermark_ts is None
                    or self._clock() - self._watermark_ts
                    > self.cfg.watermark_ttl_s):
                self.watermarked = set()
        if self.watermarked:
            preferred = [
                l for l in candidates if l.worker_id not in self.watermarked
            ]
            candidates = preferred or candidates
        # autopilot health directives: quarantined workers (spiking
        # breach rate) and pre-warm holds (cold XLA grid compiling)
        # are soft exclusions with the same last-resort semantics as
        # ``resharding`` — an entirely-unhealthy fleet still serves.
        # A stopped autopilot's last directive expires instead of
        # pinning its view on routing forever.
        if ((self.quarantined or self.prewarm_hold)
                and self.cfg.autopilot_ttl_s > 0):
            if (self._autopilot_ts is None
                    or self._clock() - self._autopilot_ts
                    > self.cfg.autopilot_ttl_s):
                self.quarantined = set()
                self.prewarm_hold = set()
        for excluded in (self.quarantined, self.prewarm_hold):
            if excluded:
                preferred = [
                    l for l in candidates if l.worker_id not in excluded
                ]
                candidates = preferred or candidates

        best_id = None
        self.last_predicted_ttft_ms = None
        if self.cfg.cost_model:
            # transfer-cost-aware placement: every candidate must be
            # calibration-ready (predict returns None when cold) — a
            # single cold candidate falls the WHOLE decision back to
            # overlap scoring, because the two score scales (predicted
            # milliseconds vs the normalized overlap cost) aren't
            # comparable within one argmin
            preds = []
            for l in candidates:
                peer = self._deepest_peer(endpoints, overlaps, l.worker_id)
                p = predict_worker_ttft_ms(
                    l, overlaps, isl_blocks,
                    pending=self._pending.get(l.worker_id, 0),
                    min_obs=self.cfg.cost_min_obs,
                    peer_slice_fp=peer.slice_fp if peer else "",
                    # pull legs move bytes at the SERVING peer's codec
                    # width (it ships its stored form), not this
                    # candidate's
                    peer_wire_bytes=(
                        peer.wire_bytes_per_block if peer else 0
                    ),
                )
                if p is None:
                    preds = None
                    break
                if self.cfg.tail_aware:
                    # tail-aware routing: no candidate may score better
                    # than its own windowed measured tail — the mean-
                    # built model hides a bimodal worker's p99
                    adjusted = tail_adjusted_ttft_ms(
                        p, self.tails.tail_ms(l.worker_id)
                    )
                    if adjusted > p:
                        self.route_tail_overrides += 1
                    p = adjusted
                preds.append((p, l.worker_id))
            if preds:
                # ties (identical candidates, or a model with barely
                # enough observations) break on the EXISTING overlap
                # score then worker id — never on float-sum iteration
                # order, which flaps routing between scrapes
                p, best_id = min(
                    preds,
                    key=lambda t: (
                        t[0], -overlaps.scores.get(t[1], 0), t[1]
                    ),
                )
                self.last_predicted_ttft_ms = p
                self.route_cost_decisions += 1

        if best_id is None:
            balance_mode = endpoints.load_std > self.cfg.balance_threshold
            alpha = (
                self.cfg.balance_alpha if balance_mode
                else self.cfg.overlap_alpha
            )
            avg = endpoints.load_avg

            def legacy_cost(l: WorkerLoad) -> float:
                overlap = overlaps.scores.get(l.worker_id, 0)
                norm_new = max(isl_blocks - overlap, 0) / max(isl_blocks, 1)
                pending = self._pending.get(l.worker_id, 0)
                req_ratio = (
                    (l.active_requests + pending) / max(l.total_slots, 1)
                )
                return (
                    alpha * (l.kv_usage - avg)
                    + (1 - alpha) * norm_new
                    + self.cfg.gamma * req_ratio
                )

            # same deterministic tie-break as the cost mode: equal-cost
            # candidates (identical loads, float-sum ties) must pick the
            # same worker regardless of the loads list's scrape order
            best_id = min(
                candidates,
                key=lambda l: (
                    legacy_cost(l),
                    -overlaps.scores.get(l.worker_id, 0),
                    l.worker_id,
                ),
            ).worker_id
            self.route_overlap_decisions += 1

        self._pending[best_id] = self._pending.get(best_id, 0) + 1
        self._emit_hit_rate(best_id, isl_blocks, overlaps.scores.get(best_id, 0))
        return best_id

    @staticmethod
    def _deepest_peer(
        endpoints: ProcessedEndpoints, overlaps: OverlapScores, worker_id: int
    ) -> Optional[WorkerLoad]:
        """Load of the deepest OTHER chain's worker — the peer a pull
        would come from, so the prediction prices the wire leg at that
        peer's slice (ICI class on a match) and codec width."""
        best_w, best_ov = None, 0
        for w, ov in overlaps.scores.items():
            if w != worker_id and (ov > best_ov or (ov == best_ov and
                                                    best_w is not None
                                                    and w < best_w)):
                best_w, best_ov = w, ov
        if best_w is None:
            return None
        return endpoints.by_id.get(best_w)

    def choose_peer(
        self,
        endpoints: ProcessedEndpoints,
        overlaps: OverlapScores,
        worker_id: int,
        n_hint: int,
    ) -> tuple[Optional[int], int]:
        """Pick the peer a prefetch hint should name: the NEAREST
        adequate peer, not the deepest. Candidates are workers whose
        chain outruns the routed worker's own tiers; with the routed
        worker's calibration in hand each candidate is scored by net
        benefit = prefill saved − predicted pull cost (priced at the
        ICI class when the peer shares the routed worker's slice), so a
        same-slice peer covering the chain beats a deeper peer across
        DCN whenever the extra depth isn't worth the slower wire. Cold
        model (or a pull predicted to cost more than recompute for
        every candidate) falls back to the PR 9 deepest-chain rule.
        Deterministic: ties break on depth then worker id."""
        tier_cov = min(overlaps.scores.get(worker_id, 0), n_hint)
        cands = sorted(
            (w, min(ov, n_hint))
            for w, ov in overlaps.scores.items()
            if w != worker_id and min(ov, n_hint) > tier_cov
        )
        if not cands:
            return None, 0
        load = endpoints.by_id.get(worker_id)
        scored = None
        if (
            self.cfg.cost_model
            and load is not None
            and load.cost_obs >= self.cfg.cost_min_obs
            and load.prefill_tok_s > 0
            and load.block_bytes > 0
            and load.block_size > 0
        ):
            from .costmodel import link_leg_ms, restore_leg_ms

            link_gbps = load.link_gbps or {}
            scored = []
            for w, depth in cands:
                extra = depth - tier_cov
                peer = endpoints.by_id.get(w)
                link = (
                    "ici"
                    if peer is not None and load.slice_fp
                    and peer.slice_fp == load.slice_fp
                    and link_gbps.get("ici")
                    else "peer"
                )
                # the WIRE leg moves bytes at the SERVING PEER's codec
                # width (the peer serves its stored form — a full-width
                # peer ships full-width bytes to a quantized puller, and
                # vice versa), so price the pull with the peer's
                # advertisement; the LANDING leg re-encodes into this
                # worker's own tiers and restores at its width
                peer_bb = (
                    peer.wire_bytes_per_block
                    if peer is not None and peer.wire_bytes_per_block
                    else load.wire_bytes_per_block
                )
                pull = link_leg_ms(
                    link_gbps, load.link_lat_ms, link, extra * peer_bb
                )
                # the pulled chain lands in host staging and still pays
                # the h2d restore leg — same pricing as predict's pull
                # term, or the two would disagree on whether a pull
                # beats recompute
                land = restore_leg_ms(
                    link_gbps, load.link_lat_ms,
                    extra * load.wire_bytes_per_block,
                )
                if pull is None or land is None:
                    scored = None  # cold pull/restore -> deepest fallback
                    break
                saved_ms = extra * load.block_size / load.prefill_tok_s * 1e3
                scored.append((saved_ms - pull - land, depth, w))
        if scored:
            net, depth, w = max(
                scored, key=lambda t: (t[0], t[1], -t[2])
            )
            if net <= 0:
                # every pull costs more than recomputing the blocks —
                # don't name a peer at all (the hint's local restore
                # still fires)
                return None, 0
            return w, depth
        # cold-start / overlap-only: deepest chain, worker id tie-break
        w, depth = max(cands, key=lambda t: (t[1], -t[0]))
        return w, depth

    def set_watermarks(self, saturated_workers) -> None:
        """Planner capacity-watermark update (full replacement — the
        planner republishes the complete set every tick, so a worker
        that cooled off clears automatically; a planner that stops
        publishing ages out via ``watermark_ttl_s``)."""
        self.watermarked = set(saturated_workers or ())
        self._watermark_ts = self._clock()

    def set_autopilot_health(self, quarantined=(), prewarm_hold=()) -> None:
        """Autopilot health-directive update (full replacement, exactly
        like ``set_watermarks``: the controller republishes the whole
        view every tick, so a reinstated worker clears automatically
        and a stopped autopilot ages out via ``autopilot_ttl_s``)."""
        self.quarantined = set(quarantined or ())
        self.prewarm_hold = set(prewarm_hold or ())
        self._autopilot_ts = self._clock()

    def request_finished(self, worker_id: int) -> None:
        """Release the optimistic bump once the request lands/completes."""
        n = self._pending.get(worker_id, 0)
        if n <= 1:
            self._pending.pop(worker_id, None)
        else:
            self._pending[worker_id] = n - 1

    def emit_prefetch(
        self, worker_id: int, blocks: list,
        peer_worker_id: Optional[int] = None, peer_blocks: int = 0,
        model: Optional[str] = None,
    ) -> None:
        """Ship the routed request's block-hash chain to the chosen
        worker as a prefetch hint ((tokens_hash, block_hash) pairs in
        prompt order) — fired when the worker's known device overlap
        doesn't cover the prompt, so the worker can start its host-tier
        h2d upload before the request arrives (engine.prefetch_hint).
        ``peer_worker_id`` names the peer whose radix chain covers the
        prompt deeper than the routed worker's own tiers (to depth
        ``peer_blocks``) — the worker pulls the continuation from that
        peer's host/disk tier over the transfer plane (fleet prefix
        cache). ``model`` names the routed model/adapter so the worker
        can pre-stage its weights (PRESERVE) alongside the KV.
        Best-effort: a lost hint only costs the overlap."""
        if self.drt is None or self._prefetch_subject is None or not blocks:
            return
        capped = blocks[:KV_PREFETCH_MAX_BLOCKS]
        hint = KvPrefetchHint(
            worker_id, [[l, s] for l, s in capped],
            peer_worker_id=peer_worker_id,
            peer_blocks=min(peer_blocks, len(capped)),
            model=model,
        )
        try:
            self.drt.bus.publish(self._prefetch_subject, hint.to_bytes())
            self.prefetch_hints_sent += 1
        except Exception:  # noqa: BLE001
            logger.debug("prefetch-hint publish failed", exc_info=True)

    def _emit_hit_rate(self, worker_id: int, isl_blocks: int, overlap: int) -> None:
        if self.drt is None or self._hit_subject is None:
            return
        try:
            self.drt.bus.publish(
                self._hit_subject,
                KVHitRateEvent(
                    worker_id, isl_blocks, overlap,
                    # -1 = the decision fell back to overlap scoring
                    # (cold start / cost model off) — the metrics
                    # component skips the gauge for those
                    predicted_ttft_ms=(
                        round(self.last_predicted_ttft_ms, 3)
                        if self.last_predicted_ttft_ms is not None else -1.0
                    ),
                ).to_bytes(),
            )
        except Exception:  # noqa: BLE001
            logger.debug("hit-rate publish failed", exc_info=True)

"""KV event schema (re-design of lib/llm/src/kv_router/protocols.rs:19-98).

Two hash kinds, as in the reference:
  * ``tokens_hash`` — content hash of one block's tokens (LocalBlockHash),
  * ``block_hash``  — chained sequence hash (ExternalSequenceBlockHash):
    hash(parent_chain, tokens_hash). The chain hash is position-dependent,
    so equal chains <=> equal full prefixes — the index key.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

KV_EVENT_SUBJECT = "kv_events"
KV_HIT_RATE_SUBJECT = "kv-hit-rate"
KV_PREFETCH_SUBJECT = "kv-prefetch"
#: fleet prefix cache: a routed worker whose local tiers miss asks the
#: peer named in its kv-prefetch hint for the chain's continuation; the
#: peer answers by pushing the blocks over the TCP transfer plane
#: (disagg/transfer.py framing + ack) to the requester's connect-back
#: address — the bus carries only the negotiation, never the KV bytes
KV_PEER_FETCH_SUBJECT = "kv-peer-fetch"

#: hard cap on blocks per prefetch hint: bounds message size and the
#: host->device burst one hint can trigger on the worker
KV_PREFETCH_MAX_BLOCKS = 512


@dataclass
class StoredBlock:
    block_hash: int  # chained
    tokens_hash: int  # local


@dataclass
class KvCacheEvent:
    """Stored (with parent linkage), Removed, or Demoted.

    ``demoted`` = the block left the device cache for the worker's
    offload tiers (host DRAM / disk): the worker still holds the KV —
    the index keeps the residency (that is what makes the fleet one
    prefix cache) but tags it offload-tier so routing can tell a
    device hit from a restorable one. The matching ``removed`` arrives
    only when the block leaves the worker's LAST tier."""

    kind: str  # "stored" | "removed" | "demoted"
    parent_hash: Optional[int] = None
    blocks: list[StoredBlock] = field(default_factory=list)
    block_hashes: list[int] = field(default_factory=list)

    @staticmethod
    def stored(parent_hash: Optional[int], blocks: list[StoredBlock]) -> "KvCacheEvent":
        return KvCacheEvent(kind="stored", parent_hash=parent_hash, blocks=blocks)

    @staticmethod
    def removed(block_hashes: list[int]) -> "KvCacheEvent":
        return KvCacheEvent(kind="removed", block_hashes=block_hashes)

    @staticmethod
    def demoted(block_hashes: list[int]) -> "KvCacheEvent":
        return KvCacheEvent(kind="demoted", block_hashes=block_hashes)


@dataclass
class RouterEvent:
    worker_id: int
    event: KvCacheEvent
    event_id: int = 0

    def to_bytes(self) -> bytes:
        d = {
            "worker_id": self.worker_id,
            "event_id": self.event_id,
            "kind": self.event.kind,
            "parent_hash": self.event.parent_hash,
            "blocks": [[b.block_hash, b.tokens_hash] for b in self.event.blocks],
            "block_hashes": self.event.block_hashes,
        }
        return json.dumps(d).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "RouterEvent":
        d = json.loads(raw)
        return RouterEvent(
            worker_id=d["worker_id"],
            event_id=d.get("event_id", 0),
            event=KvCacheEvent(
                kind=d["kind"],
                parent_hash=d.get("parent_hash"),
                blocks=[StoredBlock(b[0], b[1]) for b in d.get("blocks", [])],
                block_hashes=d.get("block_hashes", []),
            ),
        )


@dataclass
class KvPrefetchHint:
    """Router -> chosen worker, published the moment a request is routed
    to a worker whose device radix match does NOT cover the prompt: the
    prompt's full block-hash chain as (tokens_hash, block_hash) pairs in
    prompt order. The worker probes its own tiers against the chain and
    starts uploading the host-resident continuation BEFORE the request
    itself arrives (PRESERVE, arxiv 2501.08192), so admission claims the
    blocks as ordinary device prefix hits.

    ``peer_worker_id``/``peer_blocks`` (fleet prefix cache): the peer
    whose radix chain covers the prompt deeper than the routed worker's
    own tiers, and to what depth. The worker re-probes its local tiers
    first and, when they fall short, pulls the continuation from the
    peer over the transfer plane (kv-peer-fetch) before the restore.
    Advisory like the rest of the hint — a dead or mistaken peer just
    costs the pull attempt; the request recomputes.

    ``model`` (PRESERVE-style weight prefetch): the routed model or
    adapter name, so the worker can pre-stage its weights alongside
    the KV restore — today's workers resolve it to a stat-counted
    no-op hook (engine.pre_stage_weights), wiring the call path the
    multi-model work lands on warm. Absent on old routers; ignored by
    old workers (tolerant decode both ways)."""

    worker_id: int
    blocks: list  # [[tokens_hash, block_hash], ...] prompt order
    peer_worker_id: Optional[int] = None
    peer_blocks: int = 0
    model: Optional[str] = None

    def to_bytes(self) -> bytes:
        return json.dumps(
            {"worker_id": self.worker_id, "blocks": self.blocks,
             "peer_worker_id": self.peer_worker_id,
             "peer_blocks": self.peer_blocks,
             "model": self.model}
        ).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "KvPrefetchHint":
        d = json.loads(raw)
        peer = d.get("peer_worker_id")
        model = d.get("model")
        return KvPrefetchHint(
            worker_id=d["worker_id"],
            blocks=[[int(a), int(b)] for a, b in d.get("blocks", [])],
            peer_worker_id=int(peer) if peer is not None else None,
            peer_blocks=int(d.get("peer_blocks") or 0),
            model=str(model) if model else None,
        )


@dataclass
class KvPeerFetchRequest:
    """Worker -> peer negotiation for one fleet-tier prefix pull: the
    requested chain (hashes PAST the requester's local coverage, prompt
    order) plus the requester's transfer-plane connect-back address.
    The peer probes its host/disk tiers and pushes the longest
    consecutive resident run as one bulk KV transfer keyed by
    ``request_id`` (or an error delivery on a total miss, so the
    requester doesn't wait out its timeout). The KV bytes never touch
    the bus."""

    peer_worker_id: int  # the peer asked to serve
    src_worker_id: int  # the requester (logging/metrics)
    request_id: str  # transfer-plane correlation id
    hashes: list  # chained block hashes, prompt order
    connection: dict  # requester's KvTransferServer ConnectionInfo
    #: wire-codec capability (disagg/transfer.KV_QUANT_WIRE_VERSION):
    #: the requester accepts int8/fp8 payloads + scale frames and
    #: dequantizes on landing. 0/absent (legacy pullers) makes the
    #: serving peer dequantize its stored blocks to full width first —
    #: the quant/no-quant skew matrix degrades to bytes, never errors.
    accept_quant: int = 0

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "KvPeerFetchRequest":
        d = json.loads(raw)
        return KvPeerFetchRequest(
            peer_worker_id=int(d["peer_worker_id"]),
            src_worker_id=int(d.get("src_worker_id", 0)),
            request_id=str(d["request_id"]),
            hashes=[int(h) for h in d.get("hashes", [])],
            connection=d.get("connection") or {},
            accept_quant=int(d.get("accept_quant") or 0),
        )


@dataclass
class KVHitRateEvent:
    """Emitted per routing decision (ref scheduler.rs:28-32).

    ``predicted_ttft_ms`` carries the cost model's prediction for the
    chosen worker when the decision was cost-aware (-1 = overlap-mode
    fallback), so the metrics component can gauge routing's view of the
    fleet without a second event plane. Version skew: this decoder
    tolerates old events (field defaulted); a pre-field consumer
    decoding a NEW event drops it as a bad event for one upgrade
    window — hit-rate gauges are advisory, nothing routes on them."""

    worker_id: int  # dynlint: disable=dead-wire-field -- identifies the routed worker for operators replaying decision events; the metrics gauges deliberately aggregate fleet-wide
    isl_blocks: int
    overlap_blocks: int
    predicted_ttft_ms: float = -1.0

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "KVHitRateEvent":
        d = json.loads(raw)
        return KVHitRateEvent(
            worker_id=d["worker_id"],
            isl_blocks=d["isl_blocks"],
            overlap_blocks=d["overlap_blocks"],
            predicted_ttft_ms=float(d.get("predicted_ttft_ms", -1.0)),
        )

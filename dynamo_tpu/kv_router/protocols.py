"""KV event schema (re-design of lib/llm/src/kv_router/protocols.rs:19-98).

Two hash kinds, as in the reference:
  * ``tokens_hash`` — content hash of one block's tokens (LocalBlockHash),
  * ``block_hash``  — chained sequence hash (ExternalSequenceBlockHash):
    hash(parent_chain, tokens_hash). The chain hash is position-dependent,
    so equal chains <=> equal full prefixes — the index key.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

KV_EVENT_SUBJECT = "kv_events"
KV_HIT_RATE_SUBJECT = "kv-hit-rate"
KV_PREFETCH_SUBJECT = "kv-prefetch"

#: hard cap on blocks per prefetch hint: bounds message size and the
#: host->device burst one hint can trigger on the worker
KV_PREFETCH_MAX_BLOCKS = 512


@dataclass
class StoredBlock:
    block_hash: int  # chained
    tokens_hash: int  # local


@dataclass
class KvCacheEvent:
    """Stored (with parent linkage) or Removed."""

    kind: str  # "stored" | "removed"
    parent_hash: Optional[int] = None
    blocks: list[StoredBlock] = field(default_factory=list)
    block_hashes: list[int] = field(default_factory=list)

    @staticmethod
    def stored(parent_hash: Optional[int], blocks: list[StoredBlock]) -> "KvCacheEvent":
        return KvCacheEvent(kind="stored", parent_hash=parent_hash, blocks=blocks)

    @staticmethod
    def removed(block_hashes: list[int]) -> "KvCacheEvent":
        return KvCacheEvent(kind="removed", block_hashes=block_hashes)


@dataclass
class RouterEvent:
    worker_id: int
    event: KvCacheEvent
    event_id: int = 0

    def to_bytes(self) -> bytes:
        d = {
            "worker_id": self.worker_id,
            "event_id": self.event_id,
            "kind": self.event.kind,
            "parent_hash": self.event.parent_hash,
            "blocks": [[b.block_hash, b.tokens_hash] for b in self.event.blocks],
            "block_hashes": self.event.block_hashes,
        }
        return json.dumps(d).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "RouterEvent":
        d = json.loads(raw)
        return RouterEvent(
            worker_id=d["worker_id"],
            event_id=d.get("event_id", 0),
            event=KvCacheEvent(
                kind=d["kind"],
                parent_hash=d.get("parent_hash"),
                blocks=[StoredBlock(b[0], b[1]) for b in d.get("blocks", [])],
                block_hashes=d.get("block_hashes", []),
            ),
        )


@dataclass
class KvPrefetchHint:
    """Router -> chosen worker, published the moment a request is routed
    to a worker whose device radix match does NOT cover the prompt: the
    prompt's full block-hash chain as (tokens_hash, block_hash) pairs in
    prompt order. The worker probes its own tiers against the chain and
    starts uploading the host-resident continuation BEFORE the request
    itself arrives (PRESERVE, arxiv 2501.08192), so admission claims the
    blocks as ordinary device prefix hits."""

    worker_id: int
    blocks: list  # [[tokens_hash, block_hash], ...] prompt order

    def to_bytes(self) -> bytes:
        return json.dumps(
            {"worker_id": self.worker_id, "blocks": self.blocks}
        ).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "KvPrefetchHint":
        d = json.loads(raw)
        return KvPrefetchHint(
            worker_id=d["worker_id"],
            blocks=[[int(a), int(b)] for a, b in d.get("blocks", [])],
        )


@dataclass
class KVHitRateEvent:
    """Emitted per routing decision (ref scheduler.rs:28-32)."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "KVHitRateEvent":
        return KVHitRateEvent(**json.loads(raw))

"""Control/request-plane hub: the store + bus served over TCP.

The reference runs etcd (discovery/leases) and NATS (request plane) as
external services (lib/runtime/src/transports/{etcd,nats}.rs). The TPU-VM
deployment model gives us a coordinator host per pod, so this framework
ships its own single-process hub instead of requiring external
infrastructure: :class:`HubServer` exposes a LocalStore + LocalBus over one
TCP port using the two-part codec; :class:`RemoteStore`/:class:`RemoteBus`
are drop-in (awaitable) implementations of the same interfaces, so
``DistributedRuntime`` works identically in-process, multi-process on one
host, or multi-host over DCN.

Wire protocol: two-part frames. header = JSON ``{"op": ..., "id": ...,
**args}``; data = opaque payload bytes (values, messages). Server->client
pushes (watch events, bus messages) carry a subscription id instead of a
request id.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from typing import Any, Optional

from .bus import LocalBus, Message, NoResponders, Subscription
from .codec import TwoPartMessage, read_frame, write_frame
from .store import KeyExists, KvEntry, LocalStore, StoreError, ValidationFailed, Watcher

logger = logging.getLogger(__name__)

_ERRORS = {
    "KeyExists": KeyExists,
    "ValidationFailed": ValidationFailed,
    "NoResponders": NoResponders,
    "StoreError": StoreError,
}


class HubServer:
    """Serve a LocalStore + LocalBus to remote processes."""

    def __init__(
        self,
        store: Optional[LocalStore] = None,
        bus: Optional[LocalBus] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        data_dir: Optional[str] = None,
    ):
        # data_dir makes BOTH planes durable: the store snapshots+WALs
        # its KV/leases (store.py _restore) and the bus WALs its work
        # queues — a hub restart then loses neither discovery state nor
        # queued work (VERDICT r3 weak #4)
        self.store = store or LocalStore(data_dir=data_dir)
        self.bus = bus or LocalBus(data_dir=data_dir)
        self._host, self._port = host, port
        self._server: Optional[asyncio.base_events.Server] = None
        self.address = ""
        self._writers: set[asyncio.StreamWriter] = set()

    async def start(self) -> None:
        self.store.start()
        self._server = await asyncio.start_server(self._handle, self._host, self._port)
        port = self._server.sockets[0].getsockname()[1]
        self.address = f"{self._host}:{port}"

    async def close(self) -> None:
        if self._server:
            self._server.close()
            # drop live client connections — wait_closed() (3.12) blocks
            # until every handler ends, and clients that died without a
            # clean close (killed worker host) would hang it forever;
            # abort() skips the write-buffer drain a dead peer never ACKs
            for w in list(self._writers):
                w.transport.abort()
            await self._server.wait_closed()
            self._server = None
        await self.store.close()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        session = _Session(self, writer)
        self._writers.add(writer)
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                try:
                    head = frame.header_json() or {}
                    session.spawn(session.dispatch(head, frame.data))
                except Exception as e:  # noqa: BLE001
                    logger.warning("hub dispatch error: %s", e)
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            self._writers.discard(writer)
            await session.cleanup()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass  # peer already gone — the fd is released either way


class _Session:
    """Per-connection state on the server: its watchers and
    subscriptions (connection-scoped; torn down on disconnect). Leases
    are NOT revoked on disconnect: liveness is the lease TTL alone — a
    dead client stops keepaliving and expires a TTL later, while a
    RECONNECTING client (hub restart, network blip) resumes keepalives
    on its old lease id with its keys intact (etcd semantics,
    transports/etcd.rs:38; eager revoke would delete a live worker's
    registrations the moment the hub bounced)."""

    def __init__(self, hub: HubServer, writer: asyncio.StreamWriter):
        self.hub = hub
        self.writer = writer
        self.watchers: dict[int, Watcher] = {}
        self.subs: dict[int, Subscription] = {}
        self.tasks: set[asyncio.Task] = set()
        self._wlock = asyncio.Lock()

    async def send(self, head: dict, data: bytes = b"") -> None:
        async with self._wlock:
            # this lock exists to serialize whole frames onto ONE stream
            # (interleaved writes would corrupt the framing) — unlike a
            # state lock, holding it across the write is the point
            await write_frame(  # dynlint: disable=await-in-lock -- frame-serialization lock, guards only this stream
                self.writer, TwoPartMessage(json.dumps(head).encode(), data)
            )

    async def reply(self, req_id: int, result: Any = None, data: bytes = b"") -> None:
        await self.send({"op": "reply", "id": req_id, "result": result}, data)

    async def reply_err(self, req_id: int, err: Exception) -> None:
        await self.send(
            {"op": "reply", "id": req_id, "error": str(err), "etype": type(err).__name__}
        )

    def spawn(self, coro) -> None:
        t = asyncio.get_running_loop().create_task(coro)
        self.tasks.add(t)
        t.add_done_callback(self.tasks.discard)

    async def cleanup(self) -> None:
        for t in self.tasks:
            t.cancel()
        for w in self.watchers.values():
            w.cancel()
        for s in self.subs.values():
            s.unsubscribe()

    async def dispatch(self, head: dict, data: bytes) -> None:
        op = head.get("op", "")
        req_id = head.get("id", 0)
        store, bus = self.hub.store, self.hub.bus
        try:
            # ---- store ops ----
            if op == "grant_lease":
                await self.reply(req_id, store.grant_lease(head["ttl"]))
            elif op == "keep_alive":
                await self.reply(req_id, store.keep_alive(head["lease"]))
            elif op == "revoke_lease":
                store.revoke_lease(head["lease"])
                await self.reply(req_id, True)
            elif op in ("kv_put", "kv_create", "kv_create_or_validate"):
                getattr(store, op)(head["key"], data, head.get("lease", 0))
                await self.reply(req_id, True)
            elif op == "kv_get":
                entry = store.kv_get(head["key"])
                if entry is None:
                    await self.reply(req_id, None)
                else:
                    await self.reply(
                        req_id, {"key": entry.key, "lease": entry.lease_id}, entry.value
                    )
            elif op == "kv_get_prefix":
                entries = store.kv_get_prefix(head["prefix"])
                payload = json.dumps(
                    [
                        {"key": e.key, "lease": e.lease_id, "value": e.value.hex()}
                        for e in entries
                    ]
                ).encode()
                await self.reply(req_id, len(entries), payload)
            elif op == "kv_delete":
                await self.reply(req_id, store.kv_delete(head["key"]))
            elif op == "kv_delete_prefix":
                await self.reply(req_id, store.kv_delete_prefix(head["prefix"]))
            elif op == "watch":
                w = store.watch_prefix(head["prefix"])
                wid = head["watch_id"]
                self.watchers[wid] = w
                snap = json.dumps(
                    [
                        {"key": e.key, "lease": e.lease_id, "value": e.value.hex()}
                        for e in w.snapshot
                    ]
                ).encode()
                await self.reply(req_id, wid, snap)
                self.spawn(self._pump_watch(wid, w))
            elif op == "watch_cancel":
                w = self.watchers.pop(head["watch_id"], None)
                if w:
                    w.cancel()
                await self.reply(req_id, True)
            # ---- bus ops ----
            elif op == "publish":
                n = bus.publish(
                    head["subject"], data, head.get("headers") or {}, head.get("reply")
                )
                await self.reply(req_id, n)
            elif op == "subscribe":
                sub = bus.subscribe(head["subject"], head.get("group"))
                sid = head["sub_id"]
                self.subs[sid] = sub
                await self.reply(req_id, sid)
                self.spawn(self._pump_sub(sid, sub))
            elif op == "unsubscribe":
                sub = self.subs.pop(head["sub_id"], None)
                if sub:
                    sub.unsubscribe()
                await self.reply(req_id, True)
            elif op == "request":
                self.spawn(self._do_request(req_id, head, data))
            elif op == "respond":
                bus.respond(
                    Message(head.get("subject", ""), b"", reply=head["reply"]), data
                )
                await self.reply(req_id, True)
            elif op == "queue_push":
                q = bus.work_queue(head["queue"], head.get("redeliver_after", 30.0))
                await self.reply(req_id, q.push(data))
            elif op == "queue_pop":
                self.spawn(self._do_queue_pop(req_id, head))
            elif op == "queue_ack":
                q = bus.work_queue(head["queue"])
                await self.reply(req_id, q.ack(head["item_id"]))
            elif op == "queue_nack":
                q = bus.work_queue(head["queue"])
                await self.reply(req_id, q.nack(head["item_id"]))
            elif op == "queue_depth":
                await self.reply(req_id, bus.work_queue(head["queue"]).depth)
            elif op == "object_put":
                bus.object_put(head["bucket"], head["name"], data, head.get("ttl"))
                await self.reply(req_id, True)
            elif op == "object_get":
                obj = bus.object_get(head["bucket"], head["name"])
                await self.reply(req_id, obj is not None, obj or b"")
            elif op == "object_list":
                await self.reply(req_id, bus.object_list(head["bucket"]))
            else:
                await self.reply_err(req_id, StoreError(f"unknown op {op!r}"))
        except Exception as e:  # noqa: BLE001
            await self.reply_err(req_id, e)

    async def _do_request(self, req_id: int, head: dict, data: bytes) -> None:
        try:
            result = await self.hub.bus.request(
                head["subject"], data, head.get("timeout", 30.0), head.get("headers") or {}
            )
            await self.reply(req_id, True, result)
        except Exception as e:  # noqa: BLE001
            await self.reply_err(req_id, e)

    async def _do_queue_pop(self, req_id: int, head: dict) -> None:
        try:
            q = self.hub.bus.work_queue(head["queue"], head.get("redeliver_after", 30.0))
            item = await q.pop(head.get("timeout"))
            if item is None:
                await self.reply(req_id, None)
            else:
                await self.reply(
                    req_id, {"item_id": item.id, "deliveries": item.deliveries}, item.payload
                )
        except Exception as e:  # noqa: BLE001
            await self.reply_err(req_id, e)

    async def _pump_watch(self, wid: int, w: Watcher) -> None:
        try:
            async for ev in w:
                await self.send(
                    {"op": "watch_event", "watch_id": wid, "kind": ev.kind.value,
                     "key": ev.key, "lease": ev.lease_id},
                    ev.value,
                )
        except (ConnectionResetError, asyncio.CancelledError):
            pass

    async def _pump_sub(self, sid: int, sub: Subscription) -> None:
        try:
            async for msg in sub:
                await self.send(
                    {"op": "bus_msg", "sub_id": sid, "subject": msg.subject,
                     "headers": msg.headers, "reply": msg.reply},
                    msg.payload,
                )
        except (ConnectionResetError, asyncio.CancelledError):
            pass


class _HubConnection:
    """One TCP connection to the hub, shared by RemoteStore + RemoteBus.

    SURVIVES hub restarts (VERDICT r3 weak #4: a mid-life hub bounce
    used to orphan every watcher/subscription with no re-establishment):
    when the read loop sees the connection drop, a background redial
    loop takes over — new ``call``s queue on the connected-event instead
    of failing — and once the new connection is up the session is
    re-established server-side: every live subscription re-subscribes
    under its old sub id and every watcher re-watches under its old
    watch id, with the fresh snapshot RECONCILED against what the
    watcher had already delivered (missed deletes surface as synthetic
    DELETE events, current keys re-PUT — consumers like ModelWatcher
    apply events idempotently). Requests that were in flight AT the
    drop fail with ConnectionError (their server-side effects are
    unknowable); the durable hub's store revives leases so resumed
    keepalives (LeaseKeeper retries through ConnectionError) keep
    registrations alive across the bounce."""

    def __init__(self, address: str, reconnect: bool = True):
        self.address = address
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watch_queues: dict[int, asyncio.Queue] = {}
        self._sub_queues: dict[int, asyncio.Queue] = {}
        # live session state for re-establishment after a hub bounce
        self._watchers: dict[int, "RemoteWatcher"] = {}
        self._subs: dict[int, tuple[str, Optional[str]]] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._wlock = asyncio.Lock()
        self._bg_tasks: set[asyncio.Task] = set()
        self._reconnect = reconnect
        self._closing = False
        self._connected = asyncio.Event()

    async def connect(self, timeout: float = 15.0) -> None:
        """Dial the hub, retrying connection refusals with backoff until
        ``timeout``: components of one deployment start concurrently, and
        a worker/frontend may reach its dial before the hub process has
        bound its listener (the reference's runtime retries its etcd/NATS
        connects the same way)."""
        await self._dial(timeout)
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())
        self._connected.set()

    async def _dial(self, timeout: float) -> None:
        host, port = self.address.rsplit(":", 1)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        delay = 0.1
        while True:
            try:
                # per-attempt cap: a black-holed address otherwise blocks
                # in the OS connect far past the retry budget
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(host, int(port)),
                    max(deadline - loop.time(), 0.05),
                )
                return
            except (ConnectionRefusedError, OSError, asyncio.TimeoutError):
                if loop.time() >= deadline:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)

    async def close(self) -> None:
        self._closing = True
        # release callers parked on the connected-event (call() re-checks
        # _closing after the wait and raises instead of hanging forever)
        self._connected.set()
        if self._reader_task:
            self._reader_task.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass  # peer already gone — the fd is released either way

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                head = frame.header_json() or {}
                op = head.get("op")
                if op == "reply":
                    fut = self._pending.pop(head.get("id"), None)
                    if fut and not fut.done():
                        if "error" in head:
                            exc = _ERRORS.get(head.get("etype"), StoreError)(
                                head.get("error", "hub error")
                            )
                            fut.set_exception(exc)
                        else:
                            fut.set_result((head.get("result"), frame.data))
                elif op == "watch_event":
                    w = self._watchers.get(head.get("watch_id"))
                    if w is not None:
                        w._track(head.get("kind"), head.get("key"))
                    q = self._watch_queues.get(head.get("watch_id"))
                    if q:
                        q.put_nowait((head, frame.data))
                elif op == "bus_msg":
                    q = self._sub_queues.get(head.get("sub_id"))
                    if q:
                        q.put_nowait((head, frame.data))
        except (ConnectionResetError, asyncio.CancelledError, OSError):
            pass
        finally:
            self._connected.clear()
            # in-flight requests die with the old connection either way
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("hub connection lost"))
            self._pending.clear()
            if self._closing or not self._reconnect:
                for q in self._watch_queues.values():
                    q.put_nowait(None)
                for q in self._sub_queues.values():
                    q.put_nowait(None)
            elif self._reconnect_task is None or self._reconnect_task.done():
                self._reconnect_task = asyncio.get_running_loop().create_task(
                    self._re_establish()
                )

    async def _re_establish(self) -> None:
        """Redial forever (exponential backoff with jitter), then rebuild
        the session; a bounce DURING rebuild just starts the loop over.

        The jitter matters at fleet scale: a hub restart disconnects
        every worker at the same instant, and un-jittered backoff would
        have the whole fleet redial in synchronized waves (thundering
        herd against a half-started listener)."""
        import random as _random

        delay = 0.2
        while not self._closing:
            try:
                await self._dial(timeout=5.0)
            except (OSError, asyncio.TimeoutError):
                await asyncio.sleep(delay * (0.5 + _random.random()))
                delay = min(delay * 2, 5.0)
                continue
            logger.info(
                "hub %s: reconnected; re-establishing session", self.address
            )
            # the read loop must NOT respawn this task while it is the
            # one driving the rebuild — it checks reconnect_task.done()
            self._reader_task = asyncio.get_running_loop().create_task(
                self._read_loop()
            )
            self._connected.set()
            try:
                for sid, (subject, group) in list(self._subs.items()):
                    await self.call(
                        {"op": "subscribe", "subject": subject,
                         "group": group, "sub_id": sid}
                    )
                for wid, w in list(self._watchers.items()):
                    _, snap = await self.call(
                        {"op": "watch", "prefix": w.prefix, "watch_id": wid}
                    )
                    w._reconcile(json.loads(snap))
                return
            except (ConnectionError, OSError) as e:
                logger.warning(
                    "hub session rebuild interrupted (%s); retrying", e
                )
                await asyncio.sleep(delay * (0.5 + _random.random()))

    async def call(self, head: dict, data: bytes = b"") -> tuple[Any, bytes]:
        if not self._connected.is_set() and not self._closing:
            # hub bouncing: queue behind the redial instead of failing
            await self._connected.wait()
        if self._closing:
            raise ConnectionError("hub connection closed")
        req_id = next(self._ids)
        head["id"] = req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._wlock:
            # frame-serialization lock (see _Session.send): held across
            # the write by design so frames never interleave
            await write_frame(  # dynlint: disable=await-in-lock -- frame-serialization lock, guards only this stream
                self._writer, TwoPartMessage(json.dumps(head).encode(), data)
            )
        return await fut

    def call_nowait(self, head: dict, data: bytes = b"") -> asyncio.Task:
        """Fire a call in the background with a strong reference held and
        failures logged (asyncio keeps only weak refs to bare tasks)."""
        task = asyncio.get_running_loop().create_task(self.call(head, data))
        self._bg_tasks.add(task)

        def _done(t: asyncio.Task) -> None:
            self._bg_tasks.discard(t)
            if not t.cancelled() and t.exception() is not None:
                logger.warning("hub %s failed: %s", head.get("op"), t.exception())

        task.add_done_callback(_done)
        return task


class RemoteWatcher:
    def __init__(self, conn: _HubConnection, wid: int, prefix: str, snapshot: list[KvEntry]):
        self._conn = conn
        self._wid = wid
        self.prefix = prefix
        self.snapshot = snapshot
        self._queue: asyncio.Queue = asyncio.Queue()
        # keys this watcher currently believes exist — the baseline the
        # post-reconnect snapshot reconciles against
        self._seen: set[str] = {e.key for e in snapshot}
        conn._watch_queues[wid] = self._queue
        conn._watchers[wid] = self

    def _track(self, kind: str, key: str) -> None:
        (self._seen.add if kind == "put" else self._seen.discard)(key)

    def _reconcile(self, snap: list[dict]) -> None:
        """Feed the post-reconnect snapshot as synthetic events: keys
        that vanished while disconnected become DELETEs, current keys
        re-PUT (consumers apply watch events idempotently — discovery
        overwrites by key)."""
        current = {d["key"] for d in snap}
        for key in sorted(self._seen - current):
            self._queue.put_nowait(
                ({"kind": "delete", "key": key, "lease": 0}, b"")
            )
        for d in snap:
            self._queue.put_nowait(
                ({"kind": "put", "key": d["key"], "lease": d.get("lease", 0)},
                 bytes.fromhex(d["value"]))
            )
        self._seen = current
        # reconcile done: surface a watch_resumed marker so dependents
        # with state DERIVED from the event stream (instance lists,
        # model registries) know a gap just closed and can re-list —
        # before this, a consumer that missed the window could sit on
        # silently-stale state until the next organic event
        self._queue.put_nowait(
            ({"kind": "resumed", "key": self.prefix, "lease": 0}, b"")
        )

    def cancel(self) -> None:
        self._conn._watch_queues.pop(self._wid, None)
        self._conn._watchers.pop(self._wid, None)
        self._queue.put_nowait(None)

    def __aiter__(self):
        return self

    async def __anext__(self):
        from .store import EventKind, WatchEvent

        item = await self._queue.get()
        if item is None:
            raise StopAsyncIteration
        head, data = item
        return WatchEvent(EventKind(head["kind"]), head["key"], data, head.get("lease", 0))


class RemoteSubscription:
    def __init__(self, conn: _HubConnection, sid: int, subject: str, group):
        self._conn = conn
        self._sid = sid
        self.subject = subject
        self.group = group
        self._queue: asyncio.Queue = asyncio.Queue()
        conn._sub_queues[sid] = self._queue
        conn._subs[sid] = (subject, group)

    def unsubscribe(self) -> None:
        self._conn._sub_queues.pop(self._sid, None)
        self._conn._subs.pop(self._sid, None)
        self._queue.put_nowait(None)
        self._conn.call_nowait({"op": "unsubscribe", "sub_id": self._sid})

    async def next(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            item = await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None
        if item is None:
            return None
        return self._to_msg(item)

    @staticmethod
    def _to_msg(item) -> Message:
        head, data = item
        return Message(head["subject"], data, head.get("headers") or {}, head.get("reply"))

    def __aiter__(self):
        return self

    async def __anext__(self) -> Message:
        item = await self._queue.get()
        if item is None:
            raise StopAsyncIteration
        return self._to_msg(item)


class RemoteStore:
    """Store interface over the hub connection (awaitable variants)."""

    def __init__(self, conn: _HubConnection):
        self._conn = conn
        self._ids = itertools.count(1)

    def start(self) -> None:  # parity with LocalStore
        pass

    async def close(self) -> None:
        pass

    async def grant_lease(self, ttl: float) -> int:
        result, _ = await self._conn.call({"op": "grant_lease", "ttl": ttl})
        return result

    async def keep_alive(self, lease_id: int) -> bool:
        result, _ = await self._conn.call({"op": "keep_alive", "lease": lease_id})
        return bool(result)

    async def revoke_lease(self, lease_id: int) -> None:
        await self._conn.call({"op": "revoke_lease", "lease": lease_id})

    async def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._conn.call({"op": "kv_put", "key": key, "lease": lease_id}, value)

    async def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._conn.call({"op": "kv_create", "key": key, "lease": lease_id}, value)

    async def kv_create_or_validate(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._conn.call(
            {"op": "kv_create_or_validate", "key": key, "lease": lease_id}, value
        )

    async def kv_get(self, key: str) -> Optional[KvEntry]:
        result, data = await self._conn.call({"op": "kv_get", "key": key})
        if result is None:
            return None
        return KvEntry(result["key"], data, result.get("lease", 0))

    async def kv_get_prefix(self, prefix: str) -> list[KvEntry]:
        _, data = await self._conn.call({"op": "kv_get_prefix", "prefix": prefix})
        return [
            KvEntry(d["key"], bytes.fromhex(d["value"]), d.get("lease", 0))
            for d in json.loads(data)
        ]

    async def kv_delete(self, key: str) -> bool:
        result, _ = await self._conn.call({"op": "kv_delete", "key": key})
        return bool(result)

    async def kv_delete_prefix(self, prefix: str) -> int:
        result, _ = await self._conn.call({"op": "kv_delete_prefix", "prefix": prefix})
        return int(result)

    async def watch_prefix(self, prefix: str) -> RemoteWatcher:
        wid = next(self._ids)
        _, snap = await self._conn.call({"op": "watch", "prefix": prefix, "watch_id": wid})
        snapshot = [
            KvEntry(d["key"], bytes.fromhex(d["value"]), d.get("lease", 0))
            for d in json.loads(snap)
        ]
        return RemoteWatcher(self._conn, wid, prefix, snapshot)


class RemoteBus:
    """Bus interface over the hub connection."""

    def __init__(self, conn: _HubConnection):
        self._conn = conn
        self._ids = itertools.count(1)

    def subscribe(self, subject: str, group: Optional[str] = None) -> RemoteSubscription:
        sid = next(self._ids)
        sub = RemoteSubscription(self._conn, sid, subject, group)
        # `ready` lets callers (Endpoint.serve) await subscription
        # confirmation before advertising themselves in discovery
        sub.ready = self._conn.call_nowait(
            {"op": "subscribe", "subject": subject, "group": group, "sub_id": sid}
        )
        return sub

    def publish(
        self,
        subject: str,
        payload: bytes,
        headers: Optional[dict] = None,
        reply: Optional[str] = None,
    ) -> None:
        self._conn.call_nowait(
            {"op": "publish", "subject": subject, "headers": headers, "reply": reply},
            payload,
        )

    async def request(
        self,
        subject: str,
        payload: bytes,
        timeout: float = 30.0,
        headers: Optional[dict] = None,
    ) -> bytes:
        _, data = await self._conn.call(
            {"op": "request", "subject": subject, "timeout": timeout, "headers": headers},
            payload,
        )
        return data

    def respond(self, msg: Message, payload: bytes) -> None:
        if not msg.reply:
            return
        self._conn.call_nowait({"op": "respond", "reply": msg.reply}, payload)

    def work_queue(self, name: str, redeliver_after: float = 30.0) -> "RemoteWorkQueue":
        return RemoteWorkQueue(self._conn, name, redeliver_after)

    async def object_put(
        self, bucket: str, name: str, data: bytes, ttl: Optional[float] = None
    ) -> None:
        await self._conn.call(
            {"op": "object_put", "bucket": bucket, "name": name, "ttl": ttl}, data
        )

    async def object_get(self, bucket: str, name: str) -> Optional[bytes]:
        found, data = await self._conn.call(
            {"op": "object_get", "bucket": bucket, "name": name}
        )
        return data if found else None

    async def object_list(self, bucket: str) -> list[str]:
        result, _ = await self._conn.call({"op": "object_list", "bucket": bucket})
        return result


class RemoteWorkQueue:
    def __init__(self, conn: _HubConnection, name: str, redeliver_after: float):
        self._conn = conn
        self.name = name
        self.redeliver_after = redeliver_after

    async def push(self, payload: bytes) -> int:
        result, _ = await self._conn.call(
            {"op": "queue_push", "queue": self.name,
             "redeliver_after": self.redeliver_after},
            payload,
        )
        return result

    async def pop(self, timeout: Optional[float] = None):
        from .bus import QueueItem

        result, data = await self._conn.call(
            {"op": "queue_pop", "queue": self.name, "timeout": timeout,
             "redeliver_after": self.redeliver_after}
        )
        if result is None:
            return None
        return QueueItem(result["item_id"], data, result["deliveries"])

    async def ack(self, item_id: int) -> bool:
        result, _ = await self._conn.call(
            {"op": "queue_ack", "queue": self.name, "item_id": item_id}
        )
        return bool(result)

    async def nack(self, item_id: int) -> bool:
        result, _ = await self._conn.call(
            {"op": "queue_nack", "queue": self.name, "item_id": item_id}
        )
        return bool(result)

    async def depth(self) -> int:
        result, _ = await self._conn.call({"op": "queue_depth", "queue": self.name})
        return int(result)


async def connect_hub(address: str) -> tuple[RemoteStore, RemoteBus, _HubConnection]:
    """Connect to a hub; returns (store, bus, connection)."""
    conn = _HubConnection(address)
    await conn.connect()
    return RemoteStore(conn), RemoteBus(conn), conn

"""Typed streaming pipeline graph.

Re-design of the reference's pipeline nodes
(lib/runtime/src/pipeline/nodes.rs:72-210): serving stacks are composed of
stages linked frontend -> ... -> backend where a bidirectional *operator*
(e.g. the preprocessor) transforms the request on the forward edge and the
response stream on the backward edge, in one object, so paired state (like a
request's sampling options needed during post-processing) lives in one
place.

In this asyncio design an operator is simply::

    class Op(Operator[In, Out, RespIn, RespOut]):
        async def generate(self, request: Context[In], next: AsyncEngine[Out, RespIn])
            -> AsyncIterator[RespOut]

i.e. forward transformation, call into the next stage, and backward
transformation are one async generator — the natural Python shape of the
reference's forward_edge/backward_edge pair.
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Generic, TypeVar

from .engine import AsyncEngine, Context

In = TypeVar("In")
Out = TypeVar("Out")
RIn = TypeVar("RIn")
ROut = TypeVar("ROut")


class Operator(abc.ABC, Generic[In, Out, RIn, ROut]):
    """A bidirectional pipeline stage (ref: nodes.rs:122-210 Operator)."""

    @abc.abstractmethod
    def generate(
        self, request: Context[In], next_engine: AsyncEngine[Out, RIn]
    ) -> AsyncIterator[ROut]:
        ...


class _LinkedEngine(AsyncEngine[In, ROut]):
    def __init__(self, op: Operator[In, Out, RIn, ROut], next_engine: AsyncEngine[Out, RIn]):
        self._op = op
        self._next = next_engine

    def generate(self, request: Context[In]) -> AsyncIterator[ROut]:
        return self._op.generate(request, self._next)

    async def close(self) -> None:
        await self._next.close()


def link(*stages: Any) -> AsyncEngine:
    """Compose ``link(op1, op2, ..., engine)`` into one AsyncEngine.

    The last element must be an AsyncEngine (the backend / ServiceBackend);
    the rest must be Operators. Mirrors the reference's link chain
    (launch/dynamo-run/src/input/http.rs:85-101)::

        frontend -> preprocessor.fwd -> backend.fwd -> engine
                 <- preprocessor.bwd <- backend.bwd <-
    """
    if not stages:
        raise ValueError("link() requires at least one engine")
    engine = stages[-1]
    if not isinstance(engine, AsyncEngine):
        raise TypeError(f"last stage must be an AsyncEngine, got {type(engine)}")
    for op in reversed(stages[:-1]):
        if not isinstance(op, Operator):
            raise TypeError(f"intermediate stages must be Operators, got {type(op)}")
        engine = _LinkedEngine(op, engine)
    return engine


class MapOperator(Operator[In, Out, RIn, ROut]):
    """Stateless operator from a request fn and a response fn."""

    def __init__(self, fwd, bwd=None):
        self._fwd = fwd
        self._bwd = bwd

    async def generate(self, request: Context[In], next_engine: AsyncEngine) -> AsyncIterator:
        mapped = request.map(self._fwd)
        async for resp in next_engine.generate(mapped):
            yield self._bwd(resp) if self._bwd else resp

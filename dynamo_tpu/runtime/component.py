"""Component model: Namespace -> Component -> Endpoint, discovery, clients.

Re-design of the reference's component layer (lib/runtime/src/component.rs,
component/{endpoint,client,service}.rs):

  * store key scheme   ``{ns}/components/{comp}/{endpoint}:{lease_id:x}``
    (ref component.rs:142,234-244) — bound to the worker's primary lease so
    dead workers vanish from discovery automatically,
  * bus subject scheme ``{ns}.{comp}.{endpoint}-{lease_id:x}``
    (ref component.rs:246-257),
  * ``Endpoint.serve(engine)`` = the ingress: subscribe the subject, decode
    the request envelope, run the engine, connect back over TCP and stream
    (ref pipeline/network/ingress/push_endpoint.rs:23-85),
  * ``Client`` = the egress: watch the discovery prefix, keep a live
    instance list, route round_robin/random/direct, push the request and
    await the connect-back stream
    (ref component/client.rs + pipeline/network/egress/push.rs:62-175).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random as _random
import re
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Awaitable, Callable, Optional

from .annotated import Annotated
from .bus import Message, NoResponders
from .engine import AsyncEngine, AsyncEngineContext, Context
from .store import EventKind
from .tcp import ConnectionInfo, connect_response_stream
from .. import tracing

logger = logging.getLogger(__name__)

_SLUG_RE = re.compile(r"[^a-zA-Z0-9_-]+")


def slug(name: str) -> str:
    """Sanitize a name for bus subjects (ref slug.rs)."""
    return _SLUG_RE.sub("_", name)


@dataclass
class EndpointInfo:
    """Discovery record for one live endpoint instance
    (ref ComponentEndpointInfo, component/endpoint.rs:113-137)."""

    namespace: str
    component: str
    endpoint: str
    lease_id: int
    subject: str
    transport: str = "bus+tcp"

    @property
    def instance_id(self) -> int:
        return self.lease_id

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(raw: bytes) -> "EndpointInfo":
        d = json.loads(raw)
        # ignore unknown keys: a newer peer may advertise fields this
        # process doesn't know yet (version-skew safety)
        fields = EndpointInfo.__dataclass_fields__
        return EndpointInfo(**{k: v for k, v in d.items() if k in fields})


class Namespace:
    def __init__(self, drt, name: str):
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self.drt, self.name, name)


class Component:
    def __init__(self, drt, namespace: str, name: str):
        self.drt = drt
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.drt, self.namespace, self.name, name)

    @property
    def etcd_root(self) -> str:
        return f"{self.namespace}/components/{self.name}"

    def event_subject(self, event: str) -> str:
        """Subject for component-scoped event planes, e.g. kv_events
        (ref kv_router.rs:41)."""
        return f"{slug(self.namespace)}.{slug(self.name)}.{event}"

    async def scrape_stats(
        self, timeout: float = 1.0, include_missing: bool = False
    ) -> list[dict]:
        """Collect per-instance stats from every live instance of every
        endpoint of this component (ref $SRV stats scrape, component.rs:171).

        With ``include_missing``, an instance that is still discovered but
        missed the reply window (event loop starved on a loaded box) is
        reported with ``data=None`` instead of silently dropped, so callers
        holding a last-known snapshot can tell "slow" from "departed"."""
        entries = self.drt.store.kv_get_prefix(self.etcd_root + "/")
        if asyncio.iscoroutine(entries):
            entries = await entries
        out = []
        for e in entries:
            info = EndpointInfo.from_json(e.value)
            try:
                raw = await self.drt.bus.request(
                    info.subject + ".stats", b"{}", timeout=timeout
                )
                stats = json.loads(raw) if raw else {}
            except NoResponders:
                continue  # instance mid-departure; expected churn
            except asyncio.TimeoutError:
                if include_missing:
                    out.append(
                        {
                            "endpoint": info.endpoint,
                            "instance_id": info.instance_id,
                            "data": None,
                        }
                    )
                continue
            except Exception:  # noqa: BLE001
                logger.exception("bad stats from %s", info.subject)
                continue
            out.append(
                {
                    "endpoint": info.endpoint,
                    "instance_id": info.instance_id,
                    "data": stats,
                }
            )
        return out


@dataclass
class RequestEnvelope:
    """What rides the bus from caller to worker
    (ref RequestControlMessage, egress/push.rs:88-130)."""

    request_id: str
    connection_info: Optional[dict]
    payload: Any
    annotations: dict = field(default_factory=dict)
    # W3C traceparent carrying the caller's trace across the bus hop
    # (absent when tracing is off; decoders must tolerate unknown keys)
    trace: Optional[str] = None

    def to_bytes(self) -> bytes:
        d = {
            "request_id": self.request_id,
            "connection_info": self.connection_info,
            "payload": self.payload,
            "annotations": self.annotations,
        }
        if self.trace is not None:
            d["trace"] = self.trace
        return json.dumps(d).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "RequestEnvelope":
        d = json.loads(raw)
        return RequestEnvelope(
            d["request_id"], d.get("connection_info"), d.get("payload"),
            d.get("annotations", {}), d.get("trace"),
        )


StatsHandler = Callable[[], dict]


class Endpoint:
    def __init__(self, drt, namespace: str, component: str, name: str):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.name = name
        self._serving = False
        self._stats_handler: Optional[StatsHandler] = None
        self._inflight: dict[str, AsyncEngineContext] = {}

    # ---- naming ----
    @property
    def etcd_key(self) -> str:
        return (
            f"{self.namespace}/components/{self.component}/"
            f"{self.name}:{self.drt.primary_lease_id:x}"
        )

    @property
    def discovery_prefix(self) -> str:
        return f"{self.namespace}/components/{self.component}/{self.name}:"

    @property
    def subject(self) -> str:
        return (
            f"{slug(self.namespace)}.{slug(self.component)}."
            f"{slug(self.name)}-{self.drt.primary_lease_id:x}"
        )

    # ---- ingress (worker side) ----
    async def serve(
        self,
        engine: AsyncEngine,
        stats_handler: Optional[StatsHandler] = None,
    ) -> "ServeHandle":
        """Register this endpoint: subscribe its unique subject, advertise in
        the store under the primary lease, handle requests by running the
        engine and streaming responses over the TCP connect-back plane."""
        if self._serving:
            raise RuntimeError(f"endpoint {self.subject} already serving")
        self._serving = True
        self._stats_handler = stats_handler

        bus = self.drt.bus
        sub = bus.subscribe(self.subject, group="workers")
        stats_sub = bus.subscribe(self.subject + ".stats", group="workers")
        # remote bus: wait until subscriptions are confirmed before
        # advertising in discovery, or early requests would hit NoResponders
        for s in (sub, stats_sub):
            ready = getattr(s, "ready", None)
            if ready is not None:
                await ready

        info = EndpointInfo(
            namespace=self.namespace,
            component=self.component,
            endpoint=self.name,
            lease_id=self.drt.primary_lease_id,
            subject=self.subject,
        )
        handle = ServeHandle(self, sub, stats_sub)
        self.drt.runtime.spawn(self._serve_loop(engine, sub), name=f"serve:{self.subject}")
        self.drt.runtime.spawn(self._stats_loop(stats_sub), name=f"stats:{self.subject}")
        created = self.drt.store.kv_create(
            self.etcd_key, info.to_json(), lease_id=self.drt.primary_lease_id
        )
        if asyncio.iscoroutine(created):
            await created
        return handle

    async def _serve_loop(self, engine: AsyncEngine, sub) -> None:
        async for msg in sub:
            self.drt.runtime.spawn(self._handle_request(engine, msg))

    async def _stats_loop(self, sub) -> None:
        async for msg in sub:
            stats = {}
            if self._stats_handler is not None:
                try:
                    stats = self._stats_handler()
                except Exception as e:  # noqa: BLE001
                    stats = {"error": str(e)}
            self.drt.bus.respond(msg, json.dumps(stats).encode())

    async def _handle_request(self, engine: AsyncEngine, msg: Message) -> None:
        """Ingress push handler (ref ingress/push_handler.rs)."""
        writer = None
        env = None
        handle_span = tracing.NULL_SPAN
        trace_token = None
        try:
            env = RequestEnvelope.from_bytes(msg.payload)
            context = AsyncEngineContext(env.request_id)
            self._inflight[env.request_id] = context
            self.drt.bus.respond(msg, b'{"ack":true}')
            request = Context(env.payload, context, env.annotations)
            if tracing.enabled():
                # continue the caller's trace across the bus hop; this
                # task's contextvar scopes the whole engine run, so every
                # downstream span (engine, disagg) joins the same trace
                tc = tracing.TraceContext.for_request(env.request_id, env.trace)
                trace_token = tracing.set_trace(tc)
                handle_span = tracing.span(
                    "worker.handle", request_id=env.request_id,
                    endpoint=self.subject,
                )
            if env.connection_info is not None:
                info = ConnectionInfo.from_dict(env.connection_info)
                writer = await connect_response_stream(info, context)
                try:
                    async for item in engine.generate(request):
                        if not isinstance(item, Annotated):
                            item = Annotated.from_data(item)
                        await writer.send(item)
                        if context.is_killed():
                            break
                except asyncio.CancelledError:
                    # worker shutdown with this stream mid-flight: the
                    # finally's bare sentinel would hand the caller a
                    # clean-looking TRUNCATED stream (the lost-stream
                    # bug tests/test_soak_churn.py hunts) — tell the
                    # caller the truth first, then propagate
                    try:
                        await writer.error(
                            "worker shutdown: stream aborted")
                    except Exception:  # noqa: BLE001 - socket may be gone
                        logger.debug(
                            "abort notice lost (socket gone)", exc_info=True
                        )
                    raise
                except Exception as e:  # noqa: BLE001
                    logger.exception("engine error for %s", env.request_id)
                    await writer.error(str(e))
            else:
                # fire-and-forget (no response plane requested)
                async for _ in engine.generate(request):
                    pass
        except Exception as e:  # noqa: BLE001
            logger.exception("ingress failure: %s", e)
        finally:
            handle_span.end()
            if trace_token is not None:
                tracing.reset_trace(trace_token)
            if writer is not None:
                # ResponseWriter.close() is async and awaits the
                # transport's wait_closed() itself (runtime/tcp.py)
                await writer.close()  # dynlint: disable=writer-wait-closed -- ResponseWriter.close() waits internally
            if env is not None:
                self._inflight.pop(env.request_id, None)

    # ---- client ----
    def client(self) -> "Client":
        return Client(self)


class ServeHandle:
    def __init__(self, endpoint: Endpoint, sub, stats_sub):
        self._endpoint = endpoint
        self._subs = [sub, stats_sub]

    @property
    def endpoint(self) -> Endpoint:
        return self._endpoint

    def inflight_count(self) -> int:
        """Requests this endpoint's ingress is currently streaming."""
        return len(self._endpoint._inflight)

    async def deregister(self) -> None:
        """Graceful-drain step 1 (resilience/drain.py): delete the
        discovery key so routers stop picking this instance, while the
        subject subscription stays live — in-flight streams keep their
        control plane and racing requests still get an honest bounce
        from the draining engine instead of NoResponders."""
        ep = self._endpoint
        deleted = ep.drt.store.kv_delete(ep.etcd_key)
        if asyncio.iscoroutine(deleted):
            await deleted

    async def stop(self) -> None:
        ep = self._endpoint
        deleted = ep.drt.store.kv_delete(ep.etcd_key)
        if asyncio.iscoroutine(deleted):
            await deleted
        for s in self._subs:
            s.unsubscribe()
        ep._serving = False


class Client:
    """Discovery-driven client for one endpoint (ref component/client.rs)."""

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint
        self.drt = endpoint.drt
        self._instances: dict[int, EndpointInfo] = {}
        self._rr = 0
        self._watch_task: Optional[asyncio.Task] = None
        self._instances_changed = asyncio.Event()
        self._started = False

    async def start(self) -> "Client":
        if self._started:
            return self
        self._started = True
        watcher = self.drt.store.watch_prefix(self.endpoint.discovery_prefix)
        if asyncio.iscoroutine(watcher):
            watcher = await watcher
        self._watcher = watcher
        for entry in watcher.snapshot:
            info = EndpointInfo.from_json(entry.value)
            self._instances[info.instance_id] = info
        if self._instances:
            self._instances_changed.set()
        self._watch_task = self.drt.runtime.spawn(self._watch(watcher))
        return self

    async def _watch(self, watcher) -> None:
        async for ev in watcher:
            if ev.kind == EventKind.PUT:
                info = EndpointInfo.from_json(ev.value)
                self._instances[info.instance_id] = info
            elif ev.kind == EventKind.RESUMED:
                # post-reconnect reconcile finished (hub.py): the missed
                # deletes/puts were replayed just above, so the instance
                # map is consistent again — wake any parked waiters
                pass
            else:
                # key format ...{endpoint}:{lease:x}
                try:
                    lease_hex = ev.key.rsplit(":", 1)[1]
                    self._instances.pop(int(lease_hex, 16), None)
                except (IndexError, ValueError):
                    pass
            self._instances_changed.set()

    def stop(self) -> None:
        """Tear down the discovery watch (watcher + task)."""
        if getattr(self, "_watcher", None) is not None:
            self._watcher.cancel()
            self._watcher = None
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        self._started = False

    def instance_ids(self) -> list[int]:
        return sorted(self._instances)

    async def wait_for_instances(self, timeout: float = 30.0) -> list[int]:
        deadline = asyncio.get_running_loop().time() + timeout
        while not self._instances:
            if asyncio.get_running_loop().time() >= deadline:
                raise TimeoutError(
                    f"no instances for {self.endpoint.discovery_prefix} after {timeout}s"
                )
            self._instances_changed.clear()
            try:
                await asyncio.wait_for(self._instances_changed.wait(), 0.05)
            except asyncio.TimeoutError:
                pass
        return self.instance_ids()

    # ---- routing policies (ref client.rs:181-244) ----
    def _pick_round_robin(self) -> EndpointInfo:
        ids = self.instance_ids()
        if not ids:
            raise NoResponders(self.endpoint.discovery_prefix)
        info = self._instances[ids[self._rr % len(ids)]]
        self._rr += 1
        return info

    def _pick_random(self) -> EndpointInfo:
        ids = self.instance_ids()
        if not ids:
            raise NoResponders(self.endpoint.discovery_prefix)
        return self._instances[_random.choice(ids)]

    def _pick_direct(self, instance_id: int) -> EndpointInfo:
        info = self._instances.get(instance_id)
        if info is None:
            raise NoResponders(f"instance {instance_id:x} not found")
        return info

    # ---- egress (ref egress/push.rs AddressedPushRouter) ----
    async def generate(
        self,
        request: Context,
        instance_id: Optional[int] = None,
        policy: str = "round_robin",
    ) -> AsyncIterator[Annotated]:
        if instance_id is not None:
            info = self._pick_direct(instance_id)
        elif policy == "random":
            info = self._pick_random()
        else:
            info = self._pick_round_robin()
        return await self._push(info, request)

    async def direct(self, request: Context, instance_id: int) -> AsyncIterator[Annotated]:
        return await self.generate(request, instance_id=instance_id)

    async def round_robin(self, request: Context) -> AsyncIterator[Annotated]:
        return await self.generate(request, policy="round_robin")

    async def random(self, request: Context) -> AsyncIterator[Annotated]:
        return await self.generate(request, policy="random")

    async def _push(self, info: EndpointInfo, request: Context) -> AsyncIterator[Annotated]:
        tcp = await self.drt.tcp_server()
        conn = tcp.register(request.context)
        env = RequestEnvelope(
            request_id=request.id,
            connection_info=conn.to_dict(),
            payload=request.data,
            annotations=request.annotations,
            trace=tracing.current_traceparent(),
        )
        try:
            await self.drt.bus.request(info.subject, env.to_bytes(), timeout=10.0)
        except Exception:
            tcp.unregister(conn)
            raise
        return tcp.stream(conn)


class EngineClient(AsyncEngine):
    """Adapter presenting a remote Client as a local AsyncEngine, so remote
    endpoints compose into pipelines transparently (ref dyn:// engines)."""

    def __init__(self, client: Client, policy: str = "round_robin"):
        self._client = client
        self._policy = policy

    async def generate(self, request: Context) -> AsyncIterator[Annotated]:
        stream = await self._client.generate(request, policy=self._policy)
        async for item in stream:
            yield item

"""Request-plane message bus: subjects, queue groups, work queues, objects.

TPU-native re-design of the reference's NATS layer
(lib/runtime/src/transports/nats.rs + JetStream uses): the serving stack
needs four messaging shapes, all provided here behind one interface:

  * **publish/subscribe** on hierarchical subjects — KV events, hit-rate
    events (ref kv_router.rs:41 ``kv_events`` subject),
  * **request/reply to a queue group** — the addressed request plane: each
    worker endpoint subscribes its unique subject; the router publishes a
    request envelope and gets an ack (the real response rides the TCP
    response plane, see tcp.py),
  * **durable work queue** with pull + ack + redelivery — the prefill queue
    (ref examples/llm/utils/nats_queue.py:27-142),
  * **object store** buckets with TTL — model deployment cards
    (ref model_card/model.rs:42-49).

:class:`LocalBus` is the in-process implementation and the state machine
behind the TCP hub server (hub.py).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Awaitable, Callable, Optional


class BusError(Exception):
    pass


class NoResponders(BusError):
    """No subscriber on the requested subject (ref NATS no-responders)."""


@dataclass
class Message:
    subject: str
    payload: bytes
    headers: dict[str, str] = field(default_factory=dict)
    reply: Optional[str] = None


@dataclass
class QueueItem:
    id: int
    payload: bytes
    deliveries: int = 0


class Subscription:
    def __init__(self, bus: "LocalBus", subject: str, group: Optional[str]):
        self.subject = subject
        self.group = group
        self._queue: asyncio.Queue[Optional[Message]] = asyncio.Queue()
        self._bus = bus

    def _push(self, msg: Message) -> None:
        self._queue.put_nowait(msg)

    async def next(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            msg = await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None
        return msg

    def unsubscribe(self) -> None:
        self._bus._unsubscribe(self)
        self._queue.put_nowait(None)

    def __aiter__(self) -> AsyncIterator[Message]:
        return self

    async def __anext__(self) -> Message:
        msg = await self._queue.get()
        if msg is None:
            raise StopAsyncIteration
        return msg


def _subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style matching: '*' one token, '>' tail wildcard."""
    if pattern == subject:
        return True
    pt, st = pattern.split("."), subject.split(".")
    for i, p in enumerate(pt):
        if p == ">":
            return True
        if i >= len(st):
            return False
        if p != "*" and p != st[i]:
            return False
    return len(pt) == len(st)


class _WorkQueue:
    """Durable FIFO with ack + visibility-timeout redelivery (JetStream
    work-queue semantics, ref nats_queue.py).

    With ``wal_path``, every push/ack appends one fsync'd JSONL record, so
    a hub restart replays unacked items instead of silently dropping
    queued prefills (the reference gets this from JetStream's file-backed
    streams). In-flight-at-crash items replay as ready — at-least-once,
    like an expired visibility timeout. The log self-compacts once dead
    records dominate."""

    def __init__(
        self,
        name: str,
        redeliver_after: float = 30.0,
        wal_path: Optional[str] = None,
    ):
        self.name = name
        self.redeliver_after = redeliver_after
        self._ready: asyncio.Queue[QueueItem] = asyncio.Queue()
        self._inflight: dict[int, tuple[QueueItem, float]] = {}
        self._wal_path = wal_path
        self._wal = None
        self._dead_records = 0
        self._fsync_pending = False
        next_id = 1
        if wal_path:
            next_id = self._replay_wal()
            self._wal = open(wal_path, "ab")
        self._ids = itertools.count(next_id)

    def _replay_wal(self) -> int:
        """Load surviving (pushed, never acked) items; returns next id."""
        import base64
        import json
        import os

        max_id = 0
        items: dict[int, bytes] = {}
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail write from a crash
                    max_id = max(max_id, rec["id"])
                    if rec["op"] == "push":
                        items[rec["id"]] = base64.b64decode(rec["p"])
                    else:  # ack
                        items.pop(rec["id"], None)
        for item_id in sorted(items):
            self._ready.put_nowait(QueueItem(item_id, items[item_id]))
        # start from a clean, compacted log
        self._rewrite_wal(items)
        return max_id + 1

    def _rewrite_wal(self, items: dict[int, bytes]) -> None:
        import base64
        import json
        import os

        tmp = self._wal_path + ".tmp"
        with open(tmp, "wb") as f:
            for item_id in sorted(items):
                rec = {"op": "push", "id": item_id,
                       "p": base64.b64encode(items[item_id]).decode()}
                f.write(json.dumps(rec).encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._wal_path)
        self._dead_records = 0

    def _wal_append(self, rec: dict, durable: bool = True) -> None:
        """Append + flush; fsync is batched off-loop (one per event-loop
        tick) so disk latency never stalls unrelated bus traffic. Acks
        skip fsync entirely — losing one means a redelivery, not data
        loss. Crash window: records flushed to the page cache but not yet
        fsynced (one tick)."""
        import json

        self._wal.write(json.dumps(rec).encode() + b"\n")
        self._wal.flush()
        if durable:
            self._schedule_fsync()

    def _schedule_fsync(self) -> None:
        import os

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            os.fsync(self._wal.fileno())
            return
        if self._fsync_pending:
            return
        self._fsync_pending = True

        def _sync(fd=self._wal.fileno()):
            try:
                os.fsync(fd)
            except OSError:
                pass

        def _done(_fut):
            self._fsync_pending = False

        loop.run_in_executor(None, _sync).add_done_callback(_done)

    def _maybe_compact(self) -> None:
        live = self.depth
        if self._dead_records < 256 or self._dead_records < 4 * max(live, 1):
            return
        items = {i.id: i.payload for i in list(self._ready._queue)}  # type: ignore[attr-defined]
        items.update({i.id: i.payload for i, _ in self._inflight.values()})
        self._wal.close()
        self._rewrite_wal(items)
        self._wal = open(self._wal_path, "ab")

    def push(self, payload: bytes) -> int:
        item = QueueItem(next(self._ids), payload)
        if self._wal is not None:
            import base64

            self._wal_append(
                {"op": "push", "id": item.id,
                 "p": base64.b64encode(payload).decode()}
            )
        self._ready.put_nowait(item)
        return item.id

    async def pop(self, timeout: Optional[float]) -> Optional[QueueItem]:
        self._redeliver_expired()
        try:
            item = await asyncio.wait_for(self._ready.get(), timeout)
        except asyncio.TimeoutError:
            return None
        item.deliveries += 1
        self._inflight[item.id] = (item, time.monotonic() + self.redeliver_after)
        # active redelivery: a consumer already blocked in pop() must still
        # see this item again if the holder crashes without ack
        asyncio.get_running_loop().call_later(
            max(self.redeliver_after, 0.001), self._redeliver_one, item.id
        )
        return item

    def _redeliver_one(self, item_id: int) -> None:
        entry = self._inflight.get(item_id)
        if entry is None:
            return
        item, deadline = entry
        if deadline <= time.monotonic():
            del self._inflight[item_id]
            self._ready.put_nowait(item)

    def ack(self, item_id: int) -> bool:
        if self._inflight.pop(item_id, None) is None:
            return False
        if self._wal is not None:
            self._wal_append({"op": "ack", "id": item_id}, durable=False)
            self._dead_records += 2  # the push + this ack are both dead
            self._maybe_compact()
        return True

    def nack(self, item_id: int) -> bool:
        entry = self._inflight.pop(item_id, None)
        if entry is None:
            return False
        self._ready.put_nowait(entry[0])
        return True

    def _redeliver_expired(self) -> None:
        now = time.monotonic()
        for item_id, (item, deadline) in list(self._inflight.items()):
            if deadline <= now:
                del self._inflight[item_id]
                self._ready.put_nowait(item)

    @property
    def depth(self) -> int:
        self._redeliver_expired()
        return self._ready.qsize() + len(self._inflight)


@dataclass
class _ObjectEntry:
    data: bytes
    expires_at: Optional[float]


class LocalBus:
    """In-process bus implementation. ``data_dir`` enables write-ahead
    logging of work queues (one JSONL per queue) so a hub restart doesn't
    drop queued work — the JetStream-durability equivalent."""

    def __init__(self, data_dir: Optional[str] = None):
        self._subs: list[Subscription] = []
        self._rr: dict[tuple[str, str], int] = {}  # queue-group round robin
        self._inboxes: dict[str, asyncio.Future] = {}
        self._inbox_ids = itertools.count(1)
        self._queues: dict[str, _WorkQueue] = {}
        self._objects: dict[str, dict[str, _ObjectEntry]] = {}
        # request handlers registered as service endpoints (fast path)
        self._handlers: dict[str, Callable[[Message], Awaitable[bytes]]] = {}
        self._data_dir = data_dir
        if data_dir:
            import os

            os.makedirs(data_dir, exist_ok=True)

    # ---- pub/sub ----
    def subscribe(self, subject: str, group: Optional[str] = None) -> Subscription:
        sub = Subscription(self, subject, group)
        self._subs.append(sub)
        return sub

    def _unsubscribe(self, sub: Subscription) -> None:
        if sub in self._subs:
            self._subs.remove(sub)

    def publish(
        self,
        subject: str,
        payload: bytes,
        headers: Optional[dict[str, str]] = None,
        reply: Optional[str] = None,
    ) -> int:
        """Deliver to all plain subscribers and one member per queue group.
        Returns the number of deliveries."""
        msg = Message(subject, payload, headers or {}, reply)
        matched = [s for s in self._subs if _subject_matches(s.subject, subject)]
        delivered = 0
        groups: dict[str, list[Subscription]] = {}
        for s in matched:
            if s.group is None:
                s._push(msg)
                delivered += 1
            else:
                groups.setdefault(s.group, []).append(s)
        for group, members in groups.items():
            idx = self._rr.get((subject, group), 0) % len(members)
            self._rr[(subject, group)] = idx + 1
            members[idx]._push(msg)
            delivered += 1
        return delivered

    # ---- request/reply ----
    async def request(
        self,
        subject: str,
        payload: bytes,
        timeout: float = 30.0,
        headers: Optional[dict[str, str]] = None,
    ) -> bytes:
        handler = self._handlers.get(subject)
        if handler is not None:
            return await asyncio.wait_for(
                handler(Message(subject, payload, headers or {})), timeout
            )
        inbox = f"_inbox.{next(self._inbox_ids)}"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._inboxes[inbox] = fut
        try:
            n = self.publish(subject, payload, headers, reply=inbox)
            if n == 0:
                raise NoResponders(subject)
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._inboxes.pop(inbox, None)

    def respond(self, msg: Message, payload: bytes) -> None:
        if not msg.reply:
            return
        fut = self._inboxes.get(msg.reply)
        if fut is not None and not fut.done():
            fut.set_result(payload)

    def register_handler(
        self, subject: str, handler: Callable[[Message], Awaitable[bytes]]
    ) -> None:
        """Direct-call service endpoint (in-process fast path)."""
        self._handlers[subject] = handler

    def unregister_handler(self, subject: str) -> None:
        self._handlers.pop(subject, None)

    def handler_subjects(self) -> list[str]:
        return list(self._handlers)

    # ---- work queues ----
    def work_queue(self, name: str, redeliver_after: float = 30.0) -> _WorkQueue:
        q = self._queues.get(name)
        if q is None:
            wal = None
            if self._data_dir:
                import hashlib
                import os

                # short hash keeps distinct names distinct even when the
                # readable prefix sanitizes identically ('a.b' vs 'a_b')
                safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in name)
                digest = hashlib.sha1(name.encode()).hexdigest()[:8]
                wal = os.path.join(
                    self._data_dir, f"queue-{safe}-{digest}.jsonl"
                )
            q = self._queues[name] = _WorkQueue(name, redeliver_after, wal_path=wal)
        return q

    # ---- object store ----
    def object_put(
        self, bucket: str, name: str, data: bytes, ttl: Optional[float] = None
    ) -> None:
        expires = time.monotonic() + ttl if ttl else None
        self._objects.setdefault(bucket, {})[name] = _ObjectEntry(data, expires)

    def object_get(self, bucket: str, name: str) -> Optional[bytes]:
        entry = self._objects.get(bucket, {}).get(name)
        if entry is None:
            return None
        if entry.expires_at is not None and entry.expires_at <= time.monotonic():
            del self._objects[bucket][name]
            return None
        return entry.data

    def object_list(self, bucket: str) -> list[str]:
        now = time.monotonic()
        out = []
        for name, entry in list(self._objects.get(bucket, {}).items()):
            if entry.expires_at is not None and entry.expires_at <= now:
                del self._objects[bucket][name]
            else:
                out.append(name)
        return sorted(out)

"""Mock transport: in-memory planes with injectable latency.

Re-design of the reference's mock network (lib/runtime/tests/common/
mock.rs:30-43): tests exercise multi-node behavior — discovery, routing,
streaming, cancellation, lease expiry — against the in-process store/bus
with a configurable per-hop latency model (NoDelay / Constant /
NormalDistribution), no external etcd/NATS and no real network required.

Usage::

    lat = LatencyModel.normal(mean=0.005, std=0.002, seed=1)
    drt = DistributedRuntime(store=LatencyStore(LocalStore(), lat),
                             bus=LatencyBus(LocalBus(), lat))
"""

from __future__ import annotations

import asyncio
import inspect
import random
from dataclasses import dataclass
from typing import Optional

from .bus import LocalBus
from .store import LocalStore


@dataclass
class LatencyModel:
    """ref mock.rs LatencyModel::{NoDelay, Constant, NormalDistribution}."""

    mean: float = 0.0
    std: float = 0.0
    _rng: Optional[random.Random] = None

    @staticmethod
    def no_delay() -> "LatencyModel":
        return LatencyModel()

    @staticmethod
    def constant(delay: float) -> "LatencyModel":
        return LatencyModel(mean=delay)

    @staticmethod
    def normal(mean: float, std: float, seed: int = 0) -> "LatencyModel":
        return LatencyModel(mean=mean, std=std, _rng=random.Random(seed))

    def sample(self) -> float:
        if self.std and self._rng is not None:
            return max(0.0, self._rng.gauss(self.mean, self.std))
        return self.mean

    async def apply(self) -> None:
        d = self.sample()
        if d > 0:
            await asyncio.sleep(d)


async def _resolve(value):
    if inspect.iscoroutine(value):
        return await value
    return value


class _LatencyProxy:
    """Delays a fixed set of methods by one latency sample each (turning
    them into coroutines — callers already await coroutine-returning
    stores/buses, the remote hub clients work the same way)."""

    _delayed: tuple[str, ...] = ()

    def __init__(self, inner, latency: LatencyModel):
        self._inner = inner
        self.latency = latency

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name not in self._delayed or not callable(attr):
            return attr

        async def delayed(*args, **kwargs):
            await self.latency.apply()
            return await _resolve(attr(*args, **kwargs))

        return delayed


class LatencyStore(_LatencyProxy):
    """Control-plane store with per-op latency (ref mock.rs control plane)."""

    _delayed = (
        "kv_put",
        "kv_create",
        "kv_create_or_validate",
        "kv_get",
        "kv_get_prefix",
        "kv_delete",
        "kv_delete_prefix",
        "grant_lease",
        "keep_alive",
        "revoke_lease",
        "watch_prefix",
    )

    def __init__(self, inner: Optional[LocalStore] = None, latency: Optional[LatencyModel] = None):
        super().__init__(inner or LocalStore(), latency or LatencyModel.no_delay())


class LatencyBus(_LatencyProxy):
    """Message plane with per-hop latency: publish/request delay before
    delivery; request pays the hop twice (there and back)."""

    _delayed = ("publish",)

    def __init__(self, inner: Optional[LocalBus] = None, latency: Optional[LatencyModel] = None):
        super().__init__(inner or LocalBus(), latency or LatencyModel.no_delay())

    async def request(self, *args, **kwargs):
        await self.latency.apply()
        result = await _resolve(self._inner.request(*args, **kwargs))
        await self.latency.apply()
        return result


def mock_runtime(latency: Optional[LatencyModel] = None):
    """A DistributedRuntime over latency-injected in-memory planes."""
    from .runtime import DistributedRuntime

    lat = latency or LatencyModel.no_delay()
    store = LocalStore()
    return DistributedRuntime(
        store=LatencyStore(store, lat), bus=LatencyBus(LocalBus(), lat)
    )

"""Annotated stream envelope — the wire shape of every response stream.

Re-design of the reference's ``Annotated<T>``
(lib/runtime/src/protocols/annotated.rs): each element of a response stream
is either data, an SSE-style event/comment, an error, or the end-of-stream
sentinel. This envelope is what crosses process/node boundaries and what the
SSE layer maps 1:1 onto the OpenAI wire format.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Generic, Optional, TypeVar

T = TypeVar("T")


@dataclass
class Annotated(Generic[T]):
    data: Optional[T] = None
    event: Optional[str] = None
    comment: Optional[list[str]] = None
    error: Optional[str] = None
    id: Optional[str] = None

    @staticmethod
    def from_data(data: T) -> "Annotated[T]":
        return Annotated(data=data)

    @staticmethod
    def from_error(error: str) -> "Annotated[T]":
        return Annotated(event="error", error=error)

    @staticmethod
    def from_annotation(name: str, value: Any) -> "Annotated[T]":
        import json

        return Annotated(event=name, comment=[json.dumps(value)])

    @staticmethod
    def sentinel() -> "Annotated[T]":
        return Annotated(event="sentinel")

    def is_sentinel(self) -> bool:
        return self.event == "sentinel"

    def is_error(self) -> bool:
        return self.error is not None or self.event == "error"

    def to_dict(self, data_to_dict=None) -> dict:
        d: dict[str, Any] = {}
        if self.data is not None:
            d["data"] = data_to_dict(self.data) if data_to_dict else self.data
        if self.event is not None:
            d["event"] = self.event
        if self.comment:
            d["comment"] = self.comment
        if self.error is not None:
            d["error"] = self.error
        if self.id is not None:
            d["id"] = self.id
        return d

    @staticmethod
    def from_dict(d: dict, data_from_dict=None) -> "Annotated[Any]":
        data = d.get("data")
        if data is not None and data_from_dict:
            data = data_from_dict(data)
        return Annotated(
            data=data,
            event=d.get("event"),
            comment=d.get("comment"),
            error=d.get("error"),
            id=d.get("id"),
        )

"""Two-part wire codec: length-prefixed (header, data) frames.

Re-design of the reference's TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs:23-203). One frame is:

    magic(2B) | flags(1B) | header_len(u32 BE) | data_len(u64 BE) | header | data

The header is small structured metadata (JSON bytes); the data part is an
opaque payload (serialized request, a KV-block shard, a token batch...).
The u64 data length lets the same framing carry multi-GB KV-cache transfers
on the DCN KV plane (see dynamo_tpu.kv.transfer) as well as tiny control
messages.

**Forward compatibility contract**: decoders read the header keys they
know and MUST ignore the rest — a newer peer may add fields (the trace
context's ``traceparent`` rode in this way) and frames from it must still
decode on older builds. Use :meth:`TwoPartMessage.header_field` for
tolerant access; never destructure the header dict exhaustively.
"""

from __future__ import annotations

import asyncio
import json
import struct
from dataclasses import dataclass
from typing import Any, Optional

MAGIC = b"\xD7\x70"  # "dynamo tpu"
_PREFIX = struct.Struct(">2sBIQ")  # magic, flags, header_len, data_len

# Guard against corrupt/hostile frames (ref two_part.rs max-size guard).
MAX_HEADER_BYTES = 16 * 1024 * 1024
MAX_DATA_BYTES = 64 * 1024 * 1024 * 1024

FLAG_NONE = 0x00


class CodecError(Exception):
    pass


@dataclass
class TwoPartMessage:
    header: bytes = b""
    data: bytes = b""

    @staticmethod
    def from_json(obj: Any, data: bytes = b"") -> "TwoPartMessage":
        return TwoPartMessage(header=json.dumps(obj).encode(), data=data)

    def header_json(self) -> Any:
        return json.loads(self.header) if self.header else None

    def header_field(self, key: str, default: Any = None) -> Any:
        """Version-skew-safe header read: the named key if the header is
        a JSON object carrying it, else ``default``. Unknown extra keys
        in the header are — by contract — ignored, and a malformed or
        non-object header reads as "no fields" rather than an exception
        (the frame layer stays decodable even when a peer's header
        schema has drifted)."""
        if not self.header:
            return default
        try:
            obj = json.loads(self.header)
        except ValueError:
            return default
        if not isinstance(obj, dict):
            return default
        return obj.get(key, default)


def encode(msg: TwoPartMessage, flags: int = FLAG_NONE) -> bytes:
    if len(msg.header) > MAX_HEADER_BYTES:
        raise CodecError(f"header too large: {len(msg.header)}")
    if len(msg.data) > MAX_DATA_BYTES:
        raise CodecError(f"data too large: {len(msg.data)}")
    prefix = _PREFIX.pack(MAGIC, flags, len(msg.header), len(msg.data))
    return prefix + msg.header + msg.data


async def read_frame(reader: asyncio.StreamReader) -> Optional[TwoPartMessage]:
    """Read one frame; returns None on clean EOF at a frame boundary."""
    try:
        prefix = await reader.readexactly(_PREFIX.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    magic, _flags, header_len, data_len = _PREFIX.unpack(prefix)
    if magic != MAGIC:
        raise CodecError(f"bad magic {magic!r}")
    if header_len > MAX_HEADER_BYTES or data_len > MAX_DATA_BYTES:
        raise CodecError(f"frame too large: header={header_len} data={data_len}")
    try:
        header = await reader.readexactly(header_len) if header_len else b""
        data = await reader.readexactly(data_len) if data_len else b""
    except asyncio.IncompleteReadError as e:
        raise CodecError("truncated frame") from e
    return TwoPartMessage(header=header, data=data)


def decode_buffer(buf: bytes) -> tuple[TwoPartMessage, bytes]:
    """Decode one frame from a bytes buffer; returns (msg, remainder)."""
    if len(buf) < _PREFIX.size:
        raise CodecError("short buffer")
    magic, _flags, header_len, data_len = _PREFIX.unpack_from(buf)
    if magic != MAGIC:
        raise CodecError(f"bad magic {magic!r}")
    end = _PREFIX.size + header_len + data_len
    if len(buf) < end:
        raise CodecError("short buffer")
    header = buf[_PREFIX.size : _PREFIX.size + header_len]
    data = buf[_PREFIX.size + header_len : end]
    return TwoPartMessage(bytes(header), bytes(data)), buf[end:]


async def write_frame(
    writer: asyncio.StreamWriter, msg: TwoPartMessage, flags: int = FLAG_NONE
) -> None:
    writer.write(encode(msg, flags))
    await writer.drain()


async def write_frame_parts(
    writer: asyncio.StreamWriter, header: bytes, parts, flags: int = FLAG_NONE
) -> None:
    """One frame whose data section is the concatenation of ``parts``
    (C-contiguous buffers: ndarrays, bytes, memoryviews), written
    WITHOUT materializing the joined blob — the KV stream's segment
    frames are tens of MB and the ``tobytes`` copies otherwise dominate
    the sender's time on the wire path. Wire-identical to
    ``write_frame(writer, TwoPartMessage(header, b"".join(...)))``."""
    views = []
    for p in parts:
        if hasattr(p, "dtype") and hasattr(p, "view"):
            # custom dtypes (bf16/fp8 via ml_dtypes) reject the buffer
            # protocol — a uint8 reinterpret view is free and always works
            p = p.view("uint8")
        views.append(memoryview(p).cast("B"))
    data_len = sum(v.nbytes for v in views)
    if len(header) > MAX_HEADER_BYTES:
        raise CodecError(f"header too large: {len(header)}")
    if data_len > MAX_DATA_BYTES:
        raise CodecError(f"data too large: {data_len}")
    writer.write(_PREFIX.pack(MAGIC, flags, len(header), data_len))
    if header:
        writer.write(header)
    for v in views:
        writer.write(v)
    await writer.drain()

"""Async engine abstraction: the universal streaming-inference interface.

TPU-native re-design of the reference's engine layer
(lib/runtime/src/engine.rs:47-168): every stage of the serving stack — HTTP
frontend, preprocessor, router, JAX worker — implements one interface,
``AsyncEngine.generate(request) -> async stream of responses``, and every
stream carries an ``AsyncEngineContext`` that supports cooperative stop/kill
propagation across process and node boundaries.

Python asyncio is the idiomatic equivalent of the reference's tokio layer;
the TPU compute itself lives behind this interface in
:mod:`dynamo_tpu.engine`.
"""

from __future__ import annotations

import abc
import asyncio
import logging
import uuid
from typing import Any, AsyncIterator, Callable, Generic, Optional, TypeVar

logger = logging.getLogger(__name__)

Req = TypeVar("Req")
Resp = TypeVar("Resp")


class CancellationToken:
    """Hierarchical cancellation (ref: tokio CancellationToken tree used by
    lib/runtime/src/runtime.rs:38-117).

    Children are cancelled when the parent is; cancelling a child does not
    affect the parent.
    """

    def __init__(self, parent: Optional["CancellationToken"] = None):
        self._event = asyncio.Event()
        self._children: list["CancellationToken"] = []
        self._callbacks: list[Callable[[], None]] = []
        if parent is not None:
            parent._children.append(self)
            if parent.is_cancelled():
                self._event.set()

    def child_token(self) -> "CancellationToken":
        return CancellationToken(parent=self)

    def cancel(self) -> None:
        if self._event.is_set():
            return
        self._event.set()
        for cb in self._callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001 — one bad callback must not
                # stop cancellation fan-out, but it must leave a trace
                logger.debug("cancel callback failed", exc_info=True)
        for child in self._children:
            child.cancel()

    def is_cancelled(self) -> bool:
        return self._event.is_set()

    def on_cancel(self, cb: Callable[[], None]) -> None:
        if self._event.is_set():
            cb()
        else:
            self._callbacks.append(cb)

    async def cancelled(self) -> None:
        await self._event.wait()


class AsyncEngineContext:
    """Per-request stream control (ref: engine.rs:47-85).

    ``stop_generating`` asks the generator to finish gracefully (emit what it
    has, mark finish_reason); ``kill`` tears the stream down immediately.
    Both propagate backwards through pipeline stages and across the network
    via control messages on the response plane.
    """

    def __init__(self, request_id: Optional[str] = None):
        self.id: str = request_id or uuid.uuid4().hex
        self._stop = asyncio.Event()
        self._kill = asyncio.Event()

    # -- control (caller side) --
    def stop_generating(self) -> None:
        self._stop.set()

    def kill(self) -> None:
        self._stop.set()
        self._kill.set()

    # -- observation (generator side) --
    def is_stopped(self) -> bool:
        return self._stop.is_set()

    def is_killed(self) -> bool:
        return self._kill.is_set()

    async def stopped(self) -> None:
        await self._stop.wait()

    async def killed(self) -> None:
        await self._kill.wait()


class Context(Generic[Req]):
    """Request envelope carrying the payload + engine context through pipeline
    stages (ref: pipeline/context.rs).

    ``map`` transforms the payload while preserving identity/control;
    ``transfer`` moves the control context onto a new payload.
    """

    __slots__ = ("data", "context", "annotations")

    def __init__(
        self,
        data: Req,
        context: Optional[AsyncEngineContext] = None,
        annotations: Optional[dict[str, Any]] = None,
    ):
        self.data = data
        self.context = context or AsyncEngineContext()
        self.annotations: dict[str, Any] = annotations or {}

    @property
    def id(self) -> str:
        return self.context.id

    def map(self, fn: Callable[[Req], Any]) -> "Context[Any]":
        return Context(fn(self.data), self.context, self.annotations)

    def transfer(self, data: Any) -> "Context[Any]":
        return Context(data, self.context, self.annotations)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Context(id={self.id!r}, data={type(self.data).__name__})"


class AsyncEngine(abc.ABC, Generic[Req, Resp]):
    """The one interface every serving stage implements
    (ref: engine.rs:104-109 ``AsyncEngine<Req, Resp, E>::generate``)."""

    @abc.abstractmethod
    def generate(self, request: Context[Req]) -> AsyncIterator[Resp]:
        """Return an async iterator of responses for this request.

        Implementations must observe ``request.context`` for stop/kill and
        must raise nothing after the stream completes.
        """

    async def close(self) -> None:  # pragma: no cover - default no-op
        pass


class EngineFn(AsyncEngine[Req, Resp]):
    """Adapter: wrap an async-generator function as an AsyncEngine."""

    def __init__(self, fn: Callable[[Context[Req]], AsyncIterator[Resp]]):
        self._fn = fn

    def generate(self, request: Context[Req]) -> AsyncIterator[Resp]:
        return self._fn(request)


class ResponseStream(Generic[Resp]):
    """Pairs a response iterator with its controlling context
    (ref: engine.rs:116 ``ResponseStream``)."""

    def __init__(self, stream: AsyncIterator[Resp], context: AsyncEngineContext):
        self._stream = stream
        self.context = context

    def __aiter__(self) -> AsyncIterator[Resp]:
        return self._stream.__aiter__()


async def collect(stream: AsyncIterator[Resp]) -> list[Resp]:
    """Drain a response stream into a list (test/aggregation helper)."""
    out: list[Resp] = []
    async for item in stream:
        out.append(item)
    return out

"""Runtime + Worker + DistributedRuntime.

Re-design of the reference's runtime layer (lib/runtime/src/{runtime,worker,
distributed}.rs): a process-wide asyncio runtime with a cancellation-token
tree, a ``Worker`` main() wrapper with signal handling and a graceful
shutdown timeout (exit code 911 on overrun, ref worker.rs:16-80), and the
``DistributedRuntime`` which owns the control-plane store connection (with
the process's *primary lease* — the liveness primitive), the message bus,
and the lazily-started TCP response-plane server.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys
from typing import Awaitable, Callable, Optional

from .bus import LocalBus
from .engine import CancellationToken
from .store import LeaseKeeper, LocalStore
from .tcp import TcpStreamServer

logger = logging.getLogger(__name__)

EXIT_CODE_SHUTDOWN_OVERRUN = 911


class Runtime:
    """Process-wide runtime: cancellation root + background task tracking
    (ref runtime.rs:38-117)."""

    def __init__(self):
        self.cancellation = CancellationToken()
        self._tasks: set[asyncio.Task] = set()

    def child_token(self) -> CancellationToken:
        return self.cancellation.child_token()

    def spawn(self, coro: Awaitable, name: Optional[str] = None) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def shutdown(self) -> None:
        self.cancellation.cancel()

    async def join(self, timeout: Optional[float] = None, cancel: bool = False) -> bool:
        """Wait for background tasks; returns False on timeout. With
        ``cancel=True``, cancel everything first (daemon-style tasks like
        serve loops never end on their own)."""
        pending = [t for t in self._tasks if not t.done()]
        if cancel:
            for t in pending:
                t.cancel()
        if not pending:
            return True
        done, still = await asyncio.wait(pending, timeout=timeout)
        for t in still:
            t.cancel()
        return not still


class DistributedRuntime:
    """Runtime + control-plane store + bus + response-plane server
    (ref distributed.rs:31-129).

    ``store``/``bus`` may be local in-process instances or remote hub
    clients (dynamo_tpu.runtime.hub) — everything above this class is
    transport-agnostic.
    """

    PRIMARY_LEASE_TTL = 10.0

    def __init__(self, store=None, bus=None, host: str = "127.0.0.1"):
        self.runtime = Runtime()
        self.store = store if store is not None else LocalStore()
        self.bus = bus if bus is not None else LocalBus()
        self._tcp_server: Optional[TcpStreamServer] = None
        self._tcp_starting: Optional[asyncio.Future] = None
        self._host = host
        self.primary_lease_id: int = 0
        self._lease_keeper: Optional[LeaseKeeper] = None
        self._started = False
        self._shut_down = False
        self._hub_conn = None  # hub connection owned by this runtime, if any

    @classmethod
    async def from_settings(
        cls,
        store=None,
        bus=None,
        host: Optional[str] = None,
        hub_url: Optional[str] = None,
    ):
        """Build from the layered config (defaults ← TOML ← ``DYN_RUNTIME_*``
        env, ref config.rs:86-88): resolves the response-plane host and, when
        ``store``/``bus`` are not given and a hub is configured (``hub_url``
        arg or ``DYN_RUNTIME_HUB_URL``), connects both to that TCP hub. The
        hub connection is owned by the runtime and closed on shutdown."""
        from ..utils.config import RuntimeConfig

        cfg = RuntimeConfig.from_settings(hub_url=hub_url)
        hub_conn = None
        if store is None and bus is None and cfg.hub_url:
            from .hub import connect_hub

            store, bus, hub_conn = await connect_hub(cfg.hub_url)
        drt = cls(store=store, bus=bus, host=host or cfg.response_host)
        drt._hub_conn = hub_conn
        await drt.start()
        return drt

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        from concurrent.futures import ThreadPoolExecutor

        from ..utils.config import RuntimeConfig

        loop = asyncio.get_running_loop()
        if not getattr(loop, "_dyn_blocking_pool", None):
            # bound the default-executor pool used for blocking work
            # (tokenize, host staging IO) — ref config.rs max_blocking_threads
            cfg = RuntimeConfig.from_settings()
            loop._dyn_blocking_pool = ThreadPoolExecutor(
                max_workers=cfg.max_blocking_threads,
                thread_name_prefix="dyn-blocking",
            )
            loop.set_default_executor(loop._dyn_blocking_pool)
        # local stores (including latency-wrapped ones) need their lease
        # reaper started in this loop; remote hub stores have no start()
        starter = getattr(self.store, "start", None)
        if starter is not None:
            starter()
        lease = self.store.grant_lease(self.PRIMARY_LEASE_TTL)
        if asyncio.iscoroutine(lease):
            lease = await lease
        self.primary_lease_id = lease
        self._lease_keeper = LeaseKeeper(
            self.store,
            lease,
            self.PRIMARY_LEASE_TTL,
            on_lost=self.runtime.shutdown,
        )
        self._lease_keeper.start()

    @property
    def worker_id(self) -> int:
        """Stable identity of this process in the cluster = its lease id
        (the reference uses the etcd lease id the same way)."""
        return self.primary_lease_id

    async def tcp_server(self) -> TcpStreamServer:
        """Lazily-started response-plane server (ref distributed.rs lazy TCP).

        Single-flight: concurrent first callers must share one instance —
        otherwise streams register on a half-started server that a racing
        caller then overwrites."""
        if self._tcp_server is None:
            if self._tcp_starting is None:

                async def _start() -> TcpStreamServer:
                    srv = TcpStreamServer(host=self._host)
                    await srv.start()
                    return srv

                fut = asyncio.ensure_future(_start())
                self._tcp_starting = fut

                def _done(f: asyncio.Future) -> None:
                    # Publish the server even if every awaiter was cancelled
                    # mid-shield — otherwise the shielded start completes
                    # unobserved, a later caller starts a second server, and
                    # the first listening socket leaks. On failure, drop the
                    # future so a transient error isn't replayed forever.
                    if self._tcp_starting is f:
                        self._tcp_starting = None
                    if not f.cancelled() and f.exception() is None:
                        if self._tcp_server is None and not self._shut_down:
                            self._tcp_server = f.result()
                        else:  # racing second start / post-shutdown orphan
                            srv = f.result()
                            asyncio.ensure_future(srv.close())

                fut.add_done_callback(_done)
            starting = self._tcp_starting
            await asyncio.shield(starting)
            self._tcp_server = starting.result()
        return self._tcp_server

    def namespace(self, name: str):
        from .component import Namespace

        return Namespace(self, name)

    async def shutdown(self) -> None:
        self._shut_down = True
        if self._tcp_starting is not None:
            self._tcp_starting.cancel()
        self.runtime.shutdown()
        if self._lease_keeper:
            await self._lease_keeper.stop(revoke=True)
            self._lease_keeper = None
        if self._tcp_server:
            # TcpServer.close() is async and awaits the asyncio server's
            # wait_closed() itself (runtime/tcp.py)
            await self._tcp_server.close()  # dynlint: disable=writer-wait-closed -- TcpServer.close() waits internally
            self._tcp_server = None
        await self.runtime.join(timeout=5.0, cancel=True)
        if self._hub_conn is not None:
            await self._hub_conn.close()
            self._hub_conn = None


class Worker:
    """main() wrapper: run an async entrypoint under signal handling with a
    graceful-shutdown deadline (ref worker.rs:16-80)."""

    def __init__(self, drt: Optional[DistributedRuntime] = None):
        self.drt = drt

    def execute(self, fn: Callable[[DistributedRuntime], Awaitable[None]]) -> None:
        try:
            asyncio.run(self._run(fn))
        except KeyboardInterrupt:
            pass

    async def _run(self, fn: Callable[[DistributedRuntime], Awaitable[None]]) -> None:
        drt = self.drt or DistributedRuntime()
        await drt.start()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, drt.runtime.shutdown)
            except NotImplementedError:  # pragma: no cover - non-unix
                pass
        main_task = loop.create_task(fn(drt))
        cancel_wait = loop.create_task(drt.runtime.cancellation.cancelled())
        done, _ = await asyncio.wait(
            [main_task, cancel_wait], return_when=asyncio.FIRST_COMPLETED
        )
        if main_task in done:
            cancel_wait.cancel()
            main_task.result()  # propagate errors
            await drt.shutdown()
            return
        # external shutdown requested: give main a grace period
        from ..utils.config import WorkerConfig

        timeout = WorkerConfig.from_settings().graceful_shutdown_timeout
        main_task.cancel()
        try:
            await asyncio.wait_for(asyncio.gather(main_task, return_exceptions=True), timeout)
        except asyncio.TimeoutError:
            logger.error("graceful shutdown overran %ss; exiting 911", timeout)
            sys.exit(EXIT_CODE_SHUTDOWN_OVERRUN)
        await drt.shutdown()

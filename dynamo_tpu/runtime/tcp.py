"""Response data plane: direct TCP connect-back streaming.

Re-design of the reference's TCP stream server
(lib/runtime/src/pipeline/network/tcp/{server,client}.rs): the request plane
(bus) only carries small request envelopes; token streams flow on dedicated
TCP connections that the *worker opens back to the caller*, so response
bytes never transit the bus. The caller registers a pending stream and ships
``ConnectionInfo`` inside the request; the worker connects, handshakes with
a prologue naming the stream id, then streams two-part frames. The caller
can send ``stop``/``kill`` control frames upstream on the same connection
(ref pipeline/network.rs:58 ControlMessage) — this is how client
disconnects cancel TPU work across nodes.
"""

from __future__ import annotations

import asyncio
import json
import logging
import uuid
from dataclasses import dataclass
from typing import AsyncIterator, Optional

from .annotated import Annotated
from .codec import TwoPartMessage, read_frame, write_frame
from .engine import AsyncEngineContext
from .. import tracing

logger = logging.getLogger(__name__)

# frame types
T_PROLOGUE = "prologue"
T_DATA = "data"
T_SENTINEL = "sentinel"
T_CONTROL = "control"
T_ERROR = "error"


@dataclass
class ConnectionInfo:
    address: str  # "host:port"
    stream_id: str

    def to_dict(self) -> dict:
        return {"address": self.address, "stream_id": self.stream_id}

    @staticmethod
    def from_dict(d: dict) -> "ConnectionInfo":
        return ConnectionInfo(d["address"], d["stream_id"])


class _PendingStream:
    def __init__(self, context: AsyncEngineContext):
        self.context = context
        self.queue: asyncio.Queue[Optional[Annotated]] = asyncio.Queue()
        self.connected = asyncio.get_running_loop().create_future()


class TcpStreamServer:
    """Caller-side server accepting worker connect-backs
    (ref tcp/server.rs:74-125)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._pending: dict[str, _PendingStream] = {}
        self.address: str = ""

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self._host, self._port)
        port = self._server.sockets[0].getsockname()[1]
        self.address = f"{self._host}:{port}"

    async def close(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def register(self, context: AsyncEngineContext) -> ConnectionInfo:
        """Register a pending response stream; returns the ConnectionInfo to
        embed in the outgoing request envelope."""
        stream_id = uuid.uuid4().hex
        self._pending[stream_id] = _PendingStream(context)
        return ConnectionInfo(self.address, stream_id)

    def unregister(self, info: ConnectionInfo) -> None:
        self._pending.pop(info.stream_id, None)

    async def stream(
        self, info: ConnectionInfo, connect_timeout: float = 30.0
    ) -> AsyncIterator[Annotated]:
        """Await the worker connect-back, then yield the Annotated stream."""
        pending = self._pending[info.stream_id]
        try:
            await asyncio.wait_for(asyncio.shield(pending.connected), connect_timeout)
            while True:
                item = await pending.queue.get()
                if item is None:
                    return
                yield item
        finally:
            self._pending.pop(info.stream_id, None)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        pending: Optional[_PendingStream] = None
        control_task: Optional[asyncio.Task] = None
        try:
            prologue = await read_frame(reader)
            if prologue is None:
                return
            # tolerant reads: newer peers may add header keys (e.g. the
            # trace context) — decode what we know, ignore the rest
            stream_id = prologue.header_field("stream_id", "")
            pending = self._pending.get(stream_id)
            if pending is None or pending.connected.done():
                await write_frame(
                    writer, TwoPartMessage.from_json({"type": T_ERROR, "error": "unknown stream"})
                )
                return
            if tracing.enabled():
                # the worker's prologue names the request trace: record
                # the connect-back on the caller side, so the timeline
                # shows when the response plane came up for this request
                tc = tracing.TraceContext.from_traceparent(
                    prologue.header_field("traceparent")
                )
                if tc is not None:
                    tracing.RECORDER.event(
                        "response.stream_connect", trace=tc,
                        stream_id=stream_id,
                    )
            await write_frame(writer, TwoPartMessage.from_json({"type": T_PROLOGUE, "ok": True}))
            pending.connected.set_result(True)

            # forward stop/kill from the caller's context upstream
            control_task = asyncio.get_running_loop().create_task(
                self._send_control(pending.context, writer)
            )

            ended_clean = False
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    # EOF with NO sentinel: the worker died mid-stream.
                    # Silently ending here would hand the consumer a
                    # truncated stream indistinguishable from a complete
                    # one (the lost-stream failure tests/test_soak_churn.py
                    # hunts) — it must surface as an error.
                    break
                ftype = frame.header_field("type")
                if ftype == T_DATA:
                    payload = json.loads(frame.data) if frame.data else {}
                    pending.queue.put_nowait(Annotated.from_dict(payload))
                elif ftype == T_SENTINEL:
                    ended_clean = True
                    break
                elif ftype == T_ERROR:
                    ended_clean = True  # error IS a terminal signal
                    pending.queue.put_nowait(Annotated.from_error(
                        frame.header_field("error", "worker error")))
                    break
            if not ended_clean:
                pending.queue.put_nowait(Annotated.from_error(
                    "response stream truncated: worker connection lost "
                    "before the completion sentinel"))
        except (ConnectionResetError, asyncio.IncompleteReadError):
            if pending is not None:
                pending.queue.put_nowait(Annotated.from_error(
                    "response stream truncated: worker connection reset"))
        except Exception as e:  # noqa: BLE001
            logger.warning("response stream error: %s", e)
            if pending is not None:
                pending.queue.put_nowait(Annotated.from_error(str(e)))
        finally:
            if control_task:
                control_task.cancel()
            if pending is not None:
                if not pending.connected.done():
                    pending.connected.set_exception(ConnectionError("worker hung up"))
                pending.queue.put_nowait(None)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass  # peer already gone — the fd is released either way

    @staticmethod
    async def _send_control(context: AsyncEngineContext, writer: asyncio.StreamWriter):
        try:
            await context.stopped()
            msg = "kill" if context.is_killed() else "stop"
            await write_frame(writer, TwoPartMessage.from_json({"type": T_CONTROL, "msg": msg}))
        except Exception:  # noqa: BLE001
            # control is best-effort (the data plane surfaces real
            # failures) — but a silent swallow hid a dead control plane
            # once already, so leave a trace for debugging
            logger.debug("control-frame send failed", exc_info=True)


class ResponseWriter:
    """Worker-side handle for streaming responses back to the caller
    (ref tcp/client.rs:37-75)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        context: AsyncEngineContext,
    ):
        self._reader = reader
        self._writer = writer
        self.context = context
        self._control_task = asyncio.get_running_loop().create_task(self._recv_control())

    async def _recv_control(self):
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    # caller hung up -> kill generation (ref: disconnect => kill)
                    self.context.kill()
                    return
                if frame.header_field("type") == T_CONTROL:
                    if frame.header_field("msg") == "kill":
                        self.context.kill()
                    else:
                        self.context.stop_generating()
        except Exception:
            self.context.kill()

    async def send(self, item: Annotated) -> None:
        payload = item.to_dict(
            data_to_dict=lambda d: d.to_dict() if hasattr(d, "to_dict") else d
        )
        await write_frame(
            self._writer,
            TwoPartMessage(
                header=json.dumps({"type": T_DATA}).encode(),
                data=json.dumps(payload).encode(),
            ),
        )

    async def error(self, message: str) -> None:
        await write_frame(
            self._writer, TwoPartMessage.from_json({"type": T_ERROR, "error": message})
        )

    async def close(self) -> None:
        self._control_task.cancel()
        try:
            await write_frame(self._writer, TwoPartMessage.from_json({"type": T_SENTINEL}))
        except (ConnectionResetError, BrokenPipeError):
            pass
        self._writer.close()
        try:
            await self._writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass  # peer already gone — the fd is released either way


async def connect_response_stream(
    info: ConnectionInfo, context: AsyncEngineContext, timeout: float = 10.0
) -> ResponseWriter:
    """Worker side: open the connect-back stream to the caller."""
    host, port_s = info.address.rsplit(":", 1)
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, int(port_s)), timeout
    )
    prologue = {"type": T_PROLOGUE, "stream_id": info.stream_id}
    tp = tracing.current_traceparent()
    if tp is not None:
        # attribute the response stream to the request's trace; receivers
        # on older builds ignore the extra header key (codec frame headers
        # are decoded tolerantly — see read_frame / header_json)
        prologue["traceparent"] = tp
    await write_frame(writer, TwoPartMessage.from_json(prologue))
    resp = await read_frame(reader)
    head = (resp.header_json() or {}) if resp else {}
    if not head.get("ok"):
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
        raise ConnectionError(f"handshake rejected: {head}")
    return ResponseWriter(reader, writer, context)

"""Distributed runtime layer (L0/L1/L2).

Re-design of the reference's ``lib/runtime`` crate for asyncio + TPU hosts:
control-plane store with leases/watch, message bus, TCP response plane,
component model with lease-backed discovery, and the AsyncEngine/pipeline
abstractions every serving stage implements.
"""

from .annotated import Annotated
from .bus import LocalBus, Message, NoResponders
from .codec import TwoPartMessage, decode_buffer, encode, read_frame, write_frame
from .component import (
    Client,
    Component,
    Endpoint,
    EndpointInfo,
    EngineClient,
    Namespace,
    RequestEnvelope,
    slug,
)
from .engine import (
    AsyncEngine,
    AsyncEngineContext,
    CancellationToken,
    Context,
    EngineFn,
    ResponseStream,
    collect,
)
from .pipeline import MapOperator, Operator, link
from .runtime import DistributedRuntime, Runtime, Worker
from .store import (
    EventKind,
    KeyExists,
    KvEntry,
    LeaseKeeper,
    LocalStore,
    StoreError,
    ValidationFailed,
    WatchEvent,
)
from .tcp import ConnectionInfo, TcpStreamServer, connect_response_stream

__all__ = [
    "Annotated",
    "AsyncEngine",
    "AsyncEngineContext",
    "CancellationToken",
    "Client",
    "Component",
    "ConnectionInfo",
    "Context",
    "DistributedRuntime",
    "Endpoint",
    "EndpointInfo",
    "EngineClient",
    "EngineFn",
    "EventKind",
    "KeyExists",
    "KvEntry",
    "LeaseKeeper",
    "LocalBus",
    "LocalStore",
    "MapOperator",
    "Message",
    "Namespace",
    "NoResponders",
    "Operator",
    "RequestEnvelope",
    "ResponseStream",
    "Runtime",
    "StoreError",
    "TcpStreamServer",
    "TwoPartMessage",
    "ValidationFailed",
    "WatchEvent",
    "Worker",
    "collect",
    "connect_response_stream",
    "decode_buffer",
    "encode",
    "link",
    "read_frame",
    "slug",
    "write_frame",
]

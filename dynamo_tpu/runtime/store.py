"""Control-plane KV store: discovery, leases/liveness, dynamic config.

TPU-native re-design of the reference's etcd transport
(lib/runtime/src/transports/etcd.rs:38-346 + etcd/lease.rs): a
strongly-ordered key-value store with

  * **leases** with TTL + keepalive — the liveness primitive: every endpoint
    registration is bound to its worker's primary lease; lease loss deletes
    the keys, which every watcher observes (elastic membership),
  * **atomic create-if-absent** (``kv_create``) and create-or-validate,
  * **prefix get + watch** streams of Put/Delete events.

Deployments that fit on one host use :class:`LocalStore` in-process; the
multi-host path serves the same interface over TCP via
:mod:`dynamo_tpu.runtime.hub` (no external etcd dependency — TPU pods give
us a reliable single coordinator host, so a replicated consensus store is
deliberately out of scope; the interface would admit one).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import AsyncIterator, Optional


class StoreError(Exception):
    pass


class KeyExists(StoreError):
    pass


class ValidationFailed(StoreError):
    pass


class EventKind(str, Enum):
    PUT = "put"
    DELETE = "delete"
    # synthetic marker a RECONNECTED remote watcher emits after its
    # reconcile pass (hub.py RemoteWatcher._reconcile): the missed
    # deletes/puts have all been replayed, dependents holding derived
    # state can re-list/refresh. In-process watchers never emit it.
    RESUMED = "resumed"


@dataclass
class WatchEvent:
    kind: EventKind
    key: str
    value: bytes = b""
    lease_id: int = 0


@dataclass
class KvEntry:
    key: str
    value: bytes
    lease_id: int = 0
    revision: int = 0


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: set[str] = field(default_factory=set)


class Watcher:
    """A live prefix watch: async-iterate to receive WatchEvents.

    Mirrors the reference's PrefixWatcher (etcd.rs:283-332): creating one
    returns the current snapshot plus the event stream from that revision.
    """

    def __init__(self, prefix: str, snapshot: list[KvEntry], store: "LocalStore"):
        self.prefix = prefix
        self.snapshot = snapshot
        self._queue: asyncio.Queue[Optional[WatchEvent]] = asyncio.Queue()
        self._store = store

    def _push(self, ev: WatchEvent) -> None:
        self._queue.put_nowait(ev)

    def cancel(self) -> None:
        self._store._watchers.discard(self)
        self._queue.put_nowait(None)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self._queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev


class LocalStore:
    """In-process store implementation; also the state machine behind the
    TCP hub server.

    ``data_dir`` makes the store DURABLE (VERDICT r3 weak #4: an
    in-memory hub restart used to orphan every registration): mutations
    append to a JSONL write-ahead log, restore replays snapshot + WAL,
    and restored leases restart their TTL clock from restore time — hub
    downtime must not tick lease deadlines (the reference's etcd
    persists leases with their TTL the same way, etcd.rs:38 lease
    semantics). Clients that never reconnect still expire a TTL after
    the restart; clients that do reconnect just resume keepalives on
    their old lease ids (the id counters are persisted past the
    high-water mark so new grants can't collide)."""

    def __init__(self, *, clock=time.monotonic, data_dir: Optional[str] = None):
        self._data: dict[str, KvEntry] = {}
        self._leases: dict[int, _Lease] = {}
        self._watchers: set[Watcher] = set()
        self._revision = itertools.count(1)
        self._lease_ids = itertools.count(1)
        # high-water marks of EVER-ISSUED ids — persisted so a restart
        # can't reissue a revoked lease's id to a new client (a stale
        # holder of the old id would then control the new lease)
        self._rev_hw = 0
        self._lease_hw = 0
        self._clock = clock
        self._reaper_task: Optional[asyncio.Task] = None
        self._wal = None
        self._data_dir = data_dir
        if data_dir:
            self._restore(data_dir)

    # ---- persistence ----
    def _snap_path(self):
        import os

        return os.path.join(self._data_dir, "store.snap.json")

    def _wal_path(self):
        import os

        return os.path.join(self._data_dir, "store.wal.jsonl")

    def _log(self, **op) -> None:
        if self._wal is not None:
            import json

            self._wal.write(json.dumps(op) + "\n")
            self._wal.flush()

    def _apply(self, op: dict) -> None:
        """Replay one WAL record (no logging, no watcher notify — there
        are no watchers before start)."""
        kind = op["op"]
        if kind == "put":
            value = bytes.fromhex(op["v"])
            lease_id = op.get("l", 0)
            # detach from the PREVIOUS owner first (mirrors live kv_put):
            # otherwise the old lease's later expiry would delete a key
            # that a different live lease now owns
            old = self._data.get(op["k"])
            if old is not None and old.lease_id and old.lease_id != lease_id:
                prev = self._leases.get(old.lease_id)
                if prev:
                    prev.keys.discard(op["k"])
            if lease_id and lease_id in self._leases:
                self._leases[lease_id].keys.add(op["k"])
            elif lease_id:
                self._data.pop(op["k"], None)
                return  # lease already gone: the key died with it
            self._data[op["k"]] = KvEntry(op["k"], value, lease_id, op.get("r", 0))
        elif kind == "del":
            entry = self._data.pop(op["k"], None)
            if entry is not None and entry.lease_id in self._leases:
                self._leases[entry.lease_id].keys.discard(op["k"])
        elif kind == "grant":
            self._leases[op["id"]] = _Lease(op["id"], op["ttl"], 0.0)
        elif kind == "revoke":
            lease = self._leases.pop(op["id"], None)
            if lease:
                for key in list(lease.keys):
                    self._data.pop(key, None)

    def _restore(self, data_dir: str) -> None:
        import json
        import os

        os.makedirs(data_dir, exist_ok=True)
        try:
            with open(self._snap_path()) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            snap = {"data": [], "leases": [], "revision": 0, "lease_id": 0}
        for l in snap["leases"]:
            self._leases[l["id"]] = _Lease(l["id"], l["ttl"], 0.0)
        for e in snap["data"]:
            lease_id = e.get("l", 0)
            if lease_id and lease_id not in self._leases:
                continue
            self._data[e["k"]] = KvEntry(
                e["k"], bytes.fromhex(e["v"]), lease_id, e.get("r", 0)
            )
            if lease_id:
                self._leases[lease_id].keys.add(e["k"])
        max_rev, max_lease = snap.get("revision", 0), snap.get("lease_id", 0)
        try:
            with open(self._wal_path()) as f:
                for ln in f:
                    if not ln.strip():
                        continue
                    try:
                        op = json.loads(ln)
                    except ValueError:
                        continue  # torn tail write on crash
                    self._apply(op)
                    max_rev = max(max_rev, op.get("r", 0))
                    max_lease = max(max_lease, op.get("id", 0))
        except OSError:
            pass
        max_rev = max(max_rev, *(e.revision for e in self._data.values()), 0)
        self._rev_hw, self._lease_hw = max_rev, max_lease
        self._revision = itertools.count(max_rev + 1)
        self._lease_ids = itertools.count(max_lease + 1)
        # downtime doesn't count against liveness: every restored lease
        # gets a full TTL of grace from RESTORE time to resume keepalives
        now = self._clock()
        for lease in self._leases.values():
            lease.deadline = now + lease.ttl
        # compact: fresh snapshot, truncated WAL
        self._write_snapshot()
        self._wal = open(self._wal_path(), "w")

    def _write_snapshot(self) -> None:
        import json
        import os

        tmp = self._snap_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "data": [
                        {"k": e.key, "v": e.value.hex(), "l": e.lease_id,
                         "r": e.revision}
                        for e in self._data.values()
                    ],
                    "leases": [
                        {"id": l.id, "ttl": l.ttl}
                        for l in self._leases.values()
                    ],
                    # EVER-ISSUED high-water marks, not max-over-survivors:
                    # revoked ids must stay burned across restarts
                    "revision": self._rev_hw,
                    "lease_id": self._lease_hw,
                },
                f,
            )
        os.replace(tmp, self._snap_path())

    # ---- lifecycle ----
    def start(self) -> None:
        if self._reaper_task is None:
            self._reaper_task = asyncio.get_running_loop().create_task(self._reaper())

    async def close(self) -> None:
        if self._reaper_task:
            self._reaper_task.cancel()
            self._reaper_task = None
        for w in list(self._watchers):
            w.cancel()
        if self._wal is not None:
            self._write_snapshot()
            self._wal.close()
            self._wal = None
            import os

            try:  # compacted into the snapshot
                # dynlint: disable=blocking-disk-io -- shutdown-only WAL compaction, loop is tearing down
                os.remove(self._wal_path())
            except OSError:
                pass

    async def _reaper(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            self.expire_leases()

    def expire_leases(self) -> None:
        now = self._clock()
        for lease in [l for l in self._leases.values() if l.deadline <= now]:
            self._revoke(lease.id)

    # ---- leases ----
    def grant_lease(self, ttl: float) -> int:
        lease_id = next(self._lease_ids)
        self._lease_hw = max(self._lease_hw, lease_id)
        self._leases[lease_id] = _Lease(lease_id, ttl, self._clock() + ttl)
        self._log(op="grant", id=lease_id, ttl=ttl)
        return lease_id

    def keep_alive(self, lease_id: int) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.deadline = self._clock() + lease.ttl
        return True

    def revoke_lease(self, lease_id: int) -> None:
        self._revoke(lease_id)

    def _revoke(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        self._log(op="revoke", id=lease_id)
        for key in list(lease.keys):
            self._delete(key)

    # ---- kv ----
    def _notify(self, ev: WatchEvent) -> None:
        for w in list(self._watchers):
            if ev.key.startswith(w.prefix):
                w._push(ev)

    def _attach(self, key: str, lease_id: int) -> None:
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise StoreError(f"unknown lease {lease_id}")
            lease.keys.add(key)

    def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        old = self._data.get(key)
        if old is not None and old.lease_id and old.lease_id != lease_id:
            lease = self._leases.get(old.lease_id)
            if lease:
                lease.keys.discard(key)
        self._attach(key, lease_id)
        entry = KvEntry(key, value, lease_id, next(self._revision))
        self._rev_hw = max(self._rev_hw, entry.revision)
        self._data[key] = entry
        self._log(op="put", k=key, v=value.hex(), l=lease_id, r=entry.revision)
        self._notify(WatchEvent(EventKind.PUT, key, value, lease_id))

    def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> None:
        """Atomic create-if-absent (ref: etcd.rs kv_create txn)."""
        if key in self._data:
            raise KeyExists(key)
        self.kv_put(key, value, lease_id)

    def kv_create_or_validate(self, key: str, value: bytes, lease_id: int = 0) -> None:
        existing = self._data.get(key)
        if existing is None:
            self.kv_put(key, value, lease_id)
        elif existing.value != value:
            raise ValidationFailed(key)

    def kv_get(self, key: str) -> Optional[KvEntry]:
        return self._data.get(key)

    def kv_get_prefix(self, prefix: str) -> list[KvEntry]:
        return [e for k, e in sorted(self._data.items()) if k.startswith(prefix)]

    def kv_delete(self, key: str) -> bool:
        return self._delete(key)

    def _delete(self, key: str) -> bool:
        entry = self._data.pop(key, None)
        if entry is None:
            return False
        if entry.lease_id:
            lease = self._leases.get(entry.lease_id)
            if lease:
                lease.keys.discard(key)
        self._log(op="del", k=key)
        self._notify(WatchEvent(EventKind.DELETE, key))
        return True

    def kv_delete_prefix(self, prefix: str) -> int:
        keys = [k for k in self._data if k.startswith(prefix)]
        for k in keys:
            self._delete(k)
        return len(keys)

    # ---- watch ----
    def watch_prefix(self, prefix: str) -> Watcher:
        w = Watcher(prefix, self.kv_get_prefix(prefix), self)
        self._watchers.add(w)
        return w


class LeaseKeeper:
    """Background keepalive for a lease (ref: etcd/lease.rs:51). Cancels the
    given CancellationToken if the lease is lost."""

    def __init__(self, store, lease_id: int, ttl: float, on_lost=None):
        self._store = store
        self.lease_id = lease_id
        self._ttl = ttl
        self._on_lost = on_lost
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        interval = max(self._ttl / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                ok = self._store.keep_alive(self.lease_id)
                if asyncio.iscoroutine(ok):
                    ok = await ok
            except ConnectionError:
                # hub unreachable (restarting): NOT lease loss — a
                # durable hub revives the lease with a fresh TTL at
                # restore and the connection layer is redialing; only an
                # explicit keep_alive=False (lease truly gone) is fatal
                continue
            if not ok:
                if self._on_lost:
                    self._on_lost()
                return

    async def stop(self, revoke: bool = True) -> None:
        if self._task:
            self._task.cancel()
            self._task = None
        if revoke:
            r = self._store.revoke_lease(self.lease_id)
            if asyncio.iscoroutine(r):
                await r

"""Control-plane KV store: discovery, leases/liveness, dynamic config.

TPU-native re-design of the reference's etcd transport
(lib/runtime/src/transports/etcd.rs:38-346 + etcd/lease.rs): a
strongly-ordered key-value store with

  * **leases** with TTL + keepalive — the liveness primitive: every endpoint
    registration is bound to its worker's primary lease; lease loss deletes
    the keys, which every watcher observes (elastic membership),
  * **atomic create-if-absent** (``kv_create``) and create-or-validate,
  * **prefix get + watch** streams of Put/Delete events.

Deployments that fit on one host use :class:`LocalStore` in-process; the
multi-host path serves the same interface over TCP via
:mod:`dynamo_tpu.runtime.hub` (no external etcd dependency — TPU pods give
us a reliable single coordinator host, so a replicated consensus store is
deliberately out of scope; the interface would admit one).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import AsyncIterator, Optional


class StoreError(Exception):
    pass


class KeyExists(StoreError):
    pass


class ValidationFailed(StoreError):
    pass


class EventKind(str, Enum):
    PUT = "put"
    DELETE = "delete"


@dataclass
class WatchEvent:
    kind: EventKind
    key: str
    value: bytes = b""
    lease_id: int = 0


@dataclass
class KvEntry:
    key: str
    value: bytes
    lease_id: int = 0
    revision: int = 0


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: set[str] = field(default_factory=set)


class Watcher:
    """A live prefix watch: async-iterate to receive WatchEvents.

    Mirrors the reference's PrefixWatcher (etcd.rs:283-332): creating one
    returns the current snapshot plus the event stream from that revision.
    """

    def __init__(self, prefix: str, snapshot: list[KvEntry], store: "LocalStore"):
        self.prefix = prefix
        self.snapshot = snapshot
        self._queue: asyncio.Queue[Optional[WatchEvent]] = asyncio.Queue()
        self._store = store

    def _push(self, ev: WatchEvent) -> None:
        self._queue.put_nowait(ev)

    def cancel(self) -> None:
        self._store._watchers.discard(self)
        self._queue.put_nowait(None)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self._queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev


class LocalStore:
    """In-process store implementation; also the state machine behind the
    TCP hub server."""

    def __init__(self, *, clock=time.monotonic):
        self._data: dict[str, KvEntry] = {}
        self._leases: dict[int, _Lease] = {}
        self._watchers: set[Watcher] = set()
        self._revision = itertools.count(1)
        self._lease_ids = itertools.count(1)
        self._clock = clock
        self._reaper_task: Optional[asyncio.Task] = None

    # ---- lifecycle ----
    def start(self) -> None:
        if self._reaper_task is None:
            self._reaper_task = asyncio.get_running_loop().create_task(self._reaper())

    async def close(self) -> None:
        if self._reaper_task:
            self._reaper_task.cancel()
            self._reaper_task = None
        for w in list(self._watchers):
            w.cancel()

    async def _reaper(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            self.expire_leases()

    def expire_leases(self) -> None:
        now = self._clock()
        for lease in [l for l in self._leases.values() if l.deadline <= now]:
            self._revoke(lease.id)

    # ---- leases ----
    def grant_lease(self, ttl: float) -> int:
        lease_id = next(self._lease_ids)
        self._leases[lease_id] = _Lease(lease_id, ttl, self._clock() + ttl)
        return lease_id

    def keep_alive(self, lease_id: int) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.deadline = self._clock() + lease.ttl
        return True

    def revoke_lease(self, lease_id: int) -> None:
        self._revoke(lease_id)

    def _revoke(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            self._delete(key)

    # ---- kv ----
    def _notify(self, ev: WatchEvent) -> None:
        for w in list(self._watchers):
            if ev.key.startswith(w.prefix):
                w._push(ev)

    def _attach(self, key: str, lease_id: int) -> None:
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise StoreError(f"unknown lease {lease_id}")
            lease.keys.add(key)

    def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        old = self._data.get(key)
        if old is not None and old.lease_id and old.lease_id != lease_id:
            lease = self._leases.get(old.lease_id)
            if lease:
                lease.keys.discard(key)
        self._attach(key, lease_id)
        self._data[key] = KvEntry(key, value, lease_id, next(self._revision))
        self._notify(WatchEvent(EventKind.PUT, key, value, lease_id))

    def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> None:
        """Atomic create-if-absent (ref: etcd.rs kv_create txn)."""
        if key in self._data:
            raise KeyExists(key)
        self.kv_put(key, value, lease_id)

    def kv_create_or_validate(self, key: str, value: bytes, lease_id: int = 0) -> None:
        existing = self._data.get(key)
        if existing is None:
            self.kv_put(key, value, lease_id)
        elif existing.value != value:
            raise ValidationFailed(key)

    def kv_get(self, key: str) -> Optional[KvEntry]:
        return self._data.get(key)

    def kv_get_prefix(self, prefix: str) -> list[KvEntry]:
        return [e for k, e in sorted(self._data.items()) if k.startswith(prefix)]

    def kv_delete(self, key: str) -> bool:
        return self._delete(key)

    def _delete(self, key: str) -> bool:
        entry = self._data.pop(key, None)
        if entry is None:
            return False
        if entry.lease_id:
            lease = self._leases.get(entry.lease_id)
            if lease:
                lease.keys.discard(key)
        self._notify(WatchEvent(EventKind.DELETE, key))
        return True

    def kv_delete_prefix(self, prefix: str) -> int:
        keys = [k for k in self._data if k.startswith(prefix)]
        for k in keys:
            self._delete(k)
        return len(keys)

    # ---- watch ----
    def watch_prefix(self, prefix: str) -> Watcher:
        w = Watcher(prefix, self.kv_get_prefix(prefix), self)
        self._watchers.add(w)
        return w


class LeaseKeeper:
    """Background keepalive for a lease (ref: etcd/lease.rs:51). Cancels the
    given CancellationToken if the lease is lost."""

    def __init__(self, store, lease_id: int, ttl: float, on_lost=None):
        self._store = store
        self.lease_id = lease_id
        self._ttl = ttl
        self._on_lost = on_lost
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        interval = max(self._ttl / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            ok = self._store.keep_alive(self.lease_id)
            if asyncio.iscoroutine(ok):
                ok = await ok
            if not ok:
                if self._on_lost:
                    self._on_lost()
                return

    async def stop(self, revoke: bool = True) -> None:
        if self._task:
            self._task.cancel()
            self._task = None
        if revoke:
            r = self._store.revoke_lease(self.lease_id)
            if asyncio.iscoroutine(r):
                await r

"""SLA-driven planner: predictive prefill/decode autoscaling + frontend
overload control (docs/planner.md).

The control-loop component the reference Dynamo stack ships as
``components/planner``: a telemetry aggregator over the metrics/load
plane, an SLO evaluator + Holt load forecaster seeded by the roofline
capacity model, guarded scale actuators targeting the deploy
controller's replica API, and the frontend token-bucket admission gate
with per-request SLO classes.
"""

from .admission import (
    DEFAULT_CLASSES,
    AdmissionDecision,
    AdmissionGate,
    SloClass,
    TokenBucket,
)
from .actuators import BusPublisher, CallbackScaleDriver, StoreScaleDriver
from .guard import GuardConfig, ScaleAction, ScaleGuard
from .planner import MorphConfig, Planner, PlannerConfig
from .predictor import (
    CapacityModel,
    HoltForecaster,
    SloEvaluator,
    SloStatus,
    SloTargets,
)
from .protocols import (
    PLANNER_DECISION_SUBJECT,
    PLANNER_RESHARD_SUBJECT,
    PLANNER_WATERMARK_SUBJECT,
    CapacityWatermark,
    MorphDecision,
    PlannerDecision,
)
from .telemetry import ClusterSnapshot, TelemetryAggregator

__all__ = [
    "AdmissionDecision",
    "AdmissionGate",
    "BusPublisher",
    "CallbackScaleDriver",
    "CapacityModel",
    "CapacityWatermark",
    "ClusterSnapshot",
    "DEFAULT_CLASSES",
    "GuardConfig",
    "MorphConfig",
    "MorphDecision",
    "HoltForecaster",
    "PLANNER_DECISION_SUBJECT",
    "PLANNER_RESHARD_SUBJECT",
    "PLANNER_WATERMARK_SUBJECT",
    "Planner",
    "PlannerConfig",
    "PlannerDecision",
    "ScaleAction",
    "ScaleGuard",
    "SloClass",
    "SloEvaluator",
    "SloStatus",
    "SloTargets",
    "StoreScaleDriver",
    "TelemetryAggregator",
    "TokenBucket",
]

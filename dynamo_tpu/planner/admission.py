"""Frontend overload control: token-bucket admission with SLO classes.

When offered load exceeds what any scale decision can absorb, admitting
everything means EVERY request misses its SLO (unbounded queueing). The
gate sheds the excess instead — shed requests get an immediate 429 +
Retry-After (cheap for the client to retry elsewhere/later), admitted
requests keep their latency target.

Mechanics:

  * one global :class:`TokenBucket` (req/s rate + burst) — the rate is
    the cluster's serving capacity, configured or continuously updated
    from the planner's capacity watermarks;
  * per-request SLO classes, annotation-driven (``nvext.annotations:
    ["slo:batch"]``): each class declares a ``reserve_frac`` — the
    bucket floor it may not drain below. Batch traffic reserves
    capacity for interactive traffic; interactive can spend the whole
    bucket. Priority without starvation bookkeeping;
  * queue-depth-bounded shedding: each class caps how many of its
    requests may be in flight (admitted, unfinished) — a stalled fleet
    bounds its queue instead of timing everyone out.

Deterministic: clock injected, no background task — refill is computed
lazily on each admit.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class SloClass:
    name: str
    #: fraction of the bucket's burst this class must leave for more
    #: latency-critical classes (0 = may drain the bucket dry)
    reserve_frac: float = 0.0
    #: max in-flight (admitted, unfinished) requests of this class
    max_inflight: int = 256
    #: floor for the Retry-After hint (the real hint also accounts for
    #: the bucket's refill time)
    min_retry_after_s: float = 1.0


#: default ladder: interactive drains the whole bucket; batch keeps half
#: the burst in reserve for interactive and tolerates a shorter queue
DEFAULT_CLASSES = (
    SloClass("interactive", reserve_frac=0.0, max_inflight=256,
             min_retry_after_s=1.0),
    SloClass("batch", reserve_frac=0.5, max_inflight=64,
             min_retry_after_s=5.0),
)


class TokenBucket:
    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be > 0")
        self.rate = rate
        self.burst = burst
        self._clock = clock
        self.level = burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        self.level = min(self.burst, self.level + (now - self._last) * self.rate)
        self._last = now

    def set_rate(self, rate: float, burst: Optional[float] = None) -> None:
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self._refill(self._clock())
        self.rate = rate
        if burst is not None and burst > 0:
            self.burst = burst
            self.level = min(self.level, burst)

    def try_take(self, n: float = 1.0, floor: float = 0.0) -> bool:
        """Take ``n`` tokens unless that would leave fewer than
        ``floor`` in the bucket (the reserve kept for higher classes)."""
        self._refill(self._clock())
        if self.level - n < floor - 1e-9:
            return False
        self.level -= n
        return True

    def time_until(self, n: float = 1.0, floor: float = 0.0) -> float:
        """Seconds until ``try_take(n, floor)`` could succeed."""
        self._refill(self._clock())
        deficit = (floor + n) - self.level
        return max(0.0, deficit / self.rate)


@dataclass
class AdmissionDecision:
    admitted: bool
    slo_class: str = "interactive"
    reason: str = ""  # "rate" | "queue" when shed
    retry_after_s: float = 0.0


class AdmissionGate:
    """``admit()`` before dispatch, ``done()`` when the stream ends
    (success or not) — the inflight counts bound the queue."""

    ANNOTATION_PREFIX = "slo:"

    def __init__(
        self,
        rate_req_s: float,
        burst: Optional[float] = None,
        classes: tuple[SloClass, ...] = DEFAULT_CLASSES,
        clock: Callable[[], float] = time.monotonic,
        telemetry=None,
        model_classes: Optional[dict] = None,
    ):
        self._clock = clock
        self.bucket = TokenBucket(
            rate_req_s, burst if burst is not None else max(rate_req_s, 1.0),
            clock,
        )
        # autopilot headroom lane: optional per-class buckets holding a
        # class at its MEASURED headroom (set_class_rate) — checked
        # before the global bucket so a shed never needs a refund. No
        # bucket = the class rides the global rate alone, exactly the
        # pre-autopilot behavior.
        self.class_buckets: dict[str, TokenBucket] = {}
        self.classes = {c.name: c for c in classes}
        self.default_class = classes[0].name
        #: per-model SLO routing (multi-model serving): model/adapter
        #: name -> class name. A model mapped to "batch" gets batch's
        #: reserve/queue bounds for ALL its traffic — one adapter's
        #: burst can't starve another model's interactive SLO. Unknown
        #: names (and unmapped models) classify as before.
        self.model_classes = {
            m: c for m, c in (model_classes or {}).items()
            if c in self.classes
        }
        #: optional TelemetryAggregator — arrivals feed the planner
        self.telemetry = telemetry
        self.inflight: dict[str, int] = {c.name: 0 for c in classes}
        self.stats = {"admitted_total": 0, "shed_total": 0,
                      "shed_headroom_total": 0}
        for c in classes:
            self.stats[f"admitted_{c.name}"] = 0
            self.stats[f"shed_{c.name}"] = 0

    # -- classification --

    def classify(self, annotations: Optional[list] = None,
                 model: Optional[str] = None) -> str:
        """``slo:<class>`` annotation -> class name (unknown classes fall
        back to the default rather than 400ing the request). The
        explicit annotation outranks the model mapping — a request may
        always downgrade itself — then ``model`` routes through
        ``model_classes`` (multi-model pools), then the default."""
        for a in annotations or ():
            if isinstance(a, str) and a.startswith(self.ANNOTATION_PREFIX):
                name = a[len(self.ANNOTATION_PREFIX):]
                if name in self.classes:
                    return name
        if model and model in self.model_classes:
            return self.model_classes[model]
        return self.default_class

    # -- planner plane --

    def set_rate(self, rate_req_s: float, burst: Optional[float] = None) -> None:
        """Planner watermark update: hold admission at cluster capacity."""
        if rate_req_s > 0:
            self.bucket.set_rate(rate_req_s, burst)

    def set_class_rate(self, name: str, rate_req_s: float,
                       burst: Optional[float] = None) -> None:
        """Autopilot headroom update: hold ONE class at its measured
        per-class headroom (capacity left after more-critical classes'
        observed demand), instead of the static reserve fraction.
        ``rate_req_s <= 0`` removes the cap (back to the global bucket
        alone — the autopilot stopping must not freeze its last
        decision into the gate forever)."""
        if name not in self.classes:
            return
        if rate_req_s <= 0:
            self.class_buckets.pop(name, None)
            return
        b = self.class_buckets.get(name)
        if b is None:
            self.class_buckets[name] = TokenBucket(
                rate_req_s,
                burst if burst is not None else max(rate_req_s, 1.0),
                self._clock,
            )
        else:
            b.set_rate(rate_req_s, burst)

    # -- the gate --

    def admit(self, slo_class: Optional[str] = None,
              prompt_tokens: int = 0) -> AdmissionDecision:
        name = slo_class if slo_class in self.classes else self.default_class
        cls = self.classes[name]
        if self.telemetry is not None:
            self.telemetry.record_arrival(prompt_tokens)
        if self.inflight[name] >= cls.max_inflight:
            return self._shed(cls, "queue", cls.min_retry_after_s)
        # measured-headroom lane first (no refund path needed): a class
        # the autopilot capped sheds here before touching the global
        # bucket, so its excess can't drain tokens interactive needs
        cb = self.class_buckets.get(name)
        if cb is not None and not cb.try_take(1.0):
            wait = cb.time_until(1.0)
            return self._shed(
                cls, "headroom", max(cls.min_retry_after_s, math.ceil(wait))
            )
        # the reserve may never consume the whole bucket: cap the floor
        # so a full bucket always admits one request of ANY class (at
        # burst < 2 an uncapped batch floor of burst/2 would starve the
        # class forever, even on an idle gate)
        floor = min(self.bucket.burst * cls.reserve_frac,
                    max(self.bucket.burst - 1.0, 0.0))
        if not self.bucket.try_take(1.0, floor=floor):
            wait = self.bucket.time_until(1.0, floor=floor)
            return self._shed(
                cls, "rate", max(cls.min_retry_after_s, math.ceil(wait))
            )
        self.inflight[name] += 1
        self.stats["admitted_total"] += 1
        self.stats[f"admitted_{name}"] += 1
        return AdmissionDecision(True, name)

    def _shed(self, cls: SloClass, reason: str,
              retry_after: float) -> AdmissionDecision:
        self.stats["shed_total"] += 1
        self.stats[f"shed_{cls.name}"] += 1
        if reason == "headroom":
            self.stats["shed_headroom_total"] += 1
        return AdmissionDecision(False, cls.name, reason, retry_after)

    def done(self, slo_class: str) -> None:
        name = slo_class if slo_class in self.inflight else self.default_class
        self.inflight[name] = max(0, self.inflight[name] - 1)

    # -- metrics surface (http.Metrics.register_source) --

    def render_stats(self) -> dict:
        out = {f"admission_{k}": v for k, v in self.stats.items()}
        out["admission_rate_req_s"] = round(self.bucket.rate, 6)
        for name, n in self.inflight.items():
            out[f"admission_inflight_{name}"] = n
        for name, b in self.class_buckets.items():
            out[f"admission_headroom_rate_{name}"] = round(b.rate, 6)
        return out


async def start_watermark_follower(drt, component, gate: AdmissionGate):
    """Subscribe the planner's capacity watermarks and hold the gate's
    admission rate at the published cluster capacity (frontend-side
    wiring for `dynamo_run in=http ... --admission-rate`). Returns the
    consumer task; keep a reference for the frontend's lifetime."""
    from .protocols import PLANNER_WATERMARK_SUBJECT, CapacityWatermark

    sub = drt.bus.subscribe(
        component.event_subject(PLANNER_WATERMARK_SUBJECT)
    )
    ready = getattr(sub, "ready", None)
    if ready is not None:
        await ready

    async def _consume():
        import logging

        log = logging.getLogger(__name__)
        async for msg in sub:
            try:
                wm = CapacityWatermark.from_bytes(msg.payload)
                # set_rate ignores rate <= 0 (planner has no mix yet:
                # keep the configured rate)
                gate.set_rate(wm.admission_rate_req_s)
            except Exception:  # noqa: BLE001 — watermarks are advisory
                log.debug("bad capacity watermark", exc_info=True)

    return drt.runtime.spawn(_consume())

"""Scale-decision guard rails: hysteresis, cooldown, min/max bounds.

One implementation shared by the planner's prefill/decode scale drivers
and the deploy controller's queue-depth autoscaler — the reference
planner ships the same idea as ``adjustment_interval`` plus blocked
scale-down windows, and the operator grew flap guards independently;
here both planes ride ONE guard so the rails can't drift.

Semantics (asymmetric on purpose — under-provisioning breaks SLOs,
over-provisioning only costs chips):

  * scale UP applies immediately, paced only by ``up_cooldown_s``
    between consecutive up actions;
  * scale DOWN applies only after the desire has been *continuously*
    below the current value for ``down_stable_s`` (the time-domain
    hysteresis band — an oscillating signal keeps resetting the window
    and never scales down) AND ``down_cooldown_s`` has elapsed since the
    last action in either direction;
  * everything is clamped to ``[min_replicas, max_replicas]``.

Deterministic under test: the clock is injected.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class GuardConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    #: min seconds between consecutive scale-up actions (0 = every tick)
    up_cooldown_s: float = 0.0
    #: min seconds after ANY action before a scale-down may apply
    down_cooldown_s: float = 60.0
    #: the desire must sit below current for this long, continuously,
    #: before a scale-down applies (hysteresis window)
    down_stable_s: float = 30.0

    def validate(self) -> None:
        if self.min_replicas > self.max_replicas:
            raise ValueError("min_replicas > max_replicas")
        if min(self.up_cooldown_s, self.down_cooldown_s,
               self.down_stable_s) < 0:
            raise ValueError("guard windows must be >= 0")


@dataclass
class ScaleAction:
    ts: float
    from_replicas: int
    to_replicas: int

    @property
    def direction(self) -> str:
        return "up" if self.to_replicas > self.from_replicas else "down"


class ScaleGuard:
    """Feed it the raw desired replica count every tick; it returns the
    guarded value to actually apply and records each real change in
    ``actions`` (the no-flap assertions in tests count these)."""

    def __init__(
        self,
        cfg: Optional[GuardConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        initial: Optional[int] = None,
    ):
        self.cfg = cfg or GuardConfig()
        self.cfg.validate()
        self._clock = clock
        self.current: Optional[int] = (
            None if initial is None else self._clamp(initial)
        )
        self._last_action = -math.inf
        self._below_since: Optional[float] = None
        self.actions: list[ScaleAction] = []

    def _clamp(self, n: int) -> int:
        return max(self.cfg.min_replicas, min(self.cfg.max_replicas, int(n)))

    def _act(self, to: int, now: float) -> None:
        assert self.current is not None
        self.actions.append(ScaleAction(now, self.current, to))
        self.current = to
        self._last_action = now
        self._below_since = None

    def apply(self, desired: int) -> int:
        """One tick: raw desire in, guarded replica count out."""
        now = self._clock()
        desired = self._clamp(desired)
        if self.current is None:
            # seeding (spec value / first observation) is not an action
            self.current = desired
            return self.current
        if desired > self.current:
            self._below_since = None
            if now - self._last_action >= self.cfg.up_cooldown_s:
                self._act(desired, now)
        elif desired < self.current:
            if self._below_since is None:
                self._below_since = now
            if (
                now - self._below_since >= self.cfg.down_stable_s
                and now - self._last_action >= self.cfg.down_cooldown_s
            ):
                self._act(desired, now)
        else:
            self._below_since = None
        return self.current

"""Telemetry aggregator: metrics/load subjects -> sliding-window
cluster snapshots.

The planner's eyes. Folds three sources into one
:class:`ClusterSnapshot` per control tick:

  * per-worker :class:`WorkerLoad` rows (the same scrape the KV router
    uses — ``observe_loads`` accepts a load list or pulls a live
    ``KvMetricsAggregator``), including the cumulative
    ``requests_total`` / ``tokens_generated`` / ``prompt_tokens_total``
    counters whose deltas give fleet arrival and throughput rates
    without any frontend cooperation;
  * frontend arrival events (``record_arrival`` — the admission gate
    feeds these when the planner is embedded in the HTTP service);
  * latency samples: ``record_ttft``/``record_itl`` directly, or the
    tracing plane's TTFT-decomposition percentiles via an attached
    ``TraceCollector``.

Everything is windowed on an injected clock, so scripted traces replay
deterministically in tests.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..kv_router.scheduler import WorkerLoad
from ..observability.hist import MS_BUCKETS, Histogram, WindowedHistogram


@dataclass
class ClusterSnapshot:
    ts: float = 0.0
    workers: list[WorkerLoad] = field(default_factory=list)
    request_rate: float = 0.0  # req/s arriving over the window
    prompt_token_rate: float = 0.0  # prompt tok/s (prefill demand)
    gen_token_rate: float = 0.0  # generated tok/s (decode demand)
    queue_depth: int = 0  # sum of per-worker waiting
    active_requests: int = 0
    total_slots: int = 0
    ttft_p99_ms: Optional[float] = None  # None = no samples in window
    itl_p99_ms: Optional[float] = None
    #: workers that vanished from the scrape within the window WITHOUT
    #: having advertised ``draining`` first — the lost-host signal the
    #: morph policy re-lays survivors on (a graceful scale-down drains
    #: before deregistering, so it never lands here)
    lost_workers: list[int] = field(default_factory=list)

    @property
    def pool_tp(self) -> int:
        """The pool's ACTUALLY-deployed tensor-parallel degree: the
        majority of live workers' advertised ``mesh_tp`` (0 = none
        advertise one — older workers, or an empty scrape). Seeds the
        morph guard so a restarted planner reasons from reality, not
        from ``tp_min``."""
        tps = [w.mesh_tp for w in self.workers if w.mesh_tp > 0]
        return max(set(tps), key=tps.count) if tps else 0

    @property
    def mean_prompt_tokens(self) -> float:
        """Observed prompt tokens per request over the window — the
        long-prompt-dominated signal the morph policy grows TP on."""
        return (
            self.prompt_token_rate / self.request_rate
            if self.request_rate > 0 else 0.0
        )

    @property
    def decode_replicas(self) -> int:
        """Live, non-draining workers — the pool the planner sizes."""
        return sum(1 for w in self.workers if not w.draining)

    @property
    def slot_utilization(self) -> float:
        return self.active_requests / max(self.total_slots, 1)

    def saturated_workers(self, slot_frac: float = 0.9,
                          kv_frac: float = 0.9) -> list[int]:
        """Workers at/over the capacity watermark: slots nearly full
        with work queued, or KV pool nearly exhausted — routing more at
        them only grows their queue."""
        out = []
        for w in self.workers:
            if w.draining:
                continue
            slots_hot = w.slot_usage >= slot_frac and w.waiting > 0
            if slots_hot or w.kv_usage >= kv_frac:
                out.append(w.worker_id)
        return out


class TelemetryAggregator:
    def __init__(
        self,
        window_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        trace_collector=None,
        metrics_aggregator=None,
    ):
        self.window_s = window_s
        self._clock = clock
        #: optional tracing.TraceCollector — its ttft_ms percentiles
        #: back-fill the TTFT view when no direct samples are recorded
        self.trace_collector = trace_collector
        #: optional kv_router.KvMetricsAggregator — ``snapshot()`` pulls
        #: its latest endpoint view when no loads were pushed
        self.metrics_aggregator = metrics_aggregator
        self._loads: list[WorkerLoad] = []
        # (ts, requests, prompt_tokens) arrival events
        self._arrivals: deque[tuple[float, int, int]] = deque()
        # (ts, generated_tokens)
        self._generated: deque[tuple[float, int]] = deque()
        # latency distributions as windowed fixed-bucket histograms
        # (observability/hist.py): bounded memory at ANY sample rate —
        # the bounded deques these replace dropped samples under load,
        # exactly when the tail the SLO evaluator reads matters most —
        # and the same bucket schema workers advertise, so fleet_hist()
        # merges frontend and worker views loss-free
        self._ttft = WindowedHistogram(window_s, MS_BUCKETS, clock=clock)
        self._itl = WindowedHistogram(window_s, MS_BUCKETS, clock=clock)
        # cumulative-counter baselines per worker: (requests_total,
        # tokens_generated, prompt_tokens_total)
        self._counter_base: dict[int, tuple[int, int, int]] = {}
        # (ts, worker_id) of non-draining workers that vanished from a
        # scrape — windowed lost-host evidence for the morph policy
        self._lost: deque[tuple[float, int]] = deque()
        self._was_draining: dict[int, bool] = {}
        #: consecutive missed scrapes per still-unconfirmed worker: ONE
        #: miss is a slow metrics endpoint or a long compile, not a lost
        #: host — a force-relayout of the whole pool must not fire on it
        self._miss_counts: dict[int, int] = {}
        self.lost_confirm_scrapes = 2
        #: discovery-watch lease-expiry evidence (record_lease_expiry):
        #: workers whose discovery key vanished unexpectedly. NOT lost
        #: yet — a hub restart or watch flap can expire a lease while
        #: the worker keeps answering scrapes, and a force-relayout must
        #: never fire on a worker that is demonstrably alive. The
        #: evidence instead halves the scrape debounce: ONE missed
        #: scrape confirms (vs lost_confirm_scrapes without it), and a
        #: worker already missing when its lease expires confirms on
        #: the spot.
        self._lease_expired: dict[int, float] = {}
        self.lease_expiries = 0

    # ---------------- feeding ----------------

    def record_arrival(self, prompt_tokens: int = 0, n: int = 1) -> None:
        self._arrivals.append((self._clock(), n, max(prompt_tokens, 0)))

    def record_generated(self, tokens: int) -> None:
        self._generated.append((self._clock(), max(tokens, 0)))

    def record_ttft(self, ms: float) -> None:
        self._ttft.observe(ms)

    def record_itl(self, ms: float) -> None:
        self._itl.observe(ms)

    def record_lease_expiry(self, worker_id: int) -> None:
        """Discovery-watch lost-host evidence (ROADMAP PR 12 leftover):
        the worker's lease expired, which normally means its host died
        with scrapes about to stop. Cuts ``relayout_lost_host``
        detection from two missed scrapes to at most one — immediately,
        when the worker was already missing from the last scrape. A
        drained departure (deregister-before-lease-revoke, the PR 4
        shutdown order) is planned churn and ignored; a worker whose
        scrapes KEEP arriving clears the evidence instead of being
        relaid (the lease loss was the control plane's problem, not the
        host's)."""
        if worker_id not in self._was_draining:
            return  # never scraped: not part of the pool we'd re-lay
        if self._was_draining.get(worker_id):
            return  # planned departure — drain deregisters first
        self.lease_expiries += 1
        if self._miss_counts.get(worker_id, 0) >= 1:
            # already missing from the last scrape AND the lease is
            # gone: both signals agree — confirm now, not next tick
            self._miss_counts.pop(worker_id, None)
            self._lease_expired.pop(worker_id, None)
            self._was_draining.pop(worker_id, None)
            self._lost.append((self._clock(), worker_id))
            return
        self._lease_expired[worker_id] = self._clock()

    def observe_loads(self, loads: list[WorkerLoad]) -> None:
        """Fold a fresh per-worker load scrape: keep the instantaneous
        view, and convert each worker's cumulative counters into
        windowed arrival/throughput events (delta vs the last scrape;
        a restarted worker's counter reset clamps to 0, losing one
        interval instead of going negative)."""
        now = self._clock()
        self._loads = list(loads)
        seen = set()
        for w in loads:
            seen.add(w.worker_id)
            cur = (w.requests_total, w.tokens_generated, w.prompt_tokens_total)
            base = self._counter_base.get(w.worker_id)
            self._counter_base[w.worker_id] = cur
            self._was_draining[w.worker_id] = bool(w.draining)
            if base is None:
                continue  # first sight: baseline only
            d_req = max(cur[0] - base[0], 0)
            d_gen = max(cur[1] - base[1], 0)
            d_prompt = max(cur[2] - base[2], 0)
            if d_req or d_prompt:
                self._arrivals.append((now, d_req, d_prompt))
            if d_gen:
                self._generated.append((now, d_gen))
        for wid in list(self._counter_base):
            if wid not in seen:
                del self._counter_base[wid]
        for wid in seen:
            self._miss_counts.pop(wid, None)
            # scrapes still arriving: the lease expiry was a control-
            # plane flap, not a dead host — evidence cleared, and a
            # relayout never fires on a live worker (regression-pinned)
            self._lease_expired.pop(wid, None)
        for wid in list(self._was_draining):
            if wid not in seen:
                # vanished between scrapes: a drained departure is a
                # planned scale-down; anything else is lost-host
                # evidence — but only after ``lost_confirm_scrapes``
                # CONSECUTIVE misses (a reappearance above resets the
                # count), so one slow scrape can't trigger a pool-wide
                # force relayout. A discovery lease expiry for the same
                # worker corroborates the miss, so ONE is enough.
                needed = (
                    1 if wid in self._lease_expired
                    else self.lost_confirm_scrapes
                )
                misses = self._miss_counts.get(wid, 0) + 1
                if misses < needed:
                    self._miss_counts[wid] = misses
                    continue
                self._miss_counts.pop(wid, None)
                self._lease_expired.pop(wid, None)
                if not self._was_draining.pop(wid):
                    self._lost.append((now, wid))

    # ---------------- folding ----------------

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        for q in (self._arrivals, self._generated, self._lost):
            while q and q[0][0] < cutoff:
                q.popleft()

    @staticmethod
    def _p99(wh: WindowedHistogram) -> Optional[float]:
        v = wh.quantile(0.99)
        return round(v, 3) if v is not None else None

    def fleet_hist(self, name: str) -> Optional[Histogram]:
        """Merge the named worker-side distribution (``queue_wait_ms`` /
        ``prefill_ms`` / ``restore_ms`` / ``handoff_ms``) across the
        last scrape's workers — exact vector addition, so the fleet p99
        is a real quantile of every worker's observations, not a
        percentile-of-percentiles. None when no worker advertises it
        (or every vector is schema-skewed)."""
        out: Optional[Histogram] = None
        for w in self._loads:
            h = Histogram.from_vec((w.hists or {}).get(name) or {})
            if h is None:
                continue
            if out is None:
                out = h
            elif out.bounds == h.bounds:
                out.merge(h)
        return out

    def snapshot(self) -> ClusterSnapshot:
        # live wiring: pull the aggregator's latest scrape and fold its
        # counter deltas before reading the window
        if self.metrics_aggregator is not None:
            self.observe_loads(self.metrics_aggregator.endpoints.loads)
        now = self._clock()
        self._prune(now)
        loads = self._loads
        span = max(self.window_s, 1e-9)
        snap = ClusterSnapshot(
            ts=now,
            workers=list(loads),
            request_rate=sum(n for _t, n, _p in self._arrivals) / span,
            prompt_token_rate=sum(p for _t, _n, p in self._arrivals) / span,
            gen_token_rate=sum(g for _t, g in self._generated) / span,
            queue_depth=sum(w.waiting for w in loads),
            active_requests=sum(w.active_requests for w in loads),
            total_slots=sum(w.total_slots for w in loads),
            ttft_p99_ms=self._p99(self._ttft),
            itl_p99_ms=self._p99(self._itl),
            lost_workers=sorted({wid for _t, wid in self._lost}),
        )
        if snap.ttft_p99_ms is None and self.trace_collector is not None:
            snap.ttft_p99_ms = (
                self.trace_collector.percentiles(ps=(99,))
                .get("ttft_ms", {}).get("p99")
            )
        return snap


async def start_lease_watch(drt, component, telemetry: TelemetryAggregator):
    """Feed the discovery watch's lease-expiry events into the
    aggregator's lost-host evidence (``record_lease_expiry``): watch the
    component's discovery prefix and report every DELETE's lease id.
    The aggregator decides what an expiry means — drained departures
    and workers whose scrapes keep arriving are ignored there, so this
    watch can stay a dumb pipe. Returns the spawned watch task (cancel
    it to stop)."""
    import asyncio

    from ..runtime.store import EventKind

    prefix = f"{component.namespace}/components/{component.name}/"
    watcher = drt.store.watch_prefix(prefix)
    if asyncio.iscoroutine(watcher):
        watcher = await watcher

    async def _consume() -> None:
        async for ev in watcher:
            if ev.kind != EventKind.DELETE:
                continue
            # key format: {ns}/components/{comp}/{endpoint}:{lease:x}
            try:
                wid = int(ev.key.rsplit(":", 1)[1], 16)
            except (IndexError, ValueError):
                continue
            telemetry.record_lease_expiry(wid)

    return drt.runtime.spawn(_consume())

"""The SLA-driven control loop: snapshot -> evaluate -> plan -> actuate.

Closes the loop the reference Dynamo planner owns (components/planner):
observe TTFT/ITL against SLA targets plus forecast arrival rates, and
resize the prefill/decode pools accordingly — while the frontend's
admission gate and the KV scheduler's capacity watermarks protect the
admitted requests when offered load outruns any scale decision.

Every tick is synchronous and deterministic (injected clock, pure
inputs); ``run()`` merely schedules ticks on an interval. Unit tests
drive ``tick()`` directly against scripted traces.

Scaling policy per tick:

  1. demand floor — Holt-forecast prompt/gen token arrival rates over
     the telemetry window, divided by the capacity model's corrected
     per-replica rates at ``headroom`` utilization;
  2. SLO push — a TTFT-p99 breach sustained past the grace window asks
     for one more prefill replica (TTFT is prefill/queue bound; in an
     aggregated cluster with no prefill pool it bumps decode instead),
     a sustained ITL-p99 breach one more decode replica;
  3. guard rails — both pools' desires pass through
     :class:`~dynamo_tpu.planner.guard.ScaleGuard` (hysteresis,
     cooldown, min/max bounds) so the fleet never flaps;
  4. actuate — replica counts to the scale driver (deploy controller
     replica API; scale-down rides SIGTERM -> DrainCoordinator), and a
     :class:`CapacityWatermark` (saturated workers + admission rate +
     disagg ratio) onto the bus for the KV scheduler and the frontend
     gate.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .guard import GuardConfig, ScaleGuard
from .predictor import CapacityModel, HoltForecaster, SloEvaluator, SloTargets
from .protocols import CapacityWatermark, MorphDecision, PlannerDecision
from .telemetry import ClusterSnapshot, TelemetryAggregator

logger = logging.getLogger(__name__)


@dataclass
class MorphConfig:
    """Policy knobs for the planner's third verb: MORPH a pool's
    parallelism degree live (docs/elastic_resharding.md) instead of
    adding/removing whole replicas.

      * long-prompt-dominated (windowed mean prompt length at or above
        ``grow_prompt_tokens`` with real traffic behind it) → double TP
        toward ``tp_max`` — prefill is compute-bound, more chips per
        worker cut TTFT where more workers would not;
      * sustained idle (slot utilization below ``shrink_utilization``)
        → shrink back to ``tp_min`` — night-time chips return to the
        pool without dropping the streams still trickling;
      * a lost host (non-draining workers vanishing from telemetry) →
        an immediate ``relayout_lost_host`` morph at the CURRENT degree
        with ``force=True``, so survivors re-resolve their layout over
        the devices that remain.

    Desired degrees pass a :class:`ScaleGuard` (the same rails as
    replica counts — up paced by cooldown, down only after sustained
    quiet) so a bursty mix can't flap a pool between layouts."""

    tp_min: int = 1
    tp_max: int = 4
    grow_prompt_tokens: float = 512.0
    shrink_utilization: float = 0.1
    #: hold in-flight streams through morphs (False = hand off via the
    #: migration path first; for deadline-pressured pools)
    hold: bool = True
    guard: GuardConfig = field(
        default_factory=lambda: GuardConfig(
            min_replicas=1, max_replicas=4, up_cooldown_s=30.0,
            down_cooldown_s=120.0, down_stable_s=60.0,
        )
    )

    def validate(self) -> None:
        if self.tp_min < 1 or self.tp_max < self.tp_min:
            raise ValueError(
                f"morph degrees invalid: tp_min={self.tp_min} "
                f"tp_max={self.tp_max}"
            )


@dataclass
class PlannerConfig:
    tick_s: float = 2.0
    slo: SloTargets = field(default_factory=SloTargets)
    #: target utilization of modeled capacity (fraction of roofline the
    #: fleet is sized to run at — the rest is burst headroom)
    headroom: float = 0.8
    #: Holt horizon in ticks: plan for the rate ~this far ahead
    forecast_horizon: float = 2.0
    decode_guard: GuardConfig = field(default_factory=GuardConfig)
    prefill_guard: GuardConfig = field(
        default_factory=lambda: GuardConfig(min_replicas=0, max_replicas=8)
    )
    #: False = aggregated cluster: no prefill pool to size, TTFT
    #: breaches push the decode pool instead
    prefill_pool: bool = True
    #: per-worker saturation watermarks (telemetry.saturated_workers)
    watermark_slot_frac: float = 0.9
    watermark_kv_frac: float = 0.9
    #: only fold observed throughput into the capacity correction when
    #: the fleet is at least this utilized — an idle fleet's low tok/s
    #: measures demand, not capacity
    correction_min_utilization: float = 0.8
    #: elastic live resharding policy (MorphDecision on the ``reshard``
    #: subject); None = the planner never morphs (replica scaling only)
    morph: Optional[MorphConfig] = None


class Planner:
    def __init__(
        self,
        telemetry: TelemetryAggregator,
        capacity: CapacityModel,
        config: Optional[PlannerConfig] = None,
        scale_driver=None,
        publisher=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = config or PlannerConfig()
        self.telemetry = telemetry
        self.capacity = capacity
        self.scale_driver = scale_driver
        self.publisher = publisher
        self._clock = clock
        self.decode_guard = ScaleGuard(self.cfg.decode_guard, clock)
        self.prefill_guard = ScaleGuard(self.cfg.prefill_guard, clock)
        # morph rails: the SAME guard implementation paces TP degree
        # changes that paces replica counts — min/max clamp to the
        # configured degree range, scale-down hysteresis = shrink
        # hysteresis, so morphs can't flap on a bursty prompt mix
        self.morph_guard: Optional[ScaleGuard] = None
        if self.cfg.morph is not None:
            self.cfg.morph.validate()
            # the guard's clamp IS the degree range
            self.cfg.morph.guard.min_replicas = self.cfg.morph.tp_min
            self.cfg.morph.guard.max_replicas = self.cfg.morph.tp_max
            self.morph_guard = ScaleGuard(self.cfg.morph.guard, clock,
                                          initial=self.cfg.morph.tp_min)
        self.last_morph: Optional[MorphDecision] = None
        self._relayout_seen: set[int] = set()
        self.slo = SloEvaluator(self.cfg.slo, clock)
        self.req_forecast = HoltForecaster()
        self.prompt_forecast = HoltForecaster()
        self.gen_forecast = HoltForecaster()
        self.last_decision: Optional[PlannerDecision] = None
        self.last_watermark: Optional[CapacityWatermark] = None
        self.stats = {"ticks": 0, "scale_ups": 0, "scale_downs": 0,
                      "ttft_breach_ticks": 0, "itl_breach_ticks": 0}
        self._task: Optional[asyncio.Task] = None

    # ---------------- the control step ----------------

    def tick(self) -> PlannerDecision:
        self.stats["ticks"] += 1
        snap = self.telemetry.snapshot()
        self.req_forecast.update(snap.request_rate)
        self.prompt_forecast.update(snap.prompt_token_rate)
        self.gen_forecast.update(snap.gen_token_rate)

        # seed the guards from the live fleet on first sight, so the
        # planner's baseline is what actually runs, not a config guess
        if self.decode_guard.current is None and snap.decode_replicas:
            self.decode_guard.apply(snap.decode_replicas)
        cur_decode = (
            self.decode_guard.current
            if self.decode_guard.current is not None
            else max(snap.decode_replicas, 1)
        )
        cur_prefill = (
            self.prefill_guard.current
            if self.prefill_guard.current is not None
            else self.cfg.prefill_guard.min_replicas
        )

        # online capacity correction — only when the fleet is loaded
        # enough that throughput measures capacity rather than demand
        if (
            snap.gen_token_rate > 0
            and snap.decode_replicas
            and snap.slot_utilization >= self.cfg.correction_min_utilization
        ):
            self.capacity.observe_decode(
                snap.gen_token_rate, snap.decode_replicas
            )

        # 1. demand floor from the forecast
        h = self.cfg.forecast_horizon
        gen_f = self.gen_forecast.forecast(h)
        prompt_f = self.prompt_forecast.forecast(h)
        want_decode = self.capacity.decode_replicas_for(
            gen_f, self.cfg.headroom
        )
        want_prefill = (
            self.capacity.prefill_replicas_for(prompt_f, self.cfg.headroom)
            if self.cfg.prefill_pool and prompt_f > 0
            else self.cfg.prefill_guard.min_replicas
        )

        # 2. SLO push
        status = self.slo.evaluate(snap.ttft_p99_ms, snap.itl_p99_ms)
        reason = "demand" if want_decode != cur_decode else "steady"
        if status.ttft_breached:
            self.stats["ttft_breach_ticks"] += 1
        if status.itl_breached:
            self.stats["itl_breach_ticks"] += 1
        if status.ttft_sustained:
            reason = "ttft_breach"
            if self.cfg.prefill_pool:
                want_prefill = max(want_prefill, cur_prefill + 1)
            else:
                want_decode = max(want_decode, cur_decode + 1)
        if status.itl_sustained:
            reason = "itl_breach"
            want_decode = max(want_decode, cur_decode + 1)

        # 3. guard rails
        decode_n = self.decode_guard.apply(want_decode)
        prefill_n = self.prefill_guard.apply(want_prefill)
        moved = decode_n != cur_decode or (
            self.cfg.prefill_pool and prefill_n != cur_prefill
        )
        held = want_decode != decode_n or (
            self.cfg.prefill_pool and want_prefill != prefill_n
        )
        if not moved and held and reason in ("demand", "ttft_breach",
                                             "itl_breach"):
            # the guards vetoed every desired change this tick
            reason = "cooldown_hold"

        # 4. actuate
        if self.scale_driver is not None:
            try:
                self.scale_driver.set_replicas("decode", decode_n)
                if self.cfg.prefill_pool:
                    self.scale_driver.set_replicas("prefill", prefill_n)
            except Exception:  # noqa: BLE001 — a broken actuator must
                logger.exception("scale driver failed")  # not kill the loop

        decision = PlannerDecision(
            ts=self._clock(),
            decode_replicas=decode_n,
            prefill_replicas=prefill_n if self.cfg.prefill_pool else 0,
            reason=reason,
            request_rate=round(snap.request_rate, 6),
            prompt_token_rate=round(snap.prompt_token_rate, 6),
            gen_token_rate=round(snap.gen_token_rate, 6),
            ttft_p99_ms=snap.ttft_p99_ms or 0.0,
            itl_p99_ms=snap.itl_p99_ms or 0.0,
            disagg_ratio=round(
                prefill_n / max(prefill_n + decode_n, 1), 6
            ) if self.cfg.prefill_pool else 0.0,
        )
        watermark = self._watermark(snap, decision)
        if self.publisher is not None:
            try:
                self.publisher.publish(decision, watermark)
            except Exception:  # noqa: BLE001
                logger.exception("planner publish failed")
        # the third verb: morph the pool's parallelism degree (guarded)
        morph = self._evaluate_morph(snap)
        if morph is not None:
            self.stats["morphs"] = self.stats.get("morphs", 0) + 1
            self.last_morph = morph
            if self.publisher is not None:
                publish_morph = getattr(self.publisher, "publish_morph",
                                        None)
                if publish_morph is not None:
                    try:
                        publish_morph(morph)
                    except Exception:  # noqa: BLE001
                        logger.exception("morph publish failed")
        self._fold_action_stats()
        self.last_decision = decision
        self.last_watermark = watermark
        return decision

    def _evaluate_morph(self, snap: ClusterSnapshot) -> Optional[MorphDecision]:
        """One guarded morph evaluation per tick (None = no change).
        Lost-host relayouts pre-empt degree policy: survivors must be
        re-laid at the CURRENT degree before growth/shrink reasoning
        about them means anything."""
        mc, guard = self.cfg.morph, self.morph_guard
        if mc is None or guard is None:
            return None
        observed = snap.pool_tp
        if (
            self.last_morph is None
            and observed > 0
            and guard.current != observed
        ):
            # seed the rails from the pool's ACTUALLY-deployed degree
            # (workers advertise mesh_tp): a planner starting against a
            # TP=4 fleet must not reason from tp_min — its first
            # lost-host relayout would otherwise "restore" every
            # survivor to a degree the pool never ran, and a grow from
            # the fictional floor would actually SHRINK. Only before
            # the first morph: after that, actuation lag (workers
            # mid-morph still advertising the old degree) must not
            # re-seed the guard backwards and flap
            guard.current = min(max(observed, mc.tp_min), mc.tp_max)
        cur = guard.current if guard.current is not None else mc.tp_min
        new_lost = [w for w in snap.lost_workers
                    if w not in self._relayout_seen]
        if new_lost:
            self._relayout_seen.update(new_lost)
            return MorphDecision(
                ts=self._clock(), worker_id=0, tp=cur,
                reason="relayout_lost_host", hold=mc.hold, force=True,
                lost_workers=new_lost,
            )
        # degree policy: long-prompt-dominated grows (TP halves the
        # per-worker prefill wall where another replica would not);
        # sustained idle shrinks back to the floor
        desired = cur
        if (
            snap.request_rate > 0
            and snap.mean_prompt_tokens >= mc.grow_prompt_tokens
        ):
            desired = cur * 2
        elif (
            snap.slot_utilization < mc.shrink_utilization
            and snap.mean_prompt_tokens < mc.grow_prompt_tokens
        ):
            desired = mc.tp_min
        applied = guard.apply(desired)
        if applied == cur:
            return None
        return MorphDecision(
            ts=self._clock(), worker_id=0, tp=applied,
            reason="grow_tp" if applied > cur else "shrink_tp",
            hold=mc.hold,
        )

    def _watermark(self, snap: ClusterSnapshot,
                   decision: PlannerDecision) -> CapacityWatermark:
        # admission rate = corrected decode capacity at headroom,
        # converted to req/s via the observed tokens-per-request mix;
        # 0 (= leave the gate alone) until there's a real mix to use
        rate = 0.0
        if snap.request_rate > 0 and snap.gen_token_rate > 0:
            mean_gen = snap.gen_token_rate / snap.request_rate
            rate = (
                self.capacity.decode_tok_s(decision.decode_replicas)
                * self.cfg.headroom / max(mean_gen, 1e-9)
            )
        return CapacityWatermark(
            ts=decision.ts,
            saturated_workers=snap.saturated_workers(
                self.cfg.watermark_slot_frac, self.cfg.watermark_kv_frac
            ),
            cluster_utilization=round(snap.slot_utilization, 6),
            admission_rate_req_s=round(rate, 6),
            disagg_ratio=decision.disagg_ratio,
        )

    def _fold_action_stats(self) -> None:
        ups = downs = 0
        for g in (self.decode_guard, self.prefill_guard):
            ups += sum(1 for a in g.actions if a.direction == "up")
            downs += sum(1 for a in g.actions if a.direction == "down")
        self.stats["scale_ups"] = ups
        self.stats["scale_downs"] = downs

    # ---------------- metrics surface ----------------

    def render_stats(self) -> dict:
        out = {f"planner_{k}": v for k, v in self.stats.items()}
        d = self.last_decision
        if d is not None:
            out["planner_decode_replicas"] = d.decode_replicas
            out["planner_prefill_replicas"] = d.prefill_replicas
            out["planner_disagg_ratio"] = d.disagg_ratio
        w = self.last_watermark
        if w is not None:
            out["planner_saturated_workers"] = len(w.saturated_workers)
            out["planner_admission_rate_req_s"] = w.admission_rate_req_s
        m = self.last_morph
        if m is not None:
            out["planner_morphs_total"] = self.stats.get("morphs", 0)
            out["planner_morph_tp"] = m.tp
            out["planner_morph_reason"] = m.reason
        return out

    # ---------------- async loop ----------------

    async def run(self) -> None:
        while True:
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("planner tick failed")
            await asyncio.sleep(self.cfg.tick_s)

    def start(self) -> "Planner":
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self.run())
        return self

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

"""Planner wire schema: scale decisions, capacity watermarks, morphs.

Three subjects, published on the target component (same bus idiom as
the kv_router's ``kv-hit-rate``/``kv-prefetch`` events):

  * ``planner-decisions`` — one :class:`PlannerDecision` per control
    tick: the replica counts the planner wants per pool, the SLO view
    that justified them, and the disagg-ratio hint. The metrics
    component renders these as gauges; operators replay them to audit
    why the fleet resized.
  * ``planner-watermarks`` — :class:`CapacityWatermark`: which workers
    the planner considers saturated (the KV scheduler soft-excludes
    them from routing) and the admission rate the frontend's overload
    gate should hold (0 = leave the gate's configured rate alone).
  * ``reshard`` — :class:`MorphDecision`: the planner's third verb
    beside scale-up/down. Instead of adding/removing whole replicas it
    asks a pool (or one worker) to MORPH its parallelism degree live
    (docs/elastic_resharding.md): grow TP when long prompts dominate,
    shrink when sustained idle, re-lay survivors after a lost host.
    Workers actuate through a :class:`~dynamo_tpu.resilience.reshard.
    ReshardListener` → ``JaxEngine.reshard``; decisions pass the same
    :class:`~dynamo_tpu.planner.guard.ScaleGuard` rails as replica
    counts, so morphs can't flap.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

PLANNER_DECISION_SUBJECT = "planner-decisions"
PLANNER_WATERMARK_SUBJECT = "planner-watermarks"
PLANNER_RESHARD_SUBJECT = "reshard"


@dataclass
class PlannerDecision:
    ts: float = 0.0
    decode_replicas: int = 0
    prefill_replicas: int = 0
    #: why the counts moved (or didn't): "demand", "ttft_breach",
    #: "itl_breach", "steady", "cooldown_hold", ...
    reason: str = "steady"
    request_rate: float = 0.0  # observed req/s over the telemetry window
    prompt_token_rate: float = 0.0
    gen_token_rate: float = 0.0
    ttft_p99_ms: float = 0.0  # 0 = no samples in window
    itl_p99_ms: float = 0.0
    #: prefill share of the fleet the planner is steering toward —
    #: prefill_replicas / (prefill + decode); the KV router records it
    #: as its disagg-ratio hint
    disagg_ratio: float = 0.0

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "PlannerDecision":
        d = json.loads(raw)
        return PlannerDecision(**{
            k: d[k] for k in PlannerDecision().__dict__ if k in d
        })


@dataclass
class MorphDecision:
    """One live-reshard request on the ``reshard`` subject.

    ``worker_id=0`` addresses every worker in the pool (a pool-wide
    degree change, or a survivor re-layout after a lost host); a
    non-zero id targets one worker. ``tp`` is the requested tensor-
    parallel degree (the only axis today's policy morphs; dp/pp/sp/ep
    ride the same machinery through ``JaxEngine.reshard`` when a future
    policy wants them). ``hold`` asks workers to hold in-flight streams
    through the morph; False = hand them off via the migration path
    first (deadline-pressured pools). ``force`` re-lays even at an
    unchanged shape — the lost-host case, where the logical degree
    stays put but the surviving device set must re-resolve."""

    ts: float = 0.0  # dynlint: disable=dead-wire-field -- wall-clock stamp for the operator audit trail (replayed decisions); actuation is ordering-free by design
    worker_id: int = 0
    pool: str = "decode"
    tp: int = 1
    #: why: "grow_tp" (long-prompt-dominated), "shrink_tp" (sustained
    #: idle), "relayout_lost_host", ...
    reason: str = "steady"
    hold: bool = True
    force: bool = False
    #: worker ids that vanished from telemetry (lost-host evidence,
    #: observability only — workers don't need it to actuate)
    lost_workers: list = field(default_factory=list)  # dynlint: disable=dead-wire-field -- evidence payload for operators auditing WHY a relayout fired; actuation keys on force/tp alone by design

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> Optional["MorphDecision"]:
        d = json.loads(raw)
        return MorphDecision(**{
            k: d[k] for k in MorphDecision().__dict__ if k in d
        })


@dataclass
class CapacityWatermark:
    ts: float = 0.0  # dynlint: disable=dead-wire-field -- wall-clock stamp for the operator audit trail; receipt-time staleness is tracked scheduler-side (watermark_ttl_s)
    #: workers at/over the saturation watermark: the KV scheduler must
    #: stop routing NEW work at them while they drain their queues
    saturated_workers: list[int] = field(default_factory=list)
    #: fleet slot utilization (0..1) behind the watermark decision
    cluster_utilization: float = 0.0
    #: admission rate (req/s) the frontend gate should hold; 0 = don't
    #: touch the gate's configured rate
    admission_rate_req_s: float = 0.0
    #: mirror of PlannerDecision.disagg_ratio for routers that only
    #: subscribe watermarks
    disagg_ratio: float = 0.0

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> "CapacityWatermark":
        d = json.loads(raw)
        return CapacityWatermark(**{
            k: d[k] for k in CapacityWatermark().__dict__ if k in d
        })

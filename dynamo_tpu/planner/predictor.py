"""Load forecasting + capacity modeling + SLO evaluation.

Three small, pure pieces the planner composes (each deterministic —
no wall clock, no randomness — so the control loop is unit-testable
tick by tick):

  * :class:`HoltForecaster` — Holt's linear (double-exponential)
    smoothing over the observed request/token arrival rates: level +
    trend, so a ramp is extrapolated instead of chased one tick late.
  * :class:`CapacityModel` — per-replica serving rates (decode tok/s,
    prefill tok/s). Seeded from the roofline model's per-config
    estimates (perf/roofline.py) and corrected online by an EWMA of
    observed/modeled throughput, clamped so one bad sample can't wreck
    the plan.
  * :class:`SloEvaluator` — TTFT/ITL p99 targets with a grace window:
    a breach only counts once it has been sustained for
    ``grace_s`` (transient spikes must not resize the fleet).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, Optional


class HoltForecaster:
    """Holt's linear trend method: ``level`` tracks the smoothed rate,
    ``trend`` its per-update slope; ``forecast(h)`` extrapolates h
    updates ahead (floored at 0 — a negative arrival rate is noise)."""

    def __init__(self, alpha: float = 0.5, beta: float = 0.3):
        if not (0.0 < alpha <= 1.0 and 0.0 <= beta <= 1.0):
            raise ValueError("alpha in (0,1], beta in [0,1]")
        self.alpha = alpha
        self.beta = beta
        self.level: Optional[float] = None
        self.trend = 0.0

    def update(self, y: float) -> None:
        if self.level is None:
            self.level = float(y)
            return
        prev = self.level
        self.level = self.alpha * y + (1 - self.alpha) * (self.level + self.trend)
        self.trend = self.beta * (self.level - prev) + (1 - self.beta) * self.trend

    def forecast(self, horizon: float = 1.0) -> float:
        if self.level is None:
            return 0.0
        return max(0.0, self.level + horizon * self.trend)


class CapacityModel:
    """Per-replica tok/s for the two pools, roofline-seeded and
    observation-corrected.

    ``observe_decode/prefill(observed_tok_s, replicas)`` folds the
    measured fleet throughput into a multiplicative correction factor
    (EWMA of observed / modeled, clamped to ``corr_bounds``) — the
    roofline gives the shape, production gives the scale."""

    def __init__(
        self,
        decode_tok_s_per_replica: float,
        prefill_tok_s_per_replica: float,
        correction_alpha: float = 0.2,
        corr_bounds: tuple[float, float] = (0.25, 4.0),
    ):
        if decode_tok_s_per_replica <= 0 or prefill_tok_s_per_replica <= 0:
            raise ValueError("per-replica rates must be > 0")
        self.decode_seed = decode_tok_s_per_replica
        self.prefill_seed = prefill_tok_s_per_replica
        self.alpha = correction_alpha
        self.corr_bounds = corr_bounds
        self.decode_corr = 1.0
        self.prefill_corr = 1.0

    @staticmethod
    def from_roofline(scenario) -> "CapacityModel":
        """Seed from one roofline Scenario: decode = modeled decode
        tok/s/chip x chips-per-replica; prefill = isl / modeled prefill
        time (prompt tokens one replica prefills per second)."""
        from ..perf.roofline import analyze

        rec = analyze(scenario)
        decode = rec["decode_tok_s_chip_modeled"] * rec["n_chips"]
        prefill = scenario.isl / max(
            rec["ttft_prefill_modeled_ms"] / 1e3, 1e-9
        )
        return CapacityModel(decode, prefill)

    # -- online correction --

    def _fold(self, corr: float, observed: float, modeled: float) -> float:
        if observed <= 0 or modeled <= 0:
            return corr
        sample = observed / modeled
        lo, hi = self.corr_bounds
        return min(hi, max(lo, (1 - self.alpha) * corr + self.alpha * sample))

    def observe_decode(self, observed_tok_s: float, replicas: int) -> None:
        self.decode_corr = self._fold(
            self.decode_corr, observed_tok_s, self.decode_seed * max(replicas, 1)
        )

    def observe_prefill(self, observed_tok_s: float, replicas: int) -> None:
        self.prefill_corr = self._fold(
            self.prefill_corr, observed_tok_s, self.prefill_seed * max(replicas, 1)
        )

    # -- corrected capacity --

    def decode_tok_s(self, replicas: int = 1) -> float:
        return self.decode_seed * self.decode_corr * max(replicas, 0)

    def prefill_tok_s(self, replicas: int = 1) -> float:
        return self.prefill_seed * self.prefill_corr * max(replicas, 0)

    def decode_replicas_for(self, token_rate: float, headroom: float = 0.8) -> int:
        """Replicas needed to serve ``token_rate`` gen-tok/s at
        ``headroom`` target utilization (never 0 — an idle fleet still
        keeps a warm replica; the guard's min bound also enforces this)."""
        per = self.decode_tok_s(1) * max(min(headroom, 1.0), 1e-6)
        return max(1, math.ceil(token_rate / max(per, 1e-9)))

    def prefill_replicas_for(self, token_rate: float, headroom: float = 0.8) -> int:
        per = self.prefill_tok_s(1) * max(min(headroom, 1.0), 1e-6)
        return max(1, math.ceil(token_rate / max(per, 1e-9)))


@dataclass
class SloTargets:
    ttft_p99_ms: float = 2000.0
    itl_p99_ms: float = 200.0
    #: a breach must be sustained this long before it drives scaling
    grace_s: float = 10.0


@dataclass
class SloStatus:
    ttft_breached: bool = False  # instantaneous
    itl_breached: bool = False
    ttft_sustained: bool = False  # breached continuously for >= grace_s
    itl_sustained: bool = False

    @property
    def any_sustained(self) -> bool:
        return self.ttft_sustained or self.itl_sustained


class SloEvaluator:
    """Tracks how long each SLO has been continuously breached; a
    missing sample (no traffic in the window) clears the breach — an
    idle cluster is not violating anything."""

    def __init__(
        self,
        targets: Optional[SloTargets] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.targets = targets or SloTargets()
        self._clock = clock
        self._ttft_since: Optional[float] = None
        self._itl_since: Optional[float] = None

    def _track(self, since: Optional[float], breached: bool,
               now: float) -> tuple[Optional[float], bool]:
        if not breached:
            return None, False
        if since is None:
            since = now
        return since, (now - since) >= self.targets.grace_s

    def evaluate(self, ttft_p99_ms: Optional[float],
                 itl_p99_ms: Optional[float]) -> SloStatus:
        now = self._clock()
        st = SloStatus()
        st.ttft_breached = bool(
            ttft_p99_ms and ttft_p99_ms > self.targets.ttft_p99_ms
        )
        st.itl_breached = bool(
            itl_p99_ms and itl_p99_ms > self.targets.itl_p99_ms
        )
        self._ttft_since, st.ttft_sustained = self._track(
            self._ttft_since, st.ttft_breached, now
        )
        self._itl_since, st.itl_sustained = self._track(
            self._itl_since, st.itl_breached, now
        )
        return st

"""Planner actuators: replica-count drivers + bus publication.

The planner *decides*; these carry the decision out:

  * :class:`StoreScaleDriver` — targets the deploy controller's replica
    API: rewrites the service's ``replicas`` in the
    :class:`~dynamo_tpu.deploy.api_server.DeploymentStore` and lets the
    controller's reconcile loop converge processes. Scale-down is
    drain-aware for free: the controller terminates excess replicas
    with SIGTERM, which the worker's DrainCoordinator (resilience/
    drain.py) turns into deregister -> finish-or-hand-off -> lease
    revoke — the planner never has to pick a victim or kill anything
    itself.
  * :class:`CallbackScaleDriver` — embedding/test hook: records every
    (pool, replicas) application and forwards to an optional callable.
  * :class:`BusPublisher` — publishes :class:`PlannerDecision` and
    :class:`CapacityWatermark` events on the target component's
    subjects for the KV router, frontends, and the metrics component.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from dynamo_tpu.http.base import HttpError

from .protocols import (
    PLANNER_DECISION_SUBJECT,
    PLANNER_RESHARD_SUBJECT,
    PLANNER_WATERMARK_SUBJECT,
    CapacityWatermark,
    MorphDecision,
    PlannerDecision,
)

logger = logging.getLogger(__name__)


class CallbackScaleDriver:
    """Records applications; optionally forwards to ``fn(pool, n)``."""

    def __init__(self, fn: Optional[Callable[[str, int], None]] = None):
        self._fn = fn
        self.applied: list[tuple[str, int]] = []
        self.replicas: dict[str, int] = {}

    def set_replicas(self, pool: str, n: int) -> bool:
        if self.replicas.get(pool) == n:
            return False
        self.replicas[pool] = n
        self.applied.append((pool, n))
        if self._fn is not None:
            self._fn(pool, n)
        return True

    def current(self, pool: str) -> Optional[int]:
        return self.replicas.get(pool)


class StoreScaleDriver:
    """Writes replica counts into one DynamoDeployment's services.

    ``pools`` maps the planner's pool names to service names in the
    deployment (e.g. ``{"decode": "worker", "prefill": "prefill"}``);
    a pool with no mapped service is ignored (aggregated clusters have
    no prefill pool to size)."""

    def __init__(self, store, deployment: str,
                 pools: Optional[dict[str, str]] = None):
        self.store = store
        self.deployment = deployment
        self.pools = pools or {"decode": "worker", "prefill": "prefill"}

    def current(self, pool: str) -> Optional[int]:
        svc_name = self.pools.get(pool)
        if svc_name is None:
            return None
        try:
            spec = self.store.get(self.deployment)
        except (KeyError, HttpError):
            return None
        for svc in spec.get("services", []):
            if svc.get("name") == svc_name:
                return int(svc.get("replicas", 1))
        return None

    def set_replicas(self, pool: str, n: int) -> bool:
        svc_name = self.pools.get(pool)
        if svc_name is None:
            return False
        try:
            spec = self.store.get(self.deployment)
        except (KeyError, HttpError):
            logger.warning("planner target deployment %r missing",
                           self.deployment)
            return False
        for svc in spec.get("services", []):
            if svc.get("name") == svc_name:
                if int(svc.get("replicas", 1)) == n:
                    return False
                svc["replicas"] = int(n)
                self.store.put(self.deployment, spec, create=False)
                logger.info("planner: %s/%s replicas -> %d",
                            self.deployment, svc_name, n)
                return True
        logger.warning("planner pool %r: service %r not in deployment %r",
                       pool, svc_name, self.deployment)
        return False


class BusPublisher:
    """Best-effort event publication (a lost decision event costs
    observability, never correctness — the next tick republishes)."""

    def __init__(self, drt, component):
        self.drt = drt
        self._decision_subject = component.event_subject(
            PLANNER_DECISION_SUBJECT
        )
        self._watermark_subject = component.event_subject(
            PLANNER_WATERMARK_SUBJECT
        )
        self._reshard_subject = component.event_subject(
            PLANNER_RESHARD_SUBJECT
        )
        self.published = 0
        self.morphs_published = 0

    def publish(self, decision: PlannerDecision,
                watermark: CapacityWatermark) -> None:
        # dynflow: publishes=PLANNER_DECISION_SUBJECT,PLANNER_WATERMARK_SUBJECT
        for subject, ev in (
            (self._decision_subject, decision),
            (self._watermark_subject, watermark),
        ):
            try:
                self.drt.bus.publish(subject, ev.to_bytes())
                self.published += 1
            except Exception:  # noqa: BLE001
                logger.debug("planner publish failed", exc_info=True)

    def publish_morph(self, morph: MorphDecision) -> None:
        """One MorphDecision on the ``reshard`` control subject — the
        workers' ReshardListeners actuate it (resilience/reshard.py).
        Best-effort like the rest: the guard's state survives a lost
        event and an unchanged desire republishes on the next trigger."""
        try:
            self.drt.bus.publish(self._reshard_subject, morph.to_bytes())
            self.morphs_published += 1
        except Exception:  # noqa: BLE001
            logger.debug("morph publish failed", exc_info=True)

"""Model discovery: store-watched hot add/remove of served models.

Re-design of the reference's model discovery (lib/llm/src/http/service/
discovery.rs:38-145 + launch/llmctl): workers (or an operator CLI) register
a ``ModelEntry`` at ``public/models/{type}/{name}`` pointing at a component
endpoint; the HTTP frontend watches the prefix and hot-adds/removes models
from its ModelManager as workers come and go. Entries registered under a
worker's lease vanish with the worker — frontends need no health checks.

Workers serve the *full* OpenAI surface (request dict in, chunk dicts out)
— the frontend stays tokenizer-free and stateless; KV-aware routing runs in
a processor/router component behind the same endpoint scheme (see
dynamo_tpu.kv_router).
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass
from typing import AsyncIterator, Optional

from ..protocols.openai import ChatCompletionRequest, CompletionRequest
from ..runtime.annotated import Annotated
from ..runtime.component import Client
from ..runtime.engine import AsyncEngine, Context
from ..runtime.store import EventKind
from .service import ModelManager

logger = logging.getLogger(__name__)

MODEL_ROOT = "public/models"


@dataclass
class ModelEntry:
    """ref: llmctl ModelEntry (launch/llmctl/src/main.rs:16-100)."""

    name: str
    namespace: str
    component: str
    endpoint: str
    model_type: str = "chat"  # "chat" | "completion" | "both"
    instance: int = 0  # registering worker's lease id — one entry per worker

    def key(self) -> str:
        return f"{MODEL_ROOT}/{self.model_type}/{self.name}/{self.instance:x}"

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(raw: bytes) -> "ModelEntry":
        return ModelEntry(**json.loads(raw))


async def register_model(drt, entry: ModelEntry, use_lease: bool = True) -> None:
    """llmctl add: register under this process's lease so the entry dies
    with the worker."""
    lease = drt.primary_lease_id if use_lease else 0
    if entry.instance == 0:
        entry.instance = drt.primary_lease_id
    put = drt.store.kv_put(entry.key(), entry.to_json(), lease_id=lease)
    if asyncio.iscoroutine(put):
        await put


async def unregister_model(drt, model_type: str, name: str) -> int:
    """llmctl remove: drop every worker's entry for this model."""
    deleted = drt.store.kv_delete_prefix(f"{MODEL_ROOT}/{model_type}/{name}/")
    if asyncio.iscoroutine(deleted):
        deleted = await deleted
    return int(deleted)


async def list_models(drt) -> list[ModelEntry]:
    entries = drt.store.kv_get_prefix(MODEL_ROOT + "/")
    if asyncio.iscoroutine(entries):
        entries = await entries
    return [ModelEntry.from_json(e.value) for e in entries]


from ..runtime.component import EngineClient


class RemoteOpenAIEngine(EngineClient):
    """EngineClient variant speaking raw OpenAI dicts: unwraps typed
    requests to their original JSON before pushing (the worker runs its own
    pre/post-processing)."""

    async def generate(self, request: Context) -> AsyncIterator[Annotated]:
        data = request.data
        if isinstance(data, (ChatCompletionRequest, CompletionRequest)):
            request = request.transfer(data.raw)
        async for item in super().generate(request):
            yield item


class ModelWatcher:
    """ref discovery.rs:58 model_watcher."""

    def __init__(self, drt, manager: ModelManager):
        self.drt = drt
        self.manager = manager
        self._task: Optional[asyncio.Task] = None
        self._clients: dict[str, Client] = {}
        self._entries: dict[str, ModelEntry] = {}

    async def start(self) -> "ModelWatcher":
        watcher = self.drt.store.watch_prefix(MODEL_ROOT + "/")
        if asyncio.iscoroutine(watcher):
            watcher = await watcher
        for e in watcher.snapshot:
            await self._add(ModelEntry.from_json(e.value))
        self._task = self.drt.runtime.spawn(self._watch(watcher))
        return self

    async def _watch(self, watcher) -> None:
        async for ev in watcher:
            try:
                if ev.kind == EventKind.PUT:
                    await self._add(ModelEntry.from_json(ev.value))
                elif ev.kind == EventKind.DELETE:
                    self._remove_by_key(ev.key)
                # RESUMED: post-reconnect reconcile marker — the replayed
                # puts/deletes above already brought the registry current
            except Exception:  # noqa: BLE001
                logger.exception("model watcher error for %s", ev.key)

    async def _add(self, entry: ModelEntry) -> None:
        key = entry.key()
        old = self._clients.pop(key, None)
        if old is not None:
            old.stop()  # worker re-registered under the same key
        client = await (
            self.drt.namespace(entry.namespace)
            .component(entry.component)
            .endpoint(entry.endpoint)
            .client()
            .start()
        )
        self._clients[key] = client
        self._entries[key] = entry
        engine = RemoteOpenAIEngine(client)
        if entry.model_type in ("chat", "both"):
            self.manager.add_chat_model(entry.name, engine)
        if entry.model_type in ("completion", "both"):
            self.manager.add_completion_model(entry.name, engine)
        logger.info("discovered model %s -> %s/%s/%s",
                    entry.name, entry.namespace, entry.component, entry.endpoint)

    def _remove_by_key(self, key: str) -> None:
        entry = self._entries.pop(key, None)
        client = self._clients.pop(key, None)
        if client is not None:
            client.stop()
        if entry is None:
            return
        # only drop the model when no other live worker still serves it
        still_served = any(
            e.name == entry.name and e.model_type == entry.model_type
            for e in self._entries.values()
        )
        if still_served:
            return
        if entry.model_type in ("chat", "both"):
            self.manager.remove_chat_model(entry.name)
        if entry.model_type in ("completion", "both"):
            self.manager.remove_completion_model(entry.name)
        logger.info("removed model %s", entry.name)

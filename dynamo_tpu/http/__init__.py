"""OpenAI-compatible HTTP frontend (re-design of lib/llm/src/http)."""

from .metrics import Metrics
from .service import HttpService, ModelManager

__all__ = ["HttpService", "Metrics", "ModelManager"]

"""Prometheus-style service metrics.

Re-design of the reference's HTTP metrics (lib/llm/src/http/service/
metrics.rs:36-311): request counters by (model, endpoint, status), an
inflight gauge with an RAII guard, and request-duration histograms, all
rendered in the Prometheus text exposition format at /metrics — no
prometheus client dependency needed.

The latency families are real fixed-bucket histograms
(observability/hist.py, log-spaced bounds + ``+Inf``), labeled by
``model``, ``endpoint`` and ``slo_class`` — the exact
``dynamo_tpu_http_service_*_seconds_bucket`` series the shipped Grafana
dashboard queries, and the frontend half of the SLO observatory
(docs/observability.md). ``slo_breaches_total`` counts requests the
flight recorder autopsied (observability/flight.py).

The family names below are module-level constants on purpose: the
dynflow ``dashboard-metric-without-producer`` rule reads them as this
module's advertised render surface, so a dashboard query with no
producer (or a renamed family with a stale panel) fails CI instead of
flatlining in Grafana.
"""

from __future__ import annotations

import logging
import time
from collections import defaultdict

from ..observability.hist import TIME_BUCKETS_S, HistogramVec

logger = logging.getLogger(__name__)

#: label slo_class when no admission gate classified the request
DEFAULT_SLO_CLASS = "interactive"

# rendered family names (suffixed onto the ``dynamo_tpu`` prefix)
REQUESTS_TOTAL = "http_service_requests_total"
INFLIGHT_REQUESTS = "http_service_inflight_requests"
REQUEST_DURATION_SECONDS = "http_service_request_duration_seconds"
FIRST_TOKEN_SECONDS = "http_service_first_token_seconds"
INTER_TOKEN_SECONDS = "http_service_inter_token_seconds"
TOKENS_TOTAL = "tokens_total"
SLO_BREACHES_TOTAL = "slo_breaches_total"

#: histogram label schema shared by the three latency families
_HIST_LABELS = ("model", "endpoint", "slo_class")


class Metrics:
    def __init__(self, prefix: str = "dynamo_tpu"):
        self.prefix = prefix
        self.requests_total: dict[tuple, int] = defaultdict(int)
        self.inflight: dict[tuple, int] = defaultdict(int)
        self.duration = HistogramVec(
            REQUEST_DURATION_SECONDS, _HIST_LABELS, TIME_BUCKETS_S
        )
        self.tokens_total: dict[tuple, int] = defaultdict(int)
        # serving-latency histograms (BASELINE targets: p50/p99 TTFT, ITL)
        self.first_token = HistogramVec(
            FIRST_TOKEN_SECONDS, _HIST_LABELS, TIME_BUCKETS_S
        )
        self.inter_token = HistogramVec(
            INTER_TOKEN_SECONDS, _HIST_LABELS, TIME_BUCKETS_S
        )
        # SLO observatory: breaches the flight recorder confirmed
        # ((model, slo_class) -> count; observability/flight.py calls
        # observe_breach when it writes the autopsy)
        self.slo_breaches: dict[tuple, int] = defaultdict(int)
        # extra scrape sources: () -> {metric_suffix: number}, rendered as
        # plain gauges — lets subsystems (e.g. the migration wrapper's
        # migrations_total) surface counters at /metrics without coupling
        self._sources: list = []
        # optional planner.TelemetryAggregator: first/inter-token
        # observations double as the embedded planner's TTFT/ITL
        # samples (the SLO evaluator's inputs)
        self.planner_telemetry = None

    def register_source(self, fn) -> None:
        self._sources.append(fn)

    def inflight_guard(self, model: str, endpoint: str,
                       slo_class: str = DEFAULT_SLO_CLASS) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, slo_class)

    def observe_tokens(self, model: str, kind: str, n: int) -> None:
        self.tokens_total[(model, kind)] += n

    def observe_first_token(self, model: str, endpoint: str, v: float,
                            slo_class: str = DEFAULT_SLO_CLASS) -> None:
        self.first_token.labels(model, endpoint, slo_class).observe(v)
        if self.planner_telemetry is not None:
            self.planner_telemetry.record_ttft(v * 1e3)

    def observe_inter_token(self, model: str, endpoint: str, v: float,
                            slo_class: str = DEFAULT_SLO_CLASS) -> None:
        self.inter_token.labels(model, endpoint, slo_class).observe(v)
        if self.planner_telemetry is not None:
            self.planner_telemetry.record_itl(v * 1e3)

    def observe_breach(self, model: str, slo_class: str) -> None:
        """One SLO breach (flight-recorder confirmed — breach counting
        and autopsy persistence stay in lockstep)."""
        self.slo_breaches[(model, slo_class)] += 1

    def render(self) -> str:
        p = self.prefix
        lines = [
            f"# TYPE {p}_{REQUESTS_TOTAL} counter",
        ]
        for (model, endpoint, status), v in sorted(self.requests_total.items()):
            lines.append(
                f'{p}_{REQUESTS_TOTAL}{{model="{model}",endpoint="{endpoint}",status="{status}"}} {v}'
            )
        lines.append(f"# TYPE {p}_{INFLIGHT_REQUESTS} gauge")
        for (model, endpoint), v in sorted(self.inflight.items()):
            lines.append(
                f'{p}_{INFLIGHT_REQUESTS}{{model="{model}",endpoint="{endpoint}"}} {v}'
            )
        for vec in (self.duration, self.first_token, self.inter_token):
            lines.extend(vec.render(p))
        lines.append(f"# TYPE {p}_{TOKENS_TOTAL} counter")
        for (model, kind), v in sorted(self.tokens_total.items()):
            lines.append(f'{p}_{TOKENS_TOTAL}{{model="{model}",kind="{kind}"}} {v}')
        lines.append(f"# TYPE {p}_{SLO_BREACHES_TOTAL} counter")
        for (model, slo_class), v in sorted(self.slo_breaches.items()):
            lines.append(
                f'{p}_{SLO_BREACHES_TOTAL}{{model="{model}",slo_class="{slo_class}"}} {v}'
            )
        for src in self._sources:
            try:
                for k, v in sorted(src().items()):
                    lines.append(f"{p}_{k} {v}")
            except Exception:  # noqa: BLE001 — a bad source must not
                # break the whole exposition
                logger.debug("metrics source failed", exc_info=True)
        return "\n".join(lines) + "\n"


class InflightGuard:
    """RAII inflight gauge + status-coded counter (ref metrics.rs:187-311
    InflightGuard)."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str,
                 slo_class: str = DEFAULT_SLO_CLASS):
        self._m = metrics
        self._key = (model, endpoint)
        self.slo_class = slo_class
        self._status = "error"
        self._start = time.monotonic()
        self._last_token_t: float | None = None
        #: first-token latency in ms once observed (the flight recorder
        #: reads it at finish to judge the request against its SLO)
        self.ttft_ms: float | None = None
        metrics.inflight[self._key] += 1

    def observe_token(self) -> None:
        """Per-streamed-chunk timing: the first call records TTFT, later
        calls record inter-token gaps."""
        now = time.monotonic()
        model, endpoint = self._key
        if self._last_token_t is None:
            ttft = now - self._start
            self.ttft_ms = ttft * 1e3
            self._m.observe_first_token(model, endpoint, ttft, self.slo_class)
        else:
            self._m.observe_inter_token(
                model, endpoint, now - self._last_token_t, self.slo_class
            )
        self._last_token_t = now

    def mark_ok(self) -> None:
        self._status = "success"

    def mark(self, status: str) -> None:
        self._status = status

    @property
    def status(self) -> str:
        return self._status

    @property
    def elapsed_ms(self) -> float:
        return (time.monotonic() - self._start) * 1e3

    def done(self) -> None:
        m, (model, endpoint) = self._m, self._key
        m.inflight[self._key] -= 1
        m.requests_total[(model, endpoint, self._status)] += 1
        m.duration.labels(model, endpoint, self.slo_class).observe(
            time.monotonic() - self._start
        )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self._status == "error":
            self.mark_ok()
        self.done()
        return False

"""Prometheus-style service metrics.

Re-design of the reference's HTTP metrics (lib/llm/src/http/service/
metrics.rs:36-311): request counters by (model, endpoint, status), an
inflight gauge with an RAII guard, and request-duration histograms, all
rendered in the Prometheus text exposition format at /metrics — no
prometheus client dependency needed.
"""

from __future__ import annotations

import logging
import time
from collections import defaultdict

logger = logging.getLogger(__name__)

_BUCKETS = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0]


class Histogram:
    def __init__(self):
        self.counts = [0] * (len(_BUCKETS) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.n += 1
        self.total += v
        for i, b in enumerate(_BUCKETS):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class Metrics:
    def __init__(self, prefix: str = "dynamo_tpu"):
        self.prefix = prefix
        self.requests_total: dict[tuple, int] = defaultdict(int)
        self.inflight: dict[tuple, int] = defaultdict(int)
        self.duration: dict[tuple, Histogram] = defaultdict(Histogram)
        self.tokens_total: dict[tuple, int] = defaultdict(int)
        # serving-latency histograms (BASELINE targets: p50/p99 TTFT, ITL)
        self.first_token: dict[tuple, Histogram] = defaultdict(Histogram)
        self.inter_token: dict[tuple, Histogram] = defaultdict(Histogram)
        # extra scrape sources: () -> {metric_suffix: number}, rendered as
        # plain gauges — lets subsystems (e.g. the migration wrapper's
        # migrations_total) surface counters at /metrics without coupling
        self._sources: list = []
        # optional planner.TelemetryAggregator: first/inter-token
        # observations double as the embedded planner's TTFT/ITL
        # samples (the SLO evaluator's inputs)
        self.planner_telemetry = None

    def register_source(self, fn) -> None:
        self._sources.append(fn)

    def inflight_guard(self, model: str, endpoint: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint)

    def observe_tokens(self, model: str, kind: str, n: int) -> None:
        self.tokens_total[(model, kind)] += n

    def observe_first_token(self, model: str, endpoint: str, v: float) -> None:
        self.first_token[(model, endpoint)].observe(v)
        if self.planner_telemetry is not None:
            self.planner_telemetry.record_ttft(v * 1e3)

    def observe_inter_token(self, model: str, endpoint: str, v: float) -> None:
        self.inter_token[(model, endpoint)].observe(v)
        if self.planner_telemetry is not None:
            self.planner_telemetry.record_itl(v * 1e3)

    def render(self) -> str:
        p = self.prefix
        lines = [
            f"# TYPE {p}_http_service_requests_total counter",
        ]
        for (model, endpoint, status), v in sorted(self.requests_total.items()):
            lines.append(
                f'{p}_http_service_requests_total{{model="{model}",endpoint="{endpoint}",status="{status}"}} {v}'
            )
        lines.append(f"# TYPE {p}_http_service_inflight_requests gauge")
        for (model, endpoint), v in sorted(self.inflight.items()):
            lines.append(
                f'{p}_http_service_inflight_requests{{model="{model}",endpoint="{endpoint}"}} {v}'
            )
        for name, table in (
            ("request_duration_seconds", self.duration),
            ("first_token_seconds", self.first_token),
            ("inter_token_seconds", self.inter_token),
        ):
            lines.append(f"# TYPE {p}_http_service_{name} histogram")
            for (model, endpoint), h in sorted(table.items()):
                cum = 0
                for i, b in enumerate(_BUCKETS):
                    cum += h.counts[i]
                    lines.append(
                        f'{p}_http_service_{name}_bucket{{model="{model}",endpoint="{endpoint}",le="{b}"}} {cum}'
                    )
                cum += h.counts[-1]
                lines.append(
                    f'{p}_http_service_{name}_bucket{{model="{model}",endpoint="{endpoint}",le="+Inf"}} {cum}'
                )
                lines.append(
                    f'{p}_http_service_{name}_sum{{model="{model}",endpoint="{endpoint}"}} {h.total}'
                )
                lines.append(
                    f'{p}_http_service_{name}_count{{model="{model}",endpoint="{endpoint}"}} {h.n}'
                )
        lines.append(f"# TYPE {p}_tokens_total counter")
        for (model, kind), v in sorted(self.tokens_total.items()):
            lines.append(f'{p}_tokens_total{{model="{model}",kind="{kind}"}} {v}')
        for src in self._sources:
            try:
                for k, v in sorted(src().items()):
                    lines.append(f"{p}_{k} {v}")
            except Exception:  # noqa: BLE001 — a bad source must not
                # break the whole exposition
                logger.debug("metrics source failed", exc_info=True)
        return "\n".join(lines) + "\n"


class InflightGuard:
    """RAII inflight gauge + status-coded counter (ref metrics.rs:187-311
    InflightGuard)."""

    def __init__(self, metrics: Metrics, model: str, endpoint: str):
        self._m = metrics
        self._key = (model, endpoint)
        self._status = "error"
        self._start = time.monotonic()
        self._last_token_t: float | None = None
        metrics.inflight[self._key] += 1

    def observe_token(self) -> None:
        """Per-streamed-chunk timing: the first call records TTFT, later
        calls record inter-token gaps."""
        now = time.monotonic()
        model, endpoint = self._key
        if self._last_token_t is None:
            self._m.observe_first_token(model, endpoint, now - self._start)
        else:
            self._m.observe_inter_token(
                model, endpoint, now - self._last_token_t
            )
        self._last_token_t = now

    def mark_ok(self) -> None:
        self._status = "success"

    def mark(self, status: str) -> None:
        self._status = status

    def done(self) -> None:
        m, (model, endpoint) = self._m, self._key
        m.inflight[self._key] -= 1
        m.requests_total[(model, endpoint, self._status)] += 1
        m.duration[self._key].observe(time.monotonic() - self._start)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self._status == "error":
            self.mark_ok()
        self.done()
        return False

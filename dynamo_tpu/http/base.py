"""Dependency-free asyncio HTTP/1.1 server base.

The plumbing shared by the OpenAI frontend (http/service.py) and the
deploy api-server (deploy/api_server.py): request parsing (content-length
and chunked), keep-alive, JSON error envelopes. Subclasses implement
``_route`` (the reference splits the same way: axum plumbing vs per-
service routers, lib/llm/src/http/service/service_v2.rs vs
deploy/dynamo/api-server routes).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

logger = logging.getLogger(__name__)

_STATUS_TEXT = {
    200: "OK", 201: "Created", 204: "No Content",
    400: "Bad Request", 404: "Not Found", 405: "Method Not Allowed",
    409: "Conflict", 422: "Unprocessable Entity",
    429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}


class HttpError(Exception):
    def __init__(self, status: int, message: str,
                 code: str = "invalid_request_error",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.status = status
        self.message = message
        self.code = code
        # > 0 = emit a Retry-After header (admission-gate sheds: the
        # client is told when capacity is expected back)
        self.retry_after_s = retry_after_s


class HttpServerBase:
    #: reject request bodies larger than this (anti memory-exhaustion: the
    #: body is buffered in full before routing)
    max_body_bytes: int = 64 * 1024 * 1024

    def __init__(self, host: str = "0.0.0.0", port: int = 8080):
        self._host, self._port = host, port
        self._server: Optional[asyncio.base_events.Server] = None
        self.port: int = port

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self._host, self._port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("%s listening on %s:%d", type(self).__name__, self._host, self.port)

    async def run(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def close(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _route(self, method, path, headers, body, writer) -> None:
        raise NotImplementedError

    # ---------------- http plumbing ----------------

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except ValueError:
                    # malformed framing (bad content-length / chunk size)
                    await self._send_json(
                        writer, 400,
                        {"error": {"message": "malformed request framing",
                                   "type": "invalid_request_error"}},
                    )
                    break
                if req is None:
                    break
                method, path, headers, body = req
                keep_alive = headers.get("connection", "").lower() != "close"
                try:
                    await self._route(method, path, headers, body, writer)
                except HttpError as e:
                    extra = (
                        {"Retry-After": str(int(max(e.retry_after_s, 1)))}
                        if e.retry_after_s > 0 else None
                    )
                    await self._send_json(
                        writer, e.status,
                        {"error": {"message": e.message, "type": e.code}},
                        extra_headers=extra,
                    )
                except (ConnectionResetError, BrokenPipeError):
                    break
                except Exception as e:  # noqa: BLE001
                    logger.exception("handler error")
                    try:
                        await self._send_json(
                            writer, 500,
                            {"error": {"message": str(e), "type": "internal_error"}},
                        )
                    except (ConnectionResetError, BrokenPipeError):
                        break
                if not keep_alive:
                    break
        except (ConnectionResetError, asyncio.IncompleteReadError, BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 — peer may already be gone
                logger.debug("http connection teardown failed", exc_info=True)

    async def _read_request(self, reader: asyncio.StreamReader):
        try:
            request_line = await reader.readline()
        except (ConnectionResetError, asyncio.LimitOverrunError):
            return None
        if not request_line:
            return None
        try:
            method, path, _version = request_line.decode().split(None, 2)
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode().partition(":")
            headers[name.strip().lower()] = value.strip()
        body = b""
        length = int(headers.get("content-length", 0) or 0)
        if length > self.max_body_bytes:
            raise ValueError(f"body {length} exceeds limit {self.max_body_bytes}")
        if length:
            body = await reader.readexactly(length)
        elif headers.get("transfer-encoding", "").lower() == "chunked":
            chunks = []
            total = 0
            while True:
                size_line = await reader.readline()
                # RFC 7230: ignore chunk extensions after ';'
                size = int(size_line.split(b";", 1)[0].strip() or b"0", 16)
                if size == 0:
                    await reader.readline()
                    break
                total += size
                if total > self.max_body_bytes:
                    raise ValueError(f"chunked body exceeds limit {self.max_body_bytes}")
                chunks.append(await reader.readexactly(size))
                await reader.readline()
            body = b"".join(chunks)
        return method.upper(), path, headers, body

    @staticmethod
    async def _send_response(
        writer: asyncio.StreamWriter,
        status: int,
        body: bytes,
        content_type: str = "application/json",
        extra_headers: Optional[dict] = None,
    ) -> None:
        head = (
            f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Unknown')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
        )
        for k, v in (extra_headers or {}).items():
            head += f"{k}: {v}\r\n"
        head += "\r\n"
        writer.write(head.encode() + body)
        await writer.drain()

    async def _send_json(self, writer, status: int, obj,
                         extra_headers: Optional[dict] = None) -> None:
        await self._send_response(
            writer, status, json.dumps(obj).encode(),
            extra_headers=extra_headers,
        )

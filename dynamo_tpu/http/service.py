"""OpenAI-compatible HTTP frontend.

Re-design of the reference's axum HTTP service (lib/llm/src/http/service/
{service_v2,openai}.rs): routes /v1/chat/completions, /v1/completions,
/v1/models, /metrics, /health. The service always streams from the engine
and folds for non-streaming clients (ref http/service.rs:24-26); client
disconnects kill the request context so TPU work is cancelled end-to-end
(ref openai.rs client-disconnect handling).

The server is a dependency-free asyncio HTTP/1.1 implementation — the
Python-idiomatic equivalent of the reference's axum layer, with SSE
streaming via chunked transfer encoding.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from .. import tracing
from ..protocols.aggregator import aggregate_chat_chunks, aggregate_completion_chunks
from ..protocols.openai import ChatCompletionRequest, CompletionRequest, RequestError
from ..protocols.sse import encode_comment, encode_data, encode_done, encode_event
from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, AsyncEngineContext, Context
from .base import HttpError, HttpServerBase, _STATUS_TEXT  # noqa: F401 — HttpError re-exported
from .metrics import DEFAULT_SLO_CLASS, Metrics

logger = logging.getLogger(__name__)


def _chunk_has_tokens(data) -> bool:
    """True when an SSE chunk carries generated content — finish-only and
    usage-only chunks must not pollute the TTFT/ITL histograms."""
    if not isinstance(data, dict):
        return True  # raw engine items (tests/custom engines) count
    choices = data.get("choices") or []
    for c in choices:
        delta = c.get("delta") or {}
        if delta.get("content") or c.get("text"):
            return True
        msg = c.get("message") or {}
        if msg.get("content"):
            return True
    return False


class ModelManager:
    """Live model registry (ref http/service.rs:58 ModelManager): model name
    -> engine, hot add/remove as workers come and go."""

    def __init__(self):
        self._chat: dict[str, AsyncEngine] = {}
        self._completion: dict[str, AsyncEngine] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self._chat[name] = engine

    def add_completion_model(self, name: str, engine: AsyncEngine) -> None:
        self._completion[name] = engine

    def remove_chat_model(self, name: str) -> None:
        self._chat.pop(name, None)

    def remove_completion_model(self, name: str) -> None:
        self._completion.pop(name, None)

    def chat_engine(self, name: str) -> Optional[AsyncEngine]:
        return self._chat.get(name)

    def completion_engine(self, name: str) -> Optional[AsyncEngine]:
        return self._completion.get(name)

    def model_names(self) -> list[str]:
        return sorted(set(self._chat) | set(self._completion))


class HttpService(HttpServerBase):
    """ref service_v2.rs:24 HttpService + builder."""

    def __init__(
        self,
        model_manager: Optional[ModelManager] = None,
        host: str = "0.0.0.0",
        port: int = 8080,
        metrics: Optional[Metrics] = None,
        trace_collector=None,
        admission=None,
        flight=None,
        profiler=None,
    ):
        super().__init__(host=host, port=port)
        self.models = model_manager or ModelManager()
        self.metrics = metrics or Metrics()
        # tracing.TraceCollector serving /trace/{request_id} (None = off)
        self.tracing = trace_collector
        # observability.FlightRecorder (None = off): every finished
        # request is recorded; SLO breaches / error finishes persist an
        # autopsy served at /autopsy/{request_id}
        self.flight = None
        if flight is not None:
            self.attach_flight(flight)
        # async callable (seconds -> trace dir) running jax.profiler on
        # the serving engine; wired by dynamo_run when the engine is
        # in-process (None = POST /profile answers 501)
        self.profiler = profiler
        # planner.AdmissionGate overload control (None = admit all):
        # shed requests get 429 + Retry-After BEFORE touching the
        # engine, so admitted requests keep their SLO under overload
        self.admission = admission
        if admission is not None:
            self.metrics.register_source(admission.render_stats)
        # client-supplied request ids currently in flight: a duplicate
        # would key cross-request shared state (worker inflight map,
        # disagg transfer futures) onto one id — the second request
        # falls back to a minted uuid instead
        self._inflight_ids: set[str] = set()

    def attach_flight(self, flight) -> None:
        """Wire a FlightRecorder to this service: its counters join the
        /metrics exposition, and breach counting drives
        ``slo_breaches_total`` so the counter and the autopsy inventory
        can never drift apart."""
        self.flight = flight
        self.metrics.register_source(flight.counters)
        if flight.on_breach is None:
            flight.on_breach = self.metrics.observe_breach

    # ---------------- routing ----------------

    async def _route(self, method, path, headers, body, writer) -> None:
        path, _, query = path.partition("?")
        if method == "GET":
            if path in ("/health", "/live", "/ready"):
                await self._send_json(writer, 200, {"status": "ok"})
            elif path == "/metrics":
                await self._send_response(
                    writer, 200, self.metrics.render().encode(),
                    content_type="text/plain; version=0.0.4",
                )
            elif path == "/v1/models":
                data = [
                    {"id": name, "object": "model", "owned_by": "dynamo_tpu"}
                    for name in self.models.model_names()
                ]
                await self._send_json(writer, 200, {"object": "list", "data": data})
            elif path.startswith("/trace/") or path == "/trace":
                await self._trace_endpoint(writer, path, query)
            elif path.startswith("/autopsy/") or path == "/autopsy":
                await self._autopsy_endpoint(writer, path)
            else:
                raise HttpError(404, f"no route for GET {path}", "not_found")
        elif method == "POST":
            if path == "/v1/chat/completions":
                await self._openai_endpoint(writer, headers, body, chat=True)
            elif path == "/v1/completions":
                await self._openai_endpoint(writer, headers, body, chat=False)
            elif path == "/profile":
                await self._profile_endpoint(writer, query)
            else:
                raise HttpError(404, f"no route for POST {path}", "not_found")
        else:
            raise HttpError(405, f"method {method} not allowed")

    # ---------------- tracing endpoint ----------------

    async def _trace_endpoint(self, writer, path: str, query: str) -> None:
        """``GET /trace/{request_id}[?format=chrome]`` — the assembled
        per-request timeline + TTFT decomposition (or Chrome trace-event
        JSON); ``GET /trace`` lists collected trace ids + aggregate
        percentiles."""
        if self.tracing is None:
            raise HttpError(404, "tracing is not enabled", "tracing_disabled")
        if path in ("/trace", "/trace/"):
            await self._send_json(writer, 200, {
                "traces": self.tracing.trace_ids(),
                "ttft_percentiles_ms": self.tracing.percentiles(),
            })
            return
        trace_id = path[len("/trace/"):]
        fmt = "chrome" if "format=chrome" in query else "timeline"
        body = self.tracing.render_trace(trace_id, fmt=fmt)
        if body is None:
            raise HttpError(404, f"no trace for {trace_id!r}", "trace_not_found")
        if fmt == "timeline":
            body = {"request_id": trace_id, **body}
        await self._send_json(writer, 200, body)

    # ---------------- flight recorder + profiler ----------------

    async def _autopsy_endpoint(self, writer, path: str) -> None:
        """``GET /autopsy/{request_id}`` — the persisted slow-request
        autopsy (timeline + decomposition + engine/sanitizer/compile
        snapshots); ``GET /autopsy`` lists autopsied request ids."""
        if self.flight is None:
            raise HttpError(
                404, "flight recorder is not enabled", "flight_disabled"
            )
        if path in ("/autopsy", "/autopsy/"):
            await self._send_json(writer, 200, {
                "autopsies": self.flight.autopsy_ids(),
                "records_total": self.flight.recorded_total,
                "autopsies_total": self.flight.autopsies_total,
            })
            return
        rid = path[len("/autopsy/"):]
        body = self.flight.autopsy(rid)
        if body is None:
            raise HttpError(404, f"no autopsy for {rid!r}", "autopsy_not_found")
        await self._send_json(writer, 200, body)

    async def _profile_endpoint(self, writer, query: str) -> None:
        """``POST /profile?seconds=N`` — run ``jax.profiler`` on the
        in-process engine for N seconds and return the trace path."""
        if self.profiler is None:
            raise HttpError(
                501, "profiler is not wired on this frontend "
                "(in-process engine required)", "profiler_unavailable",
            )
        import math

        seconds = 2.0
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if k == "seconds" and v:
                try:
                    seconds = float(v)
                except ValueError:
                    raise HttpError(400, f"bad seconds={v!r}") from None
                if not math.isfinite(seconds):
                    # nan slides through min/max clamps (every NaN
                    # comparison is False) straight into time.sleep
                    raise HttpError(400, f"bad seconds={v!r}")
        seconds = min(max(seconds, 0.1), 120.0)
        try:
            trace_dir = await self.profiler(seconds)
        except Exception as e:  # noqa: BLE001 — surface, don't 500-loop
            raise HttpError(
                500, f"profiler failed: {type(e).__name__}: {e}",
                "profiler_error",
            ) from None
        await self._send_json(
            writer, 200, {"trace_dir": trace_dir, "seconds": seconds}
        )

    # ---------------- openai endpoints (ref openai.rs:132,214) ----------------

    @staticmethod
    def _client_request_id(headers: dict) -> Optional[str]:
        """Honor a client-supplied ``X-Request-Id`` (so client logs
        correlate with traces) — sanitized: printable, bounded, no
        whitespace. Anything unusable falls back to a minted uuid."""
        rid = (headers.get("x-request-id") or "").strip()
        if 0 < len(rid) <= 128 and all(33 <= ord(c) <= 126 for c in rid):
            return rid
        return None

    async def _openai_endpoint(self, writer, headers: dict, body: bytes, chat: bool) -> None:
        endpoint = "chat_completions" if chat else "completions"
        try:
            payload = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            raise HttpError(400, f"invalid JSON body: {e}") from None
        try:
            req = (
                ChatCompletionRequest.from_dict(payload)
                if chat
                else CompletionRequest.from_dict(payload)
            )
        except RequestError as e:
            raise HttpError(400, str(e)) from None

        engine = (
            self.models.chat_engine(req.model)
            if chat
            else self.models.completion_engine(req.model)
        )
        if engine is None:
            raise HttpError(
                404, f"model {req.model!r} not found", "model_not_found"
            )

        slo_class: Optional[str] = None
        if self.admission is not None:
            # overload gate: classify by nvext annotation (["slo:batch"])
            # — falling back to the model's configured SLO pool
            # (AdmissionGate.model_classes) — and admit/shed before any
            # engine work is queued
            slo_class = self.admission.classify(
                getattr(getattr(req, "nvext", None), "annotations", None),
                model=req.model,
            )
            decision = self.admission.admit(slo_class)
            if not decision.admitted:
                self.metrics.requests_total[
                    (req.model, endpoint, "shed")
                ] += 1
                tracing.event(
                    "frontend.shed", slo_class=slo_class,
                    reason=decision.reason,
                )
                raise HttpError(
                    429,
                    f"overloaded ({decision.reason}); retry after "
                    f"{decision.retry_after_s:.0f}s",
                    "overloaded",
                    retry_after_s=decision.retry_after_s,
                )

        guard = self.metrics.inflight_guard(
            req.model, endpoint, slo_class or DEFAULT_SLO_CLASS
        )
        client_rid = self._client_request_id(headers)
        if client_rid is not None:
            if client_rid in self._inflight_ids:
                logger.warning(
                    "duplicate in-flight X-Request-Id %r; minting fresh id",
                    client_rid,
                )
                client_rid = None
            else:
                self._inflight_ids.add(client_rid)
        context = Context(req, AsyncEngineContext(client_rid))
        if slo_class is not None:
            # downstream planes (router, engine queues, traces) see the
            # request's SLO class
            context.annotations["slo_class"] = slo_class
        req_span = tracing.NULL_SPAN
        trace_token = None
        if tracing.enabled():
            # root the request's trace here (honoring an incoming
            # traceparent); the contextvar scopes this handler task, so
            # the preprocessor/router/client-egress spans all join it
            tc = tracing.TraceContext.for_request(
                context.id, headers.get(tracing.TRACEPARENT_HEADER)
            )
            trace_token = tracing.set_trace(tc)
            req_span = tracing.span(
                "frontend.request", request_id=context.id,
                model=req.model, endpoint=endpoint,
            )
        try:
            stream = engine.generate(context)
            if req.stream:
                await self._stream_sse(writer, stream, context, req, guard)
            else:
                chunks: list[dict] = []
                error: Optional[str] = None
                first_token = True
                async for item in stream:
                    ann = item if isinstance(item, Annotated) else Annotated.from_data(item)
                    if ann.is_error():
                        error = ann.error or "engine error"
                        break
                    if ann.data is not None:
                        # the engine streams internally even for folded
                        # responses — TTFT/ITL are still real
                        if _chunk_has_tokens(ann.data):
                            guard.observe_token()
                            if first_token:
                                first_token = False
                                tracing.event(
                                    "frontend.first_token",
                                    request_id=context.id,
                                )
                        chunks.append(ann.data)
                if error is not None:
                    guard.mark("error")
                    raise HttpError(500, error, "engine_error")
                if not chunks:
                    guard.mark("error")
                    raise HttpError(500, "engine produced no output", "engine_error")
                full = (
                    aggregate_chat_chunks(chunks)
                    if chat
                    else aggregate_completion_chunks(chunks)
                )
                self._count_tokens(req.model, full)
                guard.mark_ok()
                await self._send_json(writer, 200, full)
        finally:
            elapsed_ms = guard.elapsed_ms
            guard.done()
            # close the request span BEFORE the flight recorder judges
            # the finish: the decomposition needs the frontend.request
            # anchor in the collector, or a breach autopsy would carry
            # a timeline that can't decompose
            req_span.end()
            if self.flight is not None:
                # per-worker attribution: the KV router stamps the pinned
                # instance on the shared annotations dict (autopilot
                # quarantine evidence) — absent on round-robin fallbacks
                rw = context.annotations.get("routed_worker_id")
                self.flight.finish(
                    context.id, req.model, guard.slo_class, guard.status,
                    guard.ttft_ms, elapsed_ms,
                    worker_id=rw if isinstance(rw, int) else None,
                )
            if slo_class is not None:
                self.admission.done(slo_class)
            if client_rid is not None:
                self._inflight_ids.discard(client_rid)
            if trace_token is not None:
                tracing.reset_trace(trace_token)

    def _count_tokens(self, model: str, full: dict) -> None:
        usage = full.get("usage") or {}
        if usage.get("prompt_tokens"):
            self.metrics.observe_tokens(model, "prompt", usage["prompt_tokens"])
        if usage.get("completion_tokens"):
            self.metrics.observe_tokens(model, "completion", usage["completion_tokens"])

    async def _stream_sse(self, writer, stream, context: Context, req, guard) -> None:
        head = (
            "HTTP/1.1 200 OK\r\n"
            "Content-Type: text/event-stream\r\n"
            "Cache-Control: no-cache\r\n"
            "Transfer-Encoding: chunked\r\n"
            "\r\n"
        )
        writer.write(head.encode())
        await writer.drain()

        async def send(chunk: bytes):
            writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
            await writer.drain()

        include_usage = bool(getattr(req, "stream_options", {}).get("include_usage"))
        ok = True
        first_token = True
        try:
            try:
                async for item in stream:
                    ann = item if isinstance(item, Annotated) else Annotated.from_data(item)
                    if ann.is_error():
                        await send(encode_event("error", {"message": ann.error}))
                        ok = False
                        break
                    if ann.event and ann.event != "sentinel":
                        await send(encode_event(ann.event,
                            json.loads(ann.comment[0]) if ann.comment else None))
                        continue
                    if ann.data is not None:
                        data = ann.data
                        if isinstance(data, dict) and data.get("usage") is not None:
                            self._count_tokens(req.model, data)
                            if not include_usage:
                                data = {k: v for k, v in data.items() if k != "usage"}
                        if _chunk_has_tokens(data):
                            guard.observe_token()  # TTFT / ITL histograms
                            if first_token:
                                first_token = False
                                tracing.event(
                                    "frontend.first_token",
                                    request_id=context.id,
                                )
                        await send(encode_data(data))
            except (ConnectionResetError, BrokenPipeError):
                raise
            except Exception as e:  # noqa: BLE001
                # engine failure mid-stream: the 200 + SSE head is already on
                # the wire, so surface it as an SSE error event, never as a
                # second HTTP response on the same socket
                logger.exception("engine error mid-stream")
                await send(encode_event("error", {"message": str(e)}))
                ok = False
            await send(encode_done())
        except (ConnectionResetError, BrokenPipeError):
            # client went away: kill generation end-to-end (ref openai.rs)
            context.context.kill()
            guard.mark("disconnect")
            return
        # end chunked body
        try:
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            context.context.kill()
        if ok:
            guard.mark_ok()

"""Engine-internal request/response protocol.

Re-design of the reference's common protocols
(lib/llm/src/protocols/common/{preprocessor,llm_backend}.rs, common.rs):
the *preprocessed* request (token ids + stop conditions + sampling options)
that flows frontend->worker, and the per-step engine output (token ids +
finish reason) that flows back. These are the only types the TPU engine
sees — all OpenAI surface area is translated away by the preprocessor.
"""

from __future__ import annotations

import enum
from dataclasses import asdict, dataclass, field
from typing import Any, Optional


class FinishReason(str, enum.Enum):
    EOS = "eos"
    LENGTH = "length"
    STOP = "stop"
    CANCELLED = "cancelled"
    ERROR = "error"

    def to_openai(self) -> str:
        return {
            FinishReason.EOS: "stop",
            FinishReason.STOP: "stop",
            FinishReason.LENGTH: "length",
            FinishReason.CANCELLED: "stop",
            FinishReason.ERROR: "error",
        }[self]


@dataclass
class StopConditions:
    """ref: protocols/common.rs StopConditions."""

    max_tokens: Optional[int] = None
    stop: list[str] = field(default_factory=list)
    stop_token_ids: list[int] = field(default_factory=list)
    min_tokens: Optional[int] = None
    ignore_eos: bool = False

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "StopConditions":
        return StopConditions(**{k: d[k] for k in d if k in StopConditions.__dataclass_fields__})


@dataclass
class SamplingOptions:
    """ref: protocols/common.rs SamplingOptions."""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    n: int = 1
    logprobs: Optional[int] = None

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "SamplingOptions":
        return SamplingOptions(
            **{k: d[k] for k in d if k in SamplingOptions.__dataclass_fields__}
        )


@dataclass
class PreprocessedRequest:
    """What the frontend sends to a worker
    (ref: protocols/common/preprocessor.rs:25 PreprocessedRequest)."""

    token_ids: list[int]
    stop_conditions: StopConditions = field(default_factory=StopConditions)
    sampling_options: SamplingOptions = field(default_factory=SamplingOptions)
    model: str = ""
    eos_token_ids: list[int] = field(default_factory=list)
    annotations: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "token_ids": self.token_ids,
            "stop_conditions": self.stop_conditions.to_dict(),
            "sampling_options": self.sampling_options.to_dict(),
            "model": self.model,
            "eos_token_ids": self.eos_token_ids,
            "annotations": self.annotations,
        }

    @staticmethod
    def from_dict(d: dict) -> "PreprocessedRequest":
        return PreprocessedRequest(
            token_ids=list(d["token_ids"]),
            stop_conditions=StopConditions.from_dict(d.get("stop_conditions", {})),
            sampling_options=SamplingOptions.from_dict(d.get("sampling_options", {})),
            model=d.get("model", ""),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            annotations=d.get("annotations", {}),
        )


@dataclass
class LLMEngineOutput:
    """One streamed step from the engine
    (ref: protocols/common/llm_backend.rs:27 LLMEngineOutput)."""

    token_ids: list[int] = field(default_factory=list)
    text: Optional[str] = None
    cum_log_probs: Optional[float] = None
    # per-token logprob entries aligned with token_ids (filled when the
    # request asked for logprobs): {"logprob": float, "top": [[id, lp]..]},
    # enriched with token text by the detokenizer stage
    logprobs: Optional[list] = None
    finish_reason: Optional[FinishReason] = None
    # usage accounting (filled by the engine on the final chunk)
    prompt_tokens: Optional[int] = None
    completion_tokens: Optional[int] = None
    # KV routing hints
    kv_overlap_blocks: Optional[int] = None

    def is_final(self) -> bool:
        return self.finish_reason is not None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"token_ids": self.token_ids}
        if self.text is not None:
            d["text"] = self.text
        if self.cum_log_probs is not None:
            d["cum_log_probs"] = self.cum_log_probs
        if self.logprobs is not None:
            d["logprobs"] = self.logprobs
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason.value
        if self.prompt_tokens is not None:
            d["prompt_tokens"] = self.prompt_tokens
        if self.completion_tokens is not None:
            d["completion_tokens"] = self.completion_tokens
        return d

    @staticmethod
    def from_dict(d: dict) -> "LLMEngineOutput":
        fr = d.get("finish_reason")
        return LLMEngineOutput(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text"),
            cum_log_probs=d.get("cum_log_probs"),
            logprobs=d.get("logprobs"),
            finish_reason=FinishReason(fr) if fr else None,
            prompt_tokens=d.get("prompt_tokens"),
            completion_tokens=d.get("completion_tokens"),
        )

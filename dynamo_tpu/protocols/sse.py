"""Server-Sent Events codec.

Re-design of the reference's SSE codec (lib/llm/src/protocols/codec.rs:
16-50): encode JSON payloads as ``data:`` lines with the OpenAI
``data: [DONE]`` terminator, and incrementally parse SSE byte streams back
into events (used by the aggregator tests and by clients).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

DONE = "[DONE]"


@dataclass
class SseEvent:
    data: Optional[str] = None
    event: Optional[str] = None
    comments: list[str] = field(default_factory=list)
    id: Optional[str] = None

    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == DONE

    def json(self) -> Any:
        if self.data is None:
            return None
        return json.loads(self.data)


def encode_data(obj: Any) -> bytes:
    """data: {json}\n\n"""
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"


def encode_event(event: str, obj: Any = None) -> bytes:
    out = b"event: " + event.encode() + b"\n"
    if obj is not None:
        out += b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n"
    return out + b"\n"


def encode_comment(text: str) -> bytes:
    return b": " + text.encode() + b"\n\n"


def encode_done() -> bytes:
    return b"data: [DONE]\n\n"


class SseParser:
    """Incremental SSE parser: feed bytes, iterate complete events."""

    def __init__(self):
        self._buf = b""

    def feed(self, chunk: bytes) -> Iterator[SseEvent]:
        self._buf += chunk
        while True:
            # events are separated by a blank line
            sep = self._find_sep()
            if sep is None:
                return
            block, self._buf = self._buf[: sep[0]], self._buf[sep[1] :]
            ev = self._parse_block(block.decode("utf-8", errors="replace"))
            if ev is not None:
                yield ev

    def _find_sep(self):
        for sep in (b"\r\n\r\n", b"\n\n", b"\r\r"):
            idx = self._buf.find(sep)
            if idx != -1:
                return idx, idx + len(sep)
        return None

    @staticmethod
    def _parse_block(block: str) -> Optional[SseEvent]:
        ev = SseEvent()
        data_lines: list[str] = []
        seen = False
        for line in block.splitlines():
            if not line:
                continue
            seen = True
            if line.startswith(":"):
                ev.comments.append(line[1:].lstrip())
                continue
            if ":" in line:
                fieldname, _, value = line.partition(":")
                value = value[1:] if value.startswith(" ") else value
            else:
                fieldname, value = line, ""
            if fieldname == "data":
                data_lines.append(value)
            elif fieldname == "event":
                ev.event = value
            elif fieldname == "id":
                ev.id = value
        if not seen:
            return None
        if data_lines:
            ev.data = "\n".join(data_lines)
        return ev


def parse_sse_stream(raw: bytes) -> list[SseEvent]:
    p = SseParser()
    events = list(p.feed(raw))
    return events

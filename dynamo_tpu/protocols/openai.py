"""OpenAI-compatible API types + the `nvext` extension namespace.

Re-design of the reference's protocols/openai/* (which wraps the
async-openai crate types): plain dataclasses with permissive from_dict
parsing — unknown fields are ignored, so clients built against richer
OpenAI SDKs still work. The ``nvext`` extension carries engine-specific
knobs (ref protocols/openai/nvext.rs:28: ignore_eos, use_raw_prompt,
annotations...).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from .common import SamplingOptions, StopConditions


class RequestError(ValueError):
    """400-level validation error."""


@dataclass
class NvExt:
    ignore_eos: bool = False
    use_raw_prompt: bool = False
    greed_sampling: bool = False
    annotations: list[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[dict]) -> "NvExt":
        if not d:
            return NvExt()
        return NvExt(
            ignore_eos=bool(d.get("ignore_eos", False)),
            use_raw_prompt=bool(d.get("use_raw_prompt", False)),
            greed_sampling=bool(d.get("greed_sampling", False)),
            annotations=list(d.get("annotations", [])),
        )


@dataclass
class ChatMessage:
    role: str
    content: Any = None  # str or content-part list
    name: Optional[str] = None
    tool_calls: Optional[list] = None

    def content_text(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        # content-part list: concatenate text parts
        parts = []
        for part in self.content:
            if isinstance(part, dict) and part.get("type") == "text":
                parts.append(part.get("text", ""))
        return "".join(parts)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"role": self.role, "content": self.content}
        if self.name:
            d["name"] = self.name
        if self.tool_calls:
            d["tool_calls"] = self.tool_calls
        return d


def _common_sampling(d: dict) -> SamplingOptions:
    return SamplingOptions(
        temperature=d.get("temperature"),
        top_p=d.get("top_p"),
        top_k=d.get("top_k"),
        frequency_penalty=d.get("frequency_penalty"),
        presence_penalty=d.get("presence_penalty"),
        repetition_penalty=d.get("repetition_penalty"),
        seed=d.get("seed"),
        n=_int_field(d, "n", 1, lo=1, hi=16),
        # chat schema: logprobs (bool) + top_logprobs (int, 0..20).
        # logprobs:true alone returns each chosen token's logprob with no
        # alternates (top_logprobs defaults to 0, per the OpenAI schema).
        logprobs=(
            _int_field(d, "top_logprobs", 0, lo=0, hi=20)
            if d.get("logprobs") else None
        ),
    )


def _int_field(d: dict, key: str, default: int, lo: int, hi: int) -> int:
    """Validated int request field -> 400 on junk, not a 500."""
    v = d.get(key)
    if v is None:
        return default
    try:
        v = int(v)
    except (TypeError, ValueError):
        raise RequestError(f"{key} must be an integer") from None
    if not lo <= v <= hi:
        raise RequestError(f"{key} must be between {lo} and {hi}")
    return v


def _common_stops(d: dict, nvext: NvExt) -> StopConditions:
    stop = d.get("stop")
    if stop is None:
        stop_list: list[str] = []
    elif isinstance(stop, str):
        stop_list = [stop]
    else:
        stop_list = list(stop)
    if len(stop_list) > 4:
        raise RequestError("at most 4 stop sequences are supported")
    return StopConditions(
        max_tokens=d.get("max_completion_tokens") or d.get("max_tokens"),
        stop=stop_list,
        stop_token_ids=list(d.get("stop_token_ids", [])),
        min_tokens=d.get("min_tokens"),
        ignore_eos=nvext.ignore_eos,
    )


@dataclass
class ChatCompletionRequest:
    model: str
    messages: list[ChatMessage]
    stream: bool = False
    stream_options: dict = field(default_factory=dict)
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stops: StopConditions = field(default_factory=StopConditions)
    nvext: NvExt = field(default_factory=NvExt)
    tools: Optional[list] = None
    response_format: Optional[dict] = None
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "ChatCompletionRequest":
        if "model" not in d:
            raise RequestError("missing required field: model")
        msgs_raw = d.get("messages")
        if not msgs_raw or not isinstance(msgs_raw, list):
            raise RequestError("messages must be a non-empty list")
        messages = []
        for m in msgs_raw:
            if not isinstance(m, dict) or "role" not in m:
                raise RequestError("each message must have a role")
            messages.append(
                ChatMessage(
                    role=m["role"],
                    content=m.get("content"),
                    name=m.get("name"),
                    tool_calls=m.get("tool_calls"),
                )
            )
        nvext = NvExt.from_dict(d.get("nvext"))
        return ChatCompletionRequest(
            model=d["model"],
            messages=messages,
            stream=bool(d.get("stream", False)),
            stream_options=d.get("stream_options") or {},
            sampling=_common_sampling(d),
            stops=_common_stops(d, nvext),
            nvext=nvext,
            tools=d.get("tools"),
            response_format=d.get("response_format"),
            raw=d,
        )


@dataclass
class CompletionRequest:
    model: str
    prompt: Any  # str | list[str] | list[int]
    stream: bool = False
    stream_options: dict = field(default_factory=dict)
    echo: bool = False
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stops: StopConditions = field(default_factory=StopConditions)
    nvext: NvExt = field(default_factory=NvExt)
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "CompletionRequest":
        if "model" not in d:
            raise RequestError("missing required field: model")
        if "prompt" not in d:
            raise RequestError("missing required field: prompt")
        prompt = d["prompt"]
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], str):
            # OpenAI batched prompts need one choice per prompt; not
            # supported yet — reject rather than silently concatenate
            raise RequestError(
                "list-of-strings prompt is not supported; send one request per prompt"
            )
        nvext = NvExt.from_dict(d.get("nvext"))
        sampling = _common_sampling(d)
        # legacy completions schema: logprobs is the top-k count itself
        # (0 = chosen-token logprobs with no alternates)
        if d.get("logprobs") is not None:
            sampling.logprobs = _int_field(d, "logprobs", 0, lo=0, hi=20)
        return CompletionRequest(
            model=d["model"],
            prompt=d["prompt"],
            stream=bool(d.get("stream", False)),
            stream_options=d.get("stream_options") or {},
            echo=bool(d.get("echo", False)),
            sampling=sampling,
            stops=_common_stops(d, nvext),
            nvext=nvext,
            raw=d,
        )


# ---------------- responses ----------------


def _now() -> int:
    return int(time.time())


def new_chat_id() -> str:
    return "chatcmpl-" + uuid.uuid4().hex[:24]


def new_cmpl_id() -> str:
    return "cmpl-" + uuid.uuid4().hex[:24]


@dataclass
class Usage:
    prompt_tokens: int = 0
    completion_tokens: int = 0

    def to_dict(self) -> dict:
        return {
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "total_tokens": self.prompt_tokens + self.completion_tokens,
        }


def chat_chunk(
    id: str,
    model: str,
    delta: dict,
    finish_reason: Optional[str] = None,
    created: Optional[int] = None,
    usage: Optional[Usage] = None,
    index: int = 0,
    logprobs: Optional[dict] = None,
) -> dict:
    """One chat.completion.chunk SSE object."""
    out = {
        "id": id,
        "object": "chat.completion.chunk",
        "created": created or _now(),
        "model": model,
        "choices": [
            {"index": index, "delta": delta, "finish_reason": finish_reason,
             "logprobs": logprobs}
        ],
    }
    if usage is not None:
        out["usage"] = usage.to_dict()
    return out


def chat_logprobs_block(entries: list) -> dict:
    """OpenAI chat logprobs schema from the backend's enriched entries."""
    return {
        "content": [
            {
                "token": e.get("token", ""),
                "logprob": e.get("logprob"),
                "top_logprobs": e.get("top", []),
            }
            for e in entries
        ]
    }


def completion_logprobs_block(entries: list, start_offset: int = 0) -> dict:
    """Legacy completions logprobs schema.

    Distinct token ids can decode to the same string (BPE byte /
    whitespace pieces); the dict comprehension would silently drop all
    but the last, so collisions keep the MAX logprob (the alternative a
    client ranking by probability would want). ``text_offset`` is the
    character offset of each token in the generated text, starting at
    ``start_offset`` (the caller's running offset across stream chunks).
    """
    tops = []
    for e in entries:
        d: dict = {}
        for t in e.get("top", []):
            k = t["token"]
            if k not in d or t["logprob"] > d[k]:
                d[k] = t["logprob"]
        tops.append(d)
    offsets, off = [], start_offset
    for e in entries:
        offsets.append(off)
        off += len(e.get("token", ""))
    return {
        "tokens": [e.get("token", "") for e in entries],
        "token_logprobs": [e.get("logprob") for e in entries],
        "top_logprobs": tops,
        "text_offset": offsets,
    }


def completion_chunk(
    id: str,
    model: str,
    text: str,
    finish_reason: Optional[str] = None,
    created: Optional[int] = None,
    usage: Optional[Usage] = None,
    index: int = 0,
    logprobs: Optional[dict] = None,
) -> dict:
    out = {
        "id": id,
        "object": "text_completion",
        "created": created or _now(),
        "model": model,
        "choices": [
            {"index": index, "text": text, "finish_reason": finish_reason,
             "logprobs": logprobs}
        ],
    }
    if usage is not None:
        out["usage"] = usage.to_dict()
    return out

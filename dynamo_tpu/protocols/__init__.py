"""Wire protocols: engine-internal types, OpenAI API types, SSE codec.

Re-design of the reference's lib/llm/src/protocols/* for Python dataclasses.
"""

from .common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

__all__ = [
    "FinishReason",
    "LLMEngineOutput",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
]

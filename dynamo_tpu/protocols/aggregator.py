"""Stream -> full-response aggregation (the stream=false path).

Re-design of the reference's aggregators
(protocols/openai/chat_completions/aggregator.rs:462,
completions/aggregator.rs:343): the service always streams internally and
folds chunks into a single OpenAI response for non-streaming clients
(ref http/service.rs:24-26).
"""

from __future__ import annotations

from typing import Iterable, Optional


def _merge_tool_calls(acc: list, deltas: list) -> None:
    for d in deltas:
        idx = d.get("index", 0)
        while len(acc) <= idx:
            acc.append({"index": len(acc), "type": "function",
                        "function": {"name": "", "arguments": ""}})
        slot = acc[idx]
        if d.get("id"):
            slot["id"] = d["id"]
        fn = d.get("function") or {}
        if fn.get("name"):
            slot["function"]["name"] = fn["name"]
        if fn.get("arguments"):
            slot["function"]["arguments"] += fn["arguments"]


def aggregate_chat_chunks(chunks: Iterable[dict]) -> dict:
    """Fold chat.completion.chunk dicts into one chat.completion."""
    chunks = list(chunks)
    if not chunks:
        raise ValueError("empty stream")
    base = chunks[0]
    choices: dict[int, dict] = {}
    usage: Optional[dict] = None
    created = base.get("created")
    for ch in chunks:
        if ch.get("usage"):
            usage = ch["usage"]
        for choice in ch.get("choices", []):
            idx = choice.get("index", 0)
            acc = choices.setdefault(
                idx,
                {
                    "index": idx,
                    "message": {"role": "assistant", "content": ""},
                    "finish_reason": None,
                    "logprobs": None,
                },
            )
            delta = choice.get("delta", {})
            if delta.get("role"):
                acc["message"]["role"] = delta["role"]
            if delta.get("content"):
                acc["message"]["content"] += delta["content"]
            if delta.get("reasoning_content"):
                acc["message"].setdefault("reasoning_content", "")
                acc["message"]["reasoning_content"] += delta["reasoning_content"]
            if delta.get("tool_calls"):
                acc["message"].setdefault("tool_calls", [])
                _merge_tool_calls(acc["message"]["tool_calls"], delta["tool_calls"])
            if choice.get("finish_reason"):
                acc["finish_reason"] = choice["finish_reason"]
            lp = choice.get("logprobs")
            if lp and lp.get("content"):
                if acc["logprobs"] is None:
                    acc["logprobs"] = {"content": []}
                acc["logprobs"]["content"].extend(lp["content"])
    out = {
        "id": base.get("id"),
        "object": "chat.completion",
        "created": created,
        "model": base.get("model"),
        "choices": [choices[i] for i in sorted(choices)],
    }
    if usage:
        out["usage"] = usage
    return out


def aggregate_completion_chunks(chunks: Iterable[dict]) -> dict:
    """Fold text_completion chunks into one completion response."""
    chunks = list(chunks)
    if not chunks:
        raise ValueError("empty stream")
    base = chunks[0]
    choices: dict[int, dict] = {}
    usage: Optional[dict] = None
    for ch in chunks:
        if ch.get("usage"):
            usage = ch["usage"]
        for choice in ch.get("choices", []):
            idx = choice.get("index", 0)
            acc = choices.setdefault(
                idx, {"index": idx, "text": "", "finish_reason": None, "logprobs": None}
            )
            acc["text"] += choice.get("text", "")
            if choice.get("finish_reason"):
                acc["finish_reason"] = choice["finish_reason"]
            lp = choice.get("logprobs")
            if lp and lp.get("tokens"):
                if acc["logprobs"] is None:
                    acc["logprobs"] = {
                        "tokens": [], "token_logprobs": [],
                        "top_logprobs": [], "text_offset": [],
                    }
                acc["logprobs"]["tokens"].extend(lp["tokens"])
                acc["logprobs"]["token_logprobs"].extend(lp["token_logprobs"])
                acc["logprobs"]["top_logprobs"].extend(lp["top_logprobs"])
                acc["logprobs"]["text_offset"].extend(
                    lp.get("text_offset", [])
                )
    out = {
        "id": base.get("id"),
        "object": "text_completion",
        "created": base.get("created"),
        "model": base.get("model"),
        "choices": [choices[i] for i in sorted(choices)],
    }
    if usage:
        out["usage"] = usage
    return out

"""Breach-driven worker quarantine with ScaleGuard-style hysteresis.

The flight recorder attributes every SLO breach to the worker the
router placed the request on (``FlightRecorder.worker_counters``). A
worker whose breach *rate* spikes — sick HBM, a noisy co-tenant, a
wedged executor — keeps attracting traffic for as long as its
advertised load looks attractive; the quarantine loop is the circuit
breaker: soft-exclude it from routing (exactly like a ``resharding``
worker — held streams drain, a one-worker pool still serves), hold,
then readmit it under observation and reinstate only after it proves
itself on real traffic.

Flap resistance is the design center, mirroring
:class:`~dynamo_tpu.planner.guard.ScaleGuard`:

  * evidence is per-tick *deltas* of cumulative counters, and only
    ticks that saw finished requests count — a slow scrape or an idle
    window advances nothing in either direction;
  * tripping takes ``trip_ticks`` CONSECUTIVE unhealthy observed ticks
    AND an absolute per-tick breach floor (``min_breaches``) — one
    autopsy burst or one breached request cannot quarantine a worker;
  * a dirty probe re-quarantines with exponential hold backoff
    (capped), so a genuinely sick worker converges to "mostly out"
    instead of oscillating at the probe frequency;
  * at most ``max_quarantined_frac`` of the observed pool is ever out
    at once (a lone worker is never quarantined) — the loop degrades
    to "serve with breaches" rather than "serve nothing".

Clock-injected and synchronous: the flap-resistance matrix in
tests/test_autopilot.py and the planner-sim replay drive it tick by
tick on a fake clock.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

HEALTHY = "healthy"
QUARANTINED = "quarantined"
PROBE = "probe"


@dataclass
class QuarantineConfig:
    #: breaches / finished-requests ratio (per observed tick) that
    #: counts as unhealthy evidence
    breach_frac: float = 0.5
    #: absolute per-tick breach floor — below this a tick is healthy
    #: regardless of ratio (2 breaches out of 2 finishes is a blip,
    #: not a pathology)
    min_breaches: int = 3
    #: consecutive unhealthy OBSERVED ticks before quarantining
    trip_ticks: int = 2
    #: quarantine hold before the worker is probed
    hold_s: float = 20.0
    #: consecutive clean observed ticks in PROBE to fully reinstate
    probe_ticks: int = 2
    #: hold multiplier after a dirty probe, capped at max_hold_s
    backoff: float = 2.0
    max_hold_s: float = 300.0
    #: ceiling on the quarantined share of the observed pool
    max_quarantined_frac: float = 0.5


@dataclass
class _WorkerHealth:
    state: str = HEALTHY
    #: consecutive unhealthy observed ticks (HEALTHY state)
    streak: int = 0
    #: consecutive clean observed ticks (PROBE state)
    clean: int = 0
    held_until: float = 0.0
    hold_s: float = 0.0
    #: cumulative-counter baselines from the previous tick
    last_breaches: int = 0
    last_records: int = 0
    quarantines: int = 0


@dataclass
class QuarantineEvent:
    """One state transition, recorded for no-flap assertions (the
    ScaleGuard ``actions`` idiom)."""
    ts: float
    worker_id: int
    action: str  # "quarantine" | "probe" | "reinstate" | "requarantine"
    detail: str = ""


class QuarantineManager:
    """The synchronous state machine; the autopilot controller feeds it
    one counter map per control tick."""

    def __init__(self, cfg: Optional[QuarantineConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or QuarantineConfig()
        self._clock = clock
        self._workers: dict[int, _WorkerHealth] = {}
        self.events: list[QuarantineEvent] = []
        self.quarantines_total = 0
        self.reinstates_total = 0
        self.requarantines_total = 0

    # ---- views ----

    @property
    def quarantined(self) -> list[int]:
        return sorted(w for w, h in self._workers.items()
                      if h.state == QUARANTINED)

    @property
    def probing(self) -> list[int]:
        return sorted(w for w, h in self._workers.items()
                      if h.state == PROBE)

    def state(self, worker_id: int) -> str:
        h = self._workers.get(worker_id)
        return h.state if h is not None else HEALTHY

    # ---- the control step ----

    def step(self, counters: dict[int, tuple[int, int]]) -> list[QuarantineEvent]:
        """One tick over the observed pool. ``counters`` maps
        worker_id -> (breaches_total, records_total), CUMULATIVE (the
        flight recorder's per-worker counters); deltas are taken here.
        Returns the transitions this tick produced."""
        now = self._clock()
        pool = set(counters) | set(self._workers)
        cap = int(self.cfg.max_quarantined_frac * len(pool))
        fired: list[QuarantineEvent] = []
        for wid in sorted(pool):
            b_tot, r_tot = counters.get(wid, (None, None))
            h = self._workers.setdefault(wid, _WorkerHealth())
            if b_tot is None:
                continue  # no scrape this tick: no evidence either way
            d_b = b_tot - h.last_breaches
            d_r = r_tot - h.last_records
            h.last_breaches, h.last_records = b_tot, r_tot
            if d_b < 0 or d_r < 0:
                # recorder restarted — rebase, evidence starts over
                h.streak = h.clean = 0
                continue
            observed = d_r > 0
            unhealthy = (
                observed
                and d_b >= self.cfg.min_breaches
                and d_b / d_r >= self.cfg.breach_frac
            )
            if h.state == HEALTHY:
                if unhealthy:
                    h.streak += 1
                    if (h.streak >= self.cfg.trip_ticks
                            and len(self.quarantined) < cap):
                        fired.append(self._quarantine(
                            h, wid, now,
                            f"{d_b}/{d_r} breached x{h.streak} ticks"))
                elif observed:
                    h.streak = 0
            elif h.state == QUARANTINED:
                # held streams may still breach while they drain —
                # that evidence is pre-quarantine traffic, already
                # rebased above; the hold is purely time-based
                if now >= h.held_until:
                    h.state = PROBE
                    h.clean = 0
                    fired.append(QuarantineEvent(now, wid, "probe"))
            elif h.state == PROBE:
                if unhealthy:
                    h.hold_s = min(h.hold_s * self.cfg.backoff,
                                   self.cfg.max_hold_s)
                    fired.append(self._quarantine(
                        h, wid, now, f"dirty probe {d_b}/{d_r}",
                        requarantine=True))
                elif observed:
                    h.clean += 1
                    if h.clean >= self.cfg.probe_ticks:
                        h.state = HEALTHY
                        h.streak = 0
                        self.reinstates_total += 1
                        fired.append(QuarantineEvent(now, wid, "reinstate"))
        self.events.extend(fired)
        return fired

    def _quarantine(self, h: _WorkerHealth, wid: int, now: float,
                    detail: str, requarantine: bool = False) -> QuarantineEvent:
        h.state = QUARANTINED
        h.streak = h.clean = 0
        if not requarantine:
            h.hold_s = self.cfg.hold_s
        h.held_until = now + h.hold_s
        h.quarantines += 1
        self.quarantines_total += 1
        if requarantine:
            self.requarantines_total += 1
        return QuarantineEvent(
            now, wid, "requarantine" if requarantine else "quarantine",
            detail)

    def forget(self, worker_id: int) -> None:
        """Drop a departed worker (lease expiry) so a recycled id
        starts healthy."""
        self._workers.pop(worker_id, None)

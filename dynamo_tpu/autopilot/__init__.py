"""Fleet autopilot: closed-loop control over the measured plane.

Four loops over the SLO observatory's measurements (docs/autopilot.md):
tail-aware routing (``tails.TailTracker`` folded into the KV router's
cost model), compile pre-warm (``controller`` publishes, ``warmup.
WarmupListener`` actuates), breach-driven auto-quarantine
(``quarantine.QuarantineManager`` hysteresis; the router and
``resilience.quarantine.QuarantineListener`` subscribe the health
subject), and measured-headroom admission shedding (``controller`` ->
``AdmissionGate.set_class_rate``).
"""

from .controller import Autopilot, AutopilotConfig
from .protocols import (
    AUTOPILOT_HEALTH_SUBJECT,
    AUTOPILOT_WARMUP_SUBJECT,
    HealthDirective,
    WarmupDirective,
)
from .quarantine import QuarantineConfig, QuarantineManager
from .tails import TailTracker
from .warmup import WarmupListener

__all__ = [
    "AUTOPILOT_HEALTH_SUBJECT",
    "AUTOPILOT_WARMUP_SUBJECT",
    "Autopilot",
    "AutopilotConfig",
    "HealthDirective",
    "QuarantineConfig",
    "QuarantineManager",
    "TailTracker",
    "WarmupDirective",
    "WarmupListener",
]

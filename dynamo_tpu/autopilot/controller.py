"""The autopilot controller: one synchronous control step, four loops.

Consumes the observatory's measured plane (the planner telemetry
aggregator's scrape view, the flight recorder's per-worker breach
attribution, the admission gate's class counters) and actuates:

  1. **compile pre-warm** — a worker whose compile-ledger coverage says
     its XLA bucket grid is cold (``xla_warm_buckets`` <
     ``xla_reachable_buckets``, or 0/0 — never warmed) gets a
     :class:`WarmupDirective` on the ``autopilot-warmup`` subject and
     rides the health directive's ``prewarm_hold`` list until its grid
     is warm, so traffic shifts onto it AFTER the compile stalls are
     paid, not through them. Cooldown + attempt caps bound republishes;
     a worker that can't warm (attempts exhausted) is released to serve
     cold rather than held forever.
  2. **auto-quarantine** — the flight recorder's per-worker
     (unhealthy, finished) counters feed the
     :class:`~dynamo_tpu.autopilot.quarantine.QuarantineManager`
     hysteresis; its quarantined/probing views ride the health
     directive.
  3. **headroom shedding** — measured per-class arrival rates and the
     fleet's measured serving rate/utilization size a per-class
     admission cap: reserve-bearing classes get what's left after the
     critical classes' observed demand (``AdmissionGate.
     set_class_rate``), instead of a static reserve fraction. Caps lift
     when utilization drops — and when the autopilot stops.
  4. **tail-aware routing** rides scrape-side in the scheduler's
     :class:`~dynamo_tpu.autopilot.tails.TailTracker` (no control tick
     needed — the router folds tails per decision); the controller just
     owns its knobs in :class:`AutopilotConfig` for launch wiring.

``tick()`` is synchronous and clock-injected — the planner-sim replay
and the hysteresis tests drive it deterministically; ``start()`` wraps
it in the usual spawned loop for live serving.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from .protocols import (
    AUTOPILOT_HEALTH_SUBJECT,
    AUTOPILOT_WARMUP_SUBJECT,
    HealthDirective,
    WarmupDirective,
)
from .quarantine import QuarantineConfig, QuarantineManager

logger = logging.getLogger(__name__)


@dataclass
class AutopilotConfig:
    interval_s: float = 2.0
    pool: str = "decode"
    # -- pre-warm loop --
    prewarm: bool = True
    #: min seconds between warmup directives at one worker
    prewarm_cooldown_s: float = 30.0
    #: directives per worker before giving up (a worker that cannot
    #: warm serves cold instead of being held out forever)
    prewarm_max_attempts: int = 3
    # -- quarantine loop --
    quarantine: bool = True
    quarantine_cfg: QuarantineConfig = field(default_factory=QuarantineConfig)
    # -- headroom loop --
    headroom: bool = False
    #: slot utilization above which reserve-bearing classes get capped
    #: at measured headroom (below it every cap lifts)
    headroom_util: float = 0.85
    #: safety margin on the measured serving rate
    headroom_safety: float = 0.9
    #: never cap a class below this many req/s (starvation guard)
    headroom_floor_req_s: float = 0.25
    #: window for the measured per-class arrival / admitted rates
    headroom_window_s: float = 10.0
    # -- tail-aware routing knobs (consumed by SchedulerConfig wiring) --
    tail_aware: bool = True
    tail_q: float = 0.99
    tail_window_s: float = 60.0
    tail_min_count: int = 8


class Autopilot:
    """Owns the control tick; every collaborator is optional so each
    loop degrades to "off" where the deployment shape lacks its input
    (a frontend without a flight recorder still pre-warms, etc.)."""

    def __init__(self, drt=None, component=None, telemetry=None,
                 recorder=None, gate=None,
                 config: Optional[AutopilotConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or AutopilotConfig()
        self.drt = drt
        self.telemetry = telemetry
        self.recorder = recorder
        self.gate = gate
        self._clock = clock
        self._warmup_subject = (
            component.event_subject(AUTOPILOT_WARMUP_SUBJECT)
            if component is not None else None
        )
        self._health_subject = (
            component.event_subject(AUTOPILOT_HEALTH_SUBJECT)
            if component is not None else None
        )
        self.quarantine = QuarantineManager(self.cfg.quarantine_cfg, clock)
        # pre-warm state
        self._warm_attempts: dict[int, int] = {}
        self._warm_last: dict[int, float] = {}
        self.prewarm_hold: set[int] = set()
        # headroom state: per-class (ts, arrivals-delta) windows and
        # the gate-counter baselines the deltas difference against
        self._class_arrivals: dict[str, deque] = {}
        self._class_base: dict[str, int] = {}
        self._served: deque = deque()
        self._served_base: Optional[int] = None
        self.headroom_caps: dict[str, float] = {}
        # counters (Metrics.register_source via render_stats)
        self.ticks = 0
        self.warmup_directives = 0
        self.health_published = 0
        self._task: Optional[asyncio.Task] = None

    # ---------------- the control step ----------------

    def tick(self) -> HealthDirective:
        """One synchronous control step over the measured plane."""
        self.ticks += 1
        now = self._clock()
        snap = self.telemetry.snapshot() if self.telemetry else None
        reasons: list[str] = []
        if snap is not None and self.cfg.prewarm:
            self._prewarm_step(snap, now, reasons)
        if self.recorder is not None and self.cfg.quarantine:
            for ev in self.quarantine.step(self.recorder.worker_counters()):
                reasons.append(f"{ev.action}:{ev.worker_id:x}")
        if self.gate is not None and self.cfg.headroom:
            self._headroom_step(snap, now, reasons)
        directive = HealthDirective(
            ts=now,
            quarantined=self.quarantine.quarantined,
            probing=self.quarantine.probing,
            prewarm_hold=sorted(self.prewarm_hold),
            reason=",".join(reasons) or "steady",
        )
        self._publish_health(directive)
        return directive

    # ---------------- loop 2: compile pre-warm ----------------

    @staticmethod
    def _is_cold(w) -> bool:
        """Cold = the compile ledger says the warmup-reachable bucket
        grid isn't covered. 0/0 (never warmed — warmup is what computes
        ``reachable``) counts as cold: that IS the fresh/morphed-worker
        state whose first dispatches pay the compile stalls."""
        return (w.xla_reachable_buckets == 0
                or w.xla_warm_buckets < w.xla_reachable_buckets)

    def _prewarm_step(self, snap, now: float, reasons: list) -> None:
        seen = set()
        for w in snap.workers:
            wid = w.worker_id
            seen.add(wid)
            if w.draining:
                continue
            if not self._is_cold(w):
                if wid in self.prewarm_hold:
                    self.prewarm_hold.discard(wid)
                    reasons.append(f"warm:{wid:x}")
                self._warm_attempts.pop(wid, None)
                continue
            attempts = self._warm_attempts.get(wid, 0)
            if attempts >= self.cfg.prewarm_max_attempts:
                # can't warm it — serve cold rather than hold forever
                self.prewarm_hold.discard(wid)
                continue
            self.prewarm_hold.add(wid)
            last = self._warm_last.get(wid)
            if last is not None and now - last < self.cfg.prewarm_cooldown_s:
                continue
            self._warm_attempts[wid] = attempts + 1
            self._warm_last[wid] = now
            self._publish_warmup(WarmupDirective(
                ts=now, worker_id=wid, pool=self.cfg.pool,
                reason=("cold_buckets" if w.xla_reachable_buckets == 0
                        else "partial_coverage"),
            ))
            reasons.append(f"cold:{wid:x}")
        for wid in list(self.prewarm_hold):
            if wid not in seen:  # departed mid-warm
                self.prewarm_hold.discard(wid)
                self._warm_attempts.pop(wid, None)

    # ---------------- loop 4: measured headroom ----------------

    def _headroom_step(self, snap, now: float, reasons: list) -> None:
        stats = self.gate.stats
        cutoff = now - self.cfg.headroom_window_s
        span = max(self.cfg.headroom_window_s, 1e-9)
        # measured per-class arrival rates (admitted + shed = offered)
        rates: dict[str, float] = {}
        for name in self.gate.classes:
            offered = (stats.get(f"admitted_{name}", 0)
                       + stats.get(f"shed_{name}", 0))
            base = self._class_base.get(name)
            self._class_base[name] = offered
            dq = self._class_arrivals.setdefault(name, deque())
            if base is not None and offered > base:
                dq.append((now, offered - base))
            while dq and dq[0][0] < cutoff:
                dq.popleft()
            rates[name] = sum(n for _t, n in dq) / span
        # measured serving rate: admitted/s over the same window (at
        # high utilization admissions track completions — steady state)
        admitted = stats.get("admitted_total", 0)
        if self._served_base is not None and admitted > self._served_base:
            self._served.append((now, admitted - self._served_base))
        self._served_base = admitted
        while self._served and self._served[0][0] < cutoff:
            self._served.popleft()
        served_rate = sum(n for _t, n in self._served) / span
        util = snap.slot_utilization if snap is not None else 0.0
        if util < self.cfg.headroom_util or served_rate <= 0:
            # headroom everywhere: lift every cap
            for name in list(self.headroom_caps):
                self.gate.set_class_rate(name, 0.0)
                del self.headroom_caps[name]
                reasons.append(f"headroom_lift:{name}")
            return
        capacity = served_rate / max(util, 0.1) * self.cfg.headroom_safety
        critical_demand = sum(
            rates[c.name] for c in self.gate.classes.values()
            if c.reserve_frac == 0
        )
        for c in self.gate.classes.values():
            if c.reserve_frac <= 0:
                continue  # critical classes are never headroom-capped
            cap = max(self.cfg.headroom_floor_req_s,
                      capacity - critical_demand)
            prev = self.headroom_caps.get(c.name)
            if prev is None or abs(prev - cap) / max(prev, 1e-9) > 0.05:
                self.gate.set_class_rate(c.name, cap)
                self.headroom_caps[c.name] = cap
                reasons.append(f"headroom:{c.name}={cap:.2f}")

    # ---------------- publication ----------------

    def _publish_warmup(self, directive: WarmupDirective) -> None:
        # dynflow: publishes=AUTOPILOT_WARMUP_SUBJECT
        self.warmup_directives += 1
        if self.drt is None or self._warmup_subject is None:
            return
        try:
            self.drt.bus.publish(self._warmup_subject, directive.to_bytes())
        except Exception:  # noqa: BLE001 — best-effort, next tick retries
            logger.debug("warmup directive publish failed", exc_info=True)

    def _publish_health(self, directive: HealthDirective) -> None:
        # dynflow: publishes=AUTOPILOT_HEALTH_SUBJECT
        if self.drt is None or self._health_subject is None:
            return
        try:
            self.drt.bus.publish(self._health_subject, directive.to_bytes())
            self.health_published += 1
        except Exception:  # noqa: BLE001 — full replacement republishes
            logger.debug("health directive publish failed", exc_info=True)

    # ---------------- lifecycle ----------------

    async def start(self) -> "Autopilot":
        self._task = (self.drt.runtime.spawn(self._loop()) if self.drt
                      else asyncio.get_running_loop().create_task(self._loop()))
        return self

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        # leaving caps behind would freeze the last decision into the
        # gate after the controller is gone
        if self.gate is not None:
            for name in list(self.headroom_caps):
                self.gate.set_class_rate(name, 0.0)
            self.headroom_caps.clear()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.interval_s)
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a bad tick must not end
                logger.exception("autopilot tick failed")

    # ---------------- metrics surface ----------------

    def render_stats(self) -> dict:
        return {
            "autopilot_ticks_total": self.ticks,
            "autopilot_warmup_directives_total": self.warmup_directives,
            "autopilot_health_published_total": self.health_published,
            "autopilot_prewarm_holds": len(self.prewarm_hold),
            "autopilot_quarantined_now": len(self.quarantine.quarantined),
            "autopilot_probing_now": len(self.quarantine.probing),
            "autopilot_quarantines_total": self.quarantine.quarantines_total,
            "autopilot_reinstates_total": self.quarantine.reinstates_total,
            "autopilot_requarantines_total":
                self.quarantine.requarantines_total,
            "autopilot_headroom_caps": len(self.headroom_caps),
        }

"""Worker-side actuation of autopilot pre-warm directives.

The autopilot finds cold XLA bucket grids from the scraped compile
ledger (``xla_warm_buckets`` vs ``xla_reachable_buckets`` — a fresh or
morphed worker shows 0/0 until its first warmup) and publishes a
:class:`~dynamo_tpu.autopilot.protocols.WarmupDirective` on the
component's ``autopilot-warmup`` subject. Every worker runs a
:class:`WarmupListener` that filters for its own id (0 = pool-wide) and
runs ``JaxEngine.warmup`` — the same bucket grid the launch-time
``--warmup`` flag compiles, but driven by the control plane, so a
scale-up/morph pays its compile stalls OFF the hot path while the
router's ``prewarm_hold`` keeps traffic away.

Same resilience contract as the reshard actuator it mirrors
(resilience/reshard.py): warmups apply one at a time per worker, an
already-warm grid is a counted no-op (warmup is idempotent — a
re-published directive costs nothing), and a failed warmup is counted
and logged, never raised into the subscription loop. Counters land in
``engine.stats`` so the ``load_metrics`` scrape -> WorkerLoad ->
metrics-render plane sees actuation without a new producer surface.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from .protocols import AUTOPILOT_WARMUP_SUBJECT, WarmupDirective

logger = logging.getLogger(__name__)


class WarmupListener:
    """Subscribe the ``autopilot-warmup`` subject and warm one engine's
    bucket grid on demand (see module doc)."""

    def __init__(self, drt, component, worker_id: int, engine,
                 pool: str = "decode"):
        self.drt = drt
        self.subject = component.event_subject(AUTOPILOT_WARMUP_SUBJECT)
        self.worker_id = worker_id
        self.engine = engine
        #: directives for another pool are not ours even at worker_id=0
        #: (a decode-pool pre-warm must not grid-compile prefill workers
        #: sharing the subject)
        self.pool = pool
        self.warmups_applied = 0
        self.warmups_noop = 0
        self.warmups_failed = 0
        self.warmup_ms_total = 0.0
        self._task: Optional[asyncio.Task] = None
        self._sub = None
        self._lock = asyncio.Lock()

    async def start(self) -> "WarmupListener":
        sub = self.drt.bus.subscribe(self.subject)
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._sub = sub
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()

    def _already_warm(self) -> bool:
        stats = getattr(self.engine, "stats", None) or {}
        reachable = stats.get("xla_reachable_buckets", 0)
        return reachable > 0 and stats.get("xla_warm_buckets", 0) >= reachable

    async def _consume(self, sub) -> None:
        async for msg in sub:
            try:
                directive = WarmupDirective.from_bytes(msg.payload)
                if directive is None:
                    continue
                if directive.worker_id not in (0, self.worker_id):
                    continue
                if directive.pool != self.pool:
                    continue
                await self._apply(directive)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad event must not
                logger.debug("bad warmup directive", exc_info=True)

    async def _apply(self, directive: WarmupDirective) -> None:
        async with self._lock:  # one grid compile at a time per worker
            if self._already_warm():
                # idempotence makes republished directives free — the
                # autopilot's cooldown bounds them, this zeroes them
                self.warmups_noop += 1
                self._mirror()
                return
            t0 = time.perf_counter()
            try:
                await self.engine.warmup(decode=directive.decode)  # dynlint: disable=await-in-lock -- this lock exists to serialize bucket-grid compiles on one engine; the warmup IS the work being serialized, not incidental I/O under it
                self.warmups_applied += 1
                self.warmup_ms_total += (time.perf_counter() - t0) * 1e3
                logger.info(
                    "autopilot warmup applied on worker %x (%.0f ms)",
                    self.worker_id, (time.perf_counter() - t0) * 1e3,
                )
            except Exception:  # noqa: BLE001 — engine keeps serving
                # cold; count it and let the next directive retry
                self.warmups_failed += 1
                logger.exception("autopilot warmup failed")
            self._mirror()

    def _mirror(self) -> None:
        """Mirror actuation counters into ``engine.stats`` so the
        existing load_metrics scrape advertises them fleet-wide."""
        stats = getattr(self.engine, "stats", None)
        if stats is None:
            return
        stats["autopilot_warmups_applied"] = self.warmups_applied
        stats["autopilot_warmup_ms_total"] = round(self.warmup_ms_total, 3)

    def stats(self) -> dict:
        return {
            "autopilot_warmups_applied": self.warmups_applied,
            "autopilot_warmups_noop": self.warmups_noop,
            "autopilot_warmups_failed": self.warmups_failed,
            "autopilot_warmup_ms_total": round(self.warmup_ms_total, 3),
        }

"""Windowed per-worker tails from cumulative scrape histograms.

The engine's ``load_metrics`` advertises CUMULATIVE latency histograms
(queue-wait / prefill / restore / handoff bucket vectors since process
start). Routing on a cumulative distribution is routing on history — a
worker that was slow an hour ago looks slow forever, and a worker that
*became* bimodal five seconds ago hides behind its good past. The
:class:`TailTracker` turns the cumulative vectors into a sliding-window
view router-side: it keeps a short deque of (scrape-ts, bucket-vector)
snapshots per worker and differences the newest snapshot against the
newest one at least ``window_s`` old — exact bucket-count subtraction,
the same loss-free algebra that makes histogram merge exact.

The windowed tail (default p99 of queue-wait + prefill) is the floor
:func:`~dynamo_tpu.kv_router.costmodel.tail_adjusted_ttft_ms` folds
into the cost model's predicted TTFT, so a bimodal worker is priced at
its measured tail instead of the mean its EWMA calibration reports.

Counter resets (an engine restart makes a delta go negative) rebase the
worker's window to the newest snapshot — one tick of "no tail evidence"
instead of a garbage quantile. Everything is clock-injected so the
planner-sim replay and the hysteresis tests run on a fake clock.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from ..observability.hist import Histogram

#: the TTFT-relevant worker families, in gate-first order: the FIRST
#: name gates the tail (min_count samples required); the rest add their
#: quantiles when they have any window samples at all
TAIL_FAMILIES = ("queue_wait_ms", "prefill_ms")


def delta_hist(cur_vec: dict, base_vec: Optional[dict]) -> Optional[Histogram]:
    """Exact windowed histogram: ``cur - base`` bucket by bucket.

    ``base_vec=None`` means the window predates the worker's first
    scrape — the cumulative vector IS the window. Returns None on a
    malformed vector, a bounds skew (schema-skewed peer), or a negative
    delta (counter reset): the caller treats all three as "no window
    evidence this tick" rather than a wrong number."""
    cur = Histogram.from_vec(cur_vec)
    if cur is None:
        return None
    if base_vec is None:
        return cur
    base = Histogram.from_vec(base_vec)
    if base is None or base.bounds != cur.bounds:
        return None
    out = Histogram(cur.bounds)
    total = 0
    for i in range(len(cur.counts)):
        d = cur.counts[i] - base.counts[i]
        if d < 0:
            return None  # counter reset — rebase upstream
        out.counts[i] = d
        total += d
    out.count = total
    out.sum = max(cur.sum - base.sum, 0.0)
    if total == 0:
        return out
    # the window's observed range is only known to bucket resolution:
    # clamp quantiles to the occupied buckets' edges (lower edge of the
    # first occupied bucket, upper edge of the last; the overflow
    # bucket's ceiling is the cumulative max — an overestimate bounded
    # by reality)
    occupied = [i for i, c in enumerate(out.counts) if c]
    lo_i, hi_i = occupied[0], occupied[-1]
    out.min = out.bounds[lo_i - 1] if lo_i > 0 else 0.0
    out.max = out.bounds[hi_i] if hi_i < len(out.bounds) else cur.max
    return out


class TailTracker:
    """Per-worker sliding-window tails over scraped histogram vectors."""

    def __init__(self, window_s: float = 60.0, q: float = 0.99,
                 min_count: int = 8,
                 families: tuple[str, ...] = TAIL_FAMILIES,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = window_s
        self.q = q
        #: window samples the gate family must hold before a tail is
        #: trusted — below this a worker simply has no tail evidence
        #: (cold / idle), and routing behaves exactly as before
        self.min_count = min_count
        self.families = tuple(families)
        self._clock = clock
        #: worker -> deque[(ts, {family: to_vec dict})], ts ascending
        self._snaps: dict[int, deque] = {}
        self._last_ts: dict[int, float] = {}
        self.rebases = 0

    def observe(self, worker_id: int, hists: Optional[dict],
                ts: Optional[float] = None) -> None:
        """Record one scrape's cumulative vectors. Deduped on ``ts``
        (many routing decisions ride one scrape) and pruned so at most
        one snapshot older than the window survives as the baseline."""
        if not hists:
            return
        now = self._clock() if ts is None else ts
        last = self._last_ts.get(worker_id)
        if last == now:
            return
        if ts is None and last is not None and now - last < 0.2:
            # unstamped callers (legacy producers) observe per routing
            # decision, not per scrape — rate-limit so a hot router
            # doesn't grow the snapshot deque per request
            return
        self._last_ts[worker_id] = now
        dq = self._snaps.setdefault(worker_id, deque())
        while dq and dq[-1][0] >= now:
            dq.pop()  # clock went backwards (re-seeded fake clock)
        dq.append((now, {
            f: hists[f] for f in self.families if f in hists
        }))
        cutoff = now - self.window_s
        while len(dq) > 2 and dq[1][0] <= cutoff:
            dq.popleft()

    def window_hist(self, worker_id: int, family: str) -> Optional[Histogram]:
        """The worker's windowed distribution for one family: newest
        snapshot minus the newest snapshot at least ``window_s`` old
        (or the oldest held — a shorter window early on beats no
        window). None = no evidence (single snapshot, reset, skew)."""
        dq = self._snaps.get(worker_id)
        if not dq or len(dq) < 2:
            return None
        cur_ts, cur = dq[-1]
        base = dq[0][1]
        for t, s in dq:
            if t <= cur_ts - self.window_s:
                base = s
            else:
                break
        cv = cur.get(family)
        if cv is None:
            return None
        h = delta_hist(cv, base.get(family))
        if h is None:
            # counter reset / bounds skew: rebase the worker's window
            # to the newest snapshot so the next scrape pairs cleanly
            self.rebases += 1
            self._snaps[worker_id] = deque([dq[-1]])
            return None
        return h

    def tail_ms(self, worker_id: int, q: Optional[float] = None) -> Optional[float]:
        """The worker's windowed TTFT tail floor in milliseconds:
        q-quantile of windowed queue-wait plus q-quantile of windowed
        prefill. None when the gate family holds fewer than
        ``min_count`` window samples — no evidence, no adjustment."""
        q = self.q if q is None else q
        gate = self.window_hist(worker_id, self.families[0])
        if gate is None or gate.count < self.min_count:
            return None
        total = gate.quantile(q) or 0.0
        for family in self.families[1:]:
            h = self.window_hist(worker_id, family)
            if h is not None and h.count > 0:
                total += h.quantile(q) or 0.0
        return total

    def forget(self, worker_id: int) -> None:
        """Drop a departed worker's snapshots (lease expiry)."""
        self._snaps.pop(worker_id, None)
        self._last_ts.pop(worker_id, None)

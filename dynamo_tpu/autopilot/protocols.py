"""Autopilot wire schema: the control subjects that close the loop.

Two subjects, published on the target component (same bus idiom as the
planner's ``planner-watermarks``/``reshard`` subjects):

  * ``autopilot-warmup`` — one :class:`WarmupDirective` per cold-bucket
    detection: the autopilot read a worker's compile-ledger coverage
    (``xla_warm_buckets`` vs ``xla_reachable_buckets``) and wants the
    worker to run its XLA bucket grid off the hot path BEFORE traffic
    shifts onto it. Worker-side actuation is
    :class:`~dynamo_tpu.autopilot.warmup.WarmupListener` →
    ``JaxEngine.warmup`` — the same listener shape as the reshard
    actuator, so a lost directive costs a republish, never correctness.
  * ``autopilot-health`` — one :class:`HealthDirective` per control
    tick: the full-replacement health view (like capacity watermarks —
    the newest event wins, receipt-time staleness is tracked
    subscriber-side). ``quarantined`` workers are soft-excluded from
    routing exactly like ``resharding`` workers; ``prewarm_hold``
    workers are held out of routing until their bucket grid is warm;
    ``probing`` workers are readmitted under observation after a
    quarantine hold expires (hysteresis lives in
    :class:`~dynamo_tpu.autopilot.quarantine.QuarantineManager`).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

AUTOPILOT_WARMUP_SUBJECT = "autopilot-warmup"
AUTOPILOT_HEALTH_SUBJECT = "autopilot-health"


@dataclass
class WarmupDirective:
    """One pre-warm request on the ``autopilot-warmup`` subject.

    ``worker_id=0`` addresses every worker in the pool (scale-up of a
    fresh pool); a non-zero id targets the one cold worker the
    autopilot saw. ``decode`` asks for the decode ladder on top of the
    prefill buckets (the full first-dispatch surface); False covers
    prefill-only pools."""

    ts: float = 0.0  # dynlint: disable=dead-wire-field -- wall-clock stamp for the operator audit trail; actuation is ordering-free (warmup is idempotent)
    worker_id: int = 0
    pool: str = "decode"
    #: why: "cold_buckets" (never warmed), "partial_coverage"
    #: (morph/config change grew the reachable grid), ...
    reason: str = ""  # dynlint: disable=dead-wire-field -- operator audit trail: WHY the autopilot judged the worker cold; the actuator warms the same grid regardless
    decode: bool = True

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> Optional["WarmupDirective"]:
        d = json.loads(raw)
        return WarmupDirective(**{
            k: d[k] for k in WarmupDirective().__dict__ if k in d
        })


@dataclass
class HealthDirective:
    """The autopilot's per-tick health view (full replacement).

    Subscribers: the KV router folds ``quarantined`` + ``prewarm_hold``
    into ``select_worker``'s soft-exclusion chain (the same
    last-resort semantics as ``resharding`` — a fleet that is entirely
    unhealthy still serves); every worker's
    :class:`~dynamo_tpu.resilience.quarantine.QuarantineListener`
    mirrors its own membership into engine counters so the quarantine
    state is visible in the scraped/rendered metrics plane."""

    ts: float = 0.0  # dynlint: disable=dead-wire-field -- wall-clock stamp for the operator audit trail; staleness is receipt-time tracked subscriber-side (autopilot_ttl_s)
    #: workers whose breach/autopsy rate tripped the quarantine
    #: hysteresis: route no NEW work at them (held streams drain)
    quarantined: list[int] = field(default_factory=list)
    #: quarantined workers readmitted under observation (hold expired);
    #: routable again, re-quarantined with backoff if still unhealthy
    probing: list[int] = field(default_factory=list)
    #: cold workers being pre-warmed: hold routing until the bucket
    #: grid compiles so first dispatches don't pay the compile stall
    prewarm_hold: list[int] = field(default_factory=list)
    #: why the view changed this tick ("breach_spike:7", "probe:7",
    #: "cold:9", "steady") — operators replay these to audit the loop
    reason: str = "steady"  # dynlint: disable=dead-wire-field -- operator audit trail mirroring MorphDecision.reason; exclusion keys on the membership lists alone by design

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw: bytes) -> Optional["HealthDirective"]:
        d = json.loads(raw)
        return HealthDirective(**{
            k: d[k] for k in HealthDirective().__dict__ if k in d
        })

"""CLI: ``python -m dynamo_tpu.analysis [paths...]``.

Exit 0 when clean, 1 on violations (the CI gates in scripts/check.sh):

* default — dynlint, the per-file AST pass;
* ``--program`` — dynflow, the whole-program contract checker
  (cross-file rules with evidence chains; wants the full tree);
* ``--changed`` — lint only files ``git diff HEAD`` reports touched
  (the pre-commit fast path; per-file rules only — project and
  program rules need the whole tree and are skipped);
* ``--json`` emits the machine-readable report; ``--rule`` restricts to
  a subset (comma-separated names); ``--list-rules`` prints both
  catalogs.
"""

from __future__ import annotations

import argparse
import sys

from .contracts import CONTRACT_RULES
from .engine import changed_files, check_program, lint_paths
from .rules import ALL_RULES


def _select_rules(catalog, spec: str):
    """Filter a rule catalog by the --rule spec; returns (rules, error)
    — error is the unknown-name message, None when the spec resolves."""
    if not spec:
        return catalog, None
    wanted = {n.strip() for n in spec.split(",") if n.strip()}
    unknown = wanted - {r.name for r in catalog}
    if unknown:
        return None, f"unknown rule(s): {', '.join(sorted(unknown))}"
    return tuple(r for r in catalog if r.name in wanted), None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.analysis",
        description="dynlint/dynflow: invariant-encoding static analysis "
        "(docs/static_analysis.md)",
    )
    ap.add_argument(
        "paths", nargs="*", default=["dynamo_tpu/", "tests/"],
        help="files/directories to lint (default: dynamo_tpu/ tests/)",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--program", action="store_true",
        help="run the whole-program contract checker (dynflow) instead "
        "of the per-file lint",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="lint only git-touched files (per-file rules; skips "
        "project/program rules, which need the whole tree)",
    )
    ap.add_argument(
        "--rule", default="",
        help="comma-separated rule names to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            kind = "project" if r.project else "file"
            print(f"{r.name:34s} [{kind}]   {r.summary}")
        for r in CONTRACT_RULES:
            print(f"{r.name:34s} [program] {r.summary}")
        return 0

    if args.program and args.changed:
        print("--program needs the whole tree; --changed is a per-file "
              "fast path — pick one", file=sys.stderr)
        return 2

    rules, err = _select_rules(
        CONTRACT_RULES if args.program else ALL_RULES, args.rule
    )
    if err:
        print(err, file=sys.stderr)
        return 2

    if args.program:
        report = check_program(args.paths, rules=rules)
        print(report.to_json() if args.json else report.render())
        return 0 if report.ok else 1

    paths = args.paths
    if args.changed:
        touched = changed_files(paths)
        if touched is None:
            print("dynlint: --changed needs git; falling back to the "
                  "full walk", file=sys.stderr)
        else:
            # project rules (cross-file) can't judge a partial set; a
            # --rule selection naming ONLY project rules must error,
            # not run zero rules and report a false clean
            per_file = tuple(r for r in rules if not r.project)
            if args.rule and not per_file:
                print("--changed runs per-file rules only; the selected "
                      "rule(s) are project-wide (drop --changed)",
                      file=sys.stderr)
                return 2
            rules = per_file
            if not touched:
                report = lint_paths([], rules=rules)
                print(report.to_json() if args.json else
                      "dynlint: 0 changed files, 0 violations, 0 suppressed")
                return 0
            paths = touched

    report = lint_paths(paths, rules=rules)
    print(report.to_json() if args.json else report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())

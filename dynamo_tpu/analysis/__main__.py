"""CLI: ``python -m dynamo_tpu.analysis [paths...]``.

Exit 0 when clean, 1 on violations (the CI gate in scripts/check.sh).
``--json`` emits the machine-readable report; ``--rule`` restricts to a
subset (comma-separated names); ``--list-rules`` prints the catalog.
"""

from __future__ import annotations

import argparse
import sys

from .engine import lint_paths
from .rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.analysis",
        description="dynlint: invariant-encoding static analysis "
        "(docs/static_analysis.md)",
    )
    ap.add_argument(
        "paths", nargs="*", default=["dynamo_tpu/", "tests/"],
        help="files/directories to lint (default: dynamo_tpu/ tests/)",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--rule", default="",
        help="comma-separated rule names to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            kind = "project" if r.project else "file"
            print(f"{r.name:26s} [{kind}] {r.summary}")
        return 0

    rules = ALL_RULES
    if args.rule:
        wanted = {n.strip() for n in args.rule.split(",") if n.strip()}
        unknown = wanted - {r.name for r in ALL_RULES}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = tuple(r for r in ALL_RULES if r.name in wanted)

    report = lint_paths(args.paths, rules=rules)
    print(report.to_json() if args.json else report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""dynlint: invariant-encoding static analysis for the serving stack.

PRs 1-6 built an async serving stack whose correctness rests on a
handful of conventions that previously lived only in review comments:
no blocking host work on the event loop, device mutations only under
``_device_lock`` (and no network awaits while holding it), module-scope
``jax.jit`` only, forward-compatible codec header reads, writers closed
through ``wait_closed()``, faultpoints exercised by tests. Every rule in
:mod:`dynamo_tpu.analysis.rules` encodes a bug class we actually shipped
and then fixed by hand; the pass keeps them fixed.

Run it::

    python -m dynamo_tpu.analysis dynamo_tpu/ tests/

Suppress a finding on one line with a justification::

    writer.close()  # dynlint: disable=writer-wait-closed -- lingering transports

See docs/static_analysis.md for the rule catalog and
:mod:`dynamo_tpu.analysis.sanitizer` for the runtime counterpart (loop
stall / lock hold / leaked writer detection under the live test suite).
"""

from .engine import (  # noqa: F401
    LintReport,
    Violation,
    check_program,
    lint_paths,
    lint_source,
)
from .rules import ALL_RULES, Rule  # noqa: F401
from .contracts import CONTRACT_RULES, ContractRule  # noqa: F401
from .program import ProjectModel, Site, build_model  # noqa: F401

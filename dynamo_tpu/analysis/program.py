"""dynflow project-model extraction: the whole-program half of dynlint.

The per-file rules (:mod:`.rules`) see one AST at a time and therefore
cannot see the bug classes PRs 6-12 kept finding by hand in review:
wire-schema fields that are serialized but never consumed (PR 12's
``MorphDecision.pool`` was on the wire for a whole PR before its
listener filtered it), stats emitted by ``load_metrics`` that no
``WorkerLoad.from_stats`` mapping ever scrapes, bus subjects published
with no subscriber, header keys written by a sender that no decoder
reads tolerantly, and capability versions advertised in connection info
that the peer side never checks.

This module builds ONE model of the tree — every plane's declarations
and uses, each with its ``file:line`` — and :mod:`.contracts` fires
cross-file rules over it, reporting BOTH ends of each broken contract
(the write site and the missing/present read site) as an evidence
chain.

Extraction is deliberately declaration-driven, not type-inferred: the
planes already declare themselves (``*_SUBJECT`` constants resolved
through ``component.event_subject``, wire dataclasses with
``to_bytes``/``from_bytes``, the single ``WorkerLoad.from_stats``
scrape mapping, stats producers named ``load_metrics``/``stats``/
``counters``), and where they didn't, ISSUE 13's conformance pass made
them (named header dicts, ``# dynflow: commit-block`` markers). The
model errs toward over-approximating *consumption* (an attribute read
anywhere with the right name counts), so the rules stay quiet unless a
contract end is genuinely absent from the whole tree.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

__all__ = ["Site", "ProjectModel", "build_model"]


@dataclass(frozen=True)
class Site:
    """One end of an evidence chain."""

    path: str
    line: int
    note: str = ""

    def to_dict(self) -> dict:
        d = {"path": self.path, "line": self.line}
        if self.note:
            d["note"] = self.note
        return d


# ---------------------------------------------------------------------------
# plane scopes (declaration lists the extractor reads)
# ---------------------------------------------------------------------------

#: modules whose ``head``/``fin``/``h`` dict literals are wire headers on
#: the KV transfer plane (the named-header-dict convention)
WIRE_HEADER_MODULES = (
    "dynamo_tpu/disagg/transfer.py",
    "dynamo_tpu/disagg/worker.py",
    "dynamo_tpu/disagg/ici.py",
)

#: names a dict literal/subscript-store must be bound to for its string
#: keys to count as wire-header keys in WIRE_HEADER_MODULES
HEADER_DICT_NAMES = ("head", "fin", "hdr", "header", "h")

#: modules holding versioned wire dataclasses (to_bytes/from_bytes pairs)
WIRE_PROTOCOL_MODULES = (
    "dynamo_tpu/kv_router/protocols.py",
    "dynamo_tpu/planner/protocols.py",
    "dynamo_tpu/disagg/protocols.py",
    "dynamo_tpu/autopilot/protocols.py",
)

#: stats-plane producers: (module suffix, function name or dict-target
#: name) whose string keys form the advertised scrape surface. ``None``
#: function name = dict literals assigned to ``stats``/``_stats``/
#: ``COUNTERS`` targets anywhere in the module (the DisaggEngine /
#: sanitizer style), including later subscript stores on those names.
STAT_PRODUCERS = (
    ("dynamo_tpu/engine/engine.py", "load_metrics"),
    ("dynamo_tpu/engine/offload.py", "stats"),
    ("dynamo_tpu/kv_router/costmodel.py", "counters"),
    ("dynamo_tpu/analysis/sanitizer.py", None),
    ("dynamo_tpu/disagg/worker.py", None),
)

#: the single scrape mapping (consumer side of the stats plane)
FROM_STATS_MODULE = "dynamo_tpu/kv_router/scheduler.py"

#: where WorkerLoad fields must surface to count as "rendered"
GAUGE_RENDER_MODULE = "dynamo_tpu/observability/component.py"

#: modules whose render surface defines the Prometheus series the
#: Grafana dashboard may query (dashboard-metric-without-producer):
#: metric names are declared there as ALL_CAPS string constants,
#: ``gauge("name", ...)``/``hist_rows("name", ...)`` literals, or
#: ``HistogramVec("name", ...)`` families
METRIC_RENDER_MODULES = (
    "dynamo_tpu/http/metrics.py",
    "dynamo_tpu/observability/component.py",
)

#: the dashboard artifact the rule audits (collected by
#: engine.read_files alongside the .py tree)
DASHBOARD_FILE = "grafana-dashboard.json"

#: receiver-name fragments marking a connection-info dict (the
#: capability/version advertisement surface)
CONN_NAMES = ("conn", "connection")

#: commit-block region markers (tokenize comments)
COMMIT_BLOCK_BEGIN = "dynflow: commit-block"
COMMIT_BLOCK_END = "dynflow: end-commit-block"


def _dotted(func: ast.expr) -> str:
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        return "?." + ".".join(reversed(parts))
    return ""


def _str_const(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass
class WireClass:
    name: str
    path: str
    line: int
    #: field name -> definition Site
    fields: dict[str, Site] = field(default_factory=dict)


@dataclass
class CommitBlock:
    path: str
    begin: int  # line of the begin marker
    end: int  # line of the end marker (file end if unterminated)
    note: str = ""


@dataclass
class ProjectModel:
    """Everything the contract rules look at. All maps are
    ``key -> [Site, ...]`` unless noted."""

    # -- bus subjects --
    #: CONST name -> (string value, definition site)
    subject_constants: dict[str, tuple[str, Site]] = field(default_factory=dict)
    subjects_published: dict[str, list[Site]] = field(default_factory=dict)
    subjects_subscribed: dict[str, list[Site]] = field(default_factory=dict)

    # -- wire headers (KV transfer plane) --
    header_writes: dict[str, list[Site]] = field(default_factory=dict)
    header_tolerant_reads: dict[str, list[Site]] = field(default_factory=dict)
    header_subscript_reads: dict[str, list[Site]] = field(default_factory=dict)

    # -- stats pipeline --
    stats_produced: dict[str, list[Site]] = field(default_factory=dict)
    stats_scraped: dict[str, list[Site]] = field(default_factory=dict)
    from_stats_site: Optional[Site] = None

    # -- WorkerLoad -> gauge plane --
    workerload_fields: dict[str, Site] = field(default_factory=dict)
    workerload_rendered: dict[str, list[Site]] = field(default_factory=dict)
    workerload_consumed: dict[str, list[Site]] = field(default_factory=dict)

    # -- wire dataclasses --
    wire_classes: dict[str, WireClass] = field(default_factory=dict)
    #: class name -> field name -> attribute-read sites (typed-flow traced)
    wire_field_reads: dict[str, dict[str, list[Site]]] = field(default_factory=dict)

    # -- capability / version advertisement --
    conn_advertised: dict[str, list[Site]] = field(default_factory=dict)
    conn_checked: dict[str, list[Site]] = field(default_factory=dict)

    # -- rendered Prometheus series (dashboard contract) --
    #: metric name WITHOUT the ``dynamo_tpu`` prefix -> render sites
    metrics_rendered: dict[str, list[Site]] = field(default_factory=dict)

    # -- commit blocks --
    commit_blocks: list[CommitBlock] = field(default_factory=list)

    #: parse failures (reported as model errors, not silently dropped)
    errors: list[str] = field(default_factory=list)


def _add(d: dict[str, list[Site]], key: str, site: Site) -> None:
    d.setdefault(key, []).append(site)


# ---------------------------------------------------------------------------
# per-file extraction passes
# ---------------------------------------------------------------------------


class _FileScan:
    """All single-file facts gathered in one walk, merged into the model
    afterwards."""

    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        self.tree = tree
        self.source = source


def _subject_constants(path: str, tree: ast.Module, model: ProjectModel) -> None:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            val = _str_const(node.value)
            if (
                isinstance(tgt, ast.Name)
                and tgt.id.endswith("_SUBJECT")
                and val is not None
            ):
                model.subject_constants[tgt.id] = (
                    val, Site(path, node.lineno, f"{tgt.id} = {val!r}")
                )


def _subject_uses(path: str, tree: ast.Module, model: ProjectModel) -> None:
    """Resolve bus ``publish``/``subscribe`` call sites back to the
    ``*_SUBJECT`` constant they carry. Resolution is class-scoped: an
    ``__init__`` assigning ``self.x = component.event_subject(CONST)``
    binds ``self.x`` to CONST for every method of that class; plain
    local assignments bind within their function. Unresolvable subjects
    (relay infrastructure forwarding a variable) are skipped — the rule
    only judges what it can prove."""

    def subject_const_of(expr: ast.expr, env: dict[str, str]) -> Optional[str]:
        # direct: event_subject(CONST) (or any call carrying the CONST name)
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                leaf = _dotted(sub.func).rsplit(".", 1)[-1]
                if leaf == "event_subject" and sub.args:
                    a = sub.args[0]
                    if isinstance(a, ast.Name) and a.id in model.subject_constants:
                        return a.id
        if isinstance(expr, ast.Name):
            if expr.id in model.subject_constants:
                return expr.id
            return env.get(expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return env.get("self." + expr.attr)
        return None

    def bind_env(scope: ast.AST, env: dict[str, str]) -> None:
        """Pass 1: collect name/self-attr bindings to subjects — plain
        assignments, and the property pattern (a method whose return
        resolves to a subject binds ``self.<method>``, covering
        ``TraceCollector.subject``)."""
        for fn in ast.walk(scope):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Return) and sub.value is not None:
                        const = subject_const_of(sub.value, env)
                        if const is not None:
                            env.setdefault("self." + fn.name, const)
        for sub in ast.walk(scope):
            if not isinstance(sub, ast.Assign):
                continue
            const = subject_const_of(sub.value, env)
            if const is None:
                continue
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) and isinstance(
                    tgt.value, ast.Name
                ) and tgt.value.id == "self":
                    env["self." + tgt.attr] = const
                elif isinstance(tgt, ast.Name):
                    env[tgt.id] = const

    def scan_uses(scope: ast.AST, env: dict[str, str], label: str) -> None:
        """Pass 2: resolve publish/subscribe call sites against env."""
        for sub in ast.walk(scope):
            if not isinstance(sub, ast.Call):
                continue
            leaf = _dotted(sub.func).rsplit(".", 1)[-1]
            if leaf not in ("publish", "subscribe") or not sub.args:
                continue
            const = subject_const_of(sub.args[0], env)
            if const is None:
                continue
            target = (
                model.subjects_published if leaf == "publish"
                else model.subjects_subscribed
            )
            _add(target, const, Site(path, sub.lineno, f"{leaf} in {label}"))

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            env: dict[str, str] = {}
            bind_env(node, env)
            scan_uses(node, env, node.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            env = {}
            bind_env(node, env)
            scan_uses(node, env, f"{node.name}()")


def _header_plane(path: str, tree: ast.Module, model: ProjectModel) -> None:
    if not path.endswith(WIRE_HEADER_MODULES) and path not in WIRE_HEADER_MODULES:
        return

    def is_header_name(expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in HEADER_DICT_NAMES
        if isinstance(expr, ast.Attribute):
            return expr.attr in HEADER_DICT_NAMES
        return False

    for node in ast.walk(tree):
        # dict literal bound to a header name: {"k": v, ...}
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            if any(is_header_name(t) for t in node.targets):
                for k in node.value.keys:
                    key = _str_const(k) if k is not None else None
                    if key is not None:
                        _add(model.header_writes, key,
                             Site(path, k.lineno, "header dict literal"))
        # head["k"] = v
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and is_header_name(tgt.value):
                    key = _str_const(tgt.slice)
                    if key is not None:
                        _add(model.header_writes, key,
                             Site(path, tgt.lineno, "header key store"))
        elif isinstance(node, ast.Call):
            leaf = _dotted(node.func).rsplit(".", 1)[-1]
            if leaf == "get" and node.args:
                key = _str_const(node.args[0])
                if key is not None:
                    _add(model.header_tolerant_reads, key,
                         Site(path, node.lineno, ".get read"))
            elif leaf == "header_field" and node.args:
                key = _str_const(node.args[-1])
                if key is not None:
                    _add(model.header_tolerant_reads, key,
                         Site(path, node.lineno, "header_field read"))
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            if is_header_name(node.value):
                key = _str_const(node.slice)
                if key is not None:
                    _add(model.header_subscript_reads, key,
                         Site(path, node.lineno, "intolerant [] read"))


def _dict_keys_of(node: ast.Dict, path: str, note: str,
                  out: dict[str, list[Site]]) -> None:
    for k in node.keys:
        key = _str_const(k) if k is not None else None
        if key is not None:
            _add(out, key, Site(path, k.lineno, note))


def _stats_producers(path: str, tree: ast.Module, model: ProjectModel) -> None:
    for suffix, fn_name in STAT_PRODUCERS:
        if not path.endswith(suffix):
            continue
        if fn_name is not None:
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node.name == fn_name:
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Dict):
                            _dict_keys_of(sub, path, f"{fn_name}()",
                                          model.stats_produced)
                        elif isinstance(sub, ast.Assign):
                            for tgt in sub.targets:
                                if isinstance(tgt, ast.Subscript):
                                    key = _str_const(tgt.slice)
                                    if key is not None:
                                        _add(model.stats_produced, key,
                                             Site(path, tgt.lineno,
                                                  f"{fn_name}() store"))
        else:
            # dict literals assigned to stats/_stats/COUNTERS targets +
            # later subscript stores on those names
            def is_stats_target(t: ast.expr) -> bool:
                if isinstance(t, ast.Name):
                    return t.id in ("stats", "_stats", "COUNTERS")
                if isinstance(t, ast.Attribute):
                    return t.attr in ("stats", "_stats")
                return False

            for node in ast.walk(tree):
                if isinstance(node, ast.Assign):
                    if isinstance(node.value, ast.Dict) and any(
                        is_stats_target(t) for t in node.targets
                    ):
                        _dict_keys_of(node.value, path, "stats dict",
                                      model.stats_produced)
                    else:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Subscript) \
                                    and is_stats_target(tgt.value):
                                key = _str_const(tgt.slice)
                                if key is not None:
                                    _add(model.stats_produced, key,
                                         Site(path, tgt.lineno, "stats store"))
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Subscript
                ) and is_stats_target(node.target.value):
                    key = _str_const(node.target.slice)
                    if key is not None:
                        _add(model.stats_produced, key,
                             Site(path, node.target.lineno, "stats counter"))


def _workerload_plane(path: str, tree: ast.Module, model: ProjectModel) -> None:
    if path.endswith(FROM_STATS_MODULE) or path == FROM_STATS_MODULE:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == "WorkerLoad":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        model.workerload_fields[stmt.target.id] = Site(
                            path, stmt.lineno, "WorkerLoad field"
                        )
                for fn in node.body:
                    if isinstance(fn, ast.FunctionDef) and fn.name == "from_stats":
                        model.from_stats_site = Site(path, fn.lineno,
                                                     "WorkerLoad.from_stats")
                        for sub in ast.walk(fn):
                            if isinstance(sub, ast.Call):
                                leaf = _dotted(sub.func).rsplit(".", 1)[-1]
                                if leaf == "get" and sub.args:
                                    key = _str_const(sub.args[0])
                                    if key is not None:
                                        _add(model.stats_scraped, key,
                                             Site(path, sub.lineno,
                                                  "from_stats .get"))


def _workerload_uses(path: str, tree: ast.Module, model: ProjectModel) -> None:
    """Attribute reads matching WorkerLoad field names. Runs AFTER field
    extraction (second pass over files). Renders = reads in the gauge
    module; consumption = reads anywhere else in dynamo_tpu outside the
    defining module."""
    fields = model.workerload_fields
    if not fields:
        return
    in_render = path.endswith(GAUGE_RENDER_MODULE) or path == GAUGE_RENDER_MODULE
    in_def = path.endswith(FROM_STATS_MODULE) or path == FROM_STATS_MODULE
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load) \
                and node.attr in fields:
            site = Site(path, node.lineno, f".{node.attr} read")
            if in_render:
                _add(model.workerload_rendered, node.attr, site)
            elif not in_def:
                _add(model.workerload_consumed, node.attr, site)
        elif isinstance(node, ast.Call):
            # getattr(load, "field", ...) consumption (costmodel style)
            if _dotted(node.func) == "getattr" and len(node.args) >= 2:
                key = _str_const(node.args[1])
                if key in fields and not in_def:
                    target = (
                        model.workerload_rendered if in_render
                        else model.workerload_consumed
                    )
                    _add(target, key, Site(path, node.lineno, "getattr read"))


def _wire_classes(path: str, tree: ast.Module, model: ProjectModel) -> None:
    if not (path.endswith(WIRE_PROTOCOL_MODULES) or path in WIRE_PROTOCOL_MODULES):
        return
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        meth = {
            f.name for f in node.body
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not ({"to_bytes", "to_json"} & meth):
            continue  # not a wire roundtrip class
        wc = WireClass(node.name, path, node.lineno)
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                wc.fields[stmt.target.id] = Site(
                    path, stmt.lineno, f"{node.name}.{stmt.target.id}"
                )
        if wc.fields:
            model.wire_classes[node.name] = wc


def _wire_class_reads(path: str, tree: ast.Module, model: ProjectModel) -> None:
    """Typed-flow consumption trace for wire dataclass fields: a symbol
    assigned from ``C.from_bytes(...)`` / ``C(...)`` (or annotated
    ``x: C``) types it as C; attribute reads on typed symbols count as
    consumption of that class's field. One level of Name-to-Name /
    self-attr propagation covers the collector pattern
    (``self.planner_decision = C.from_bytes(...)`` ... ``d = self.
    planner_decision``). Protocol modules themselves are excluded —
    ``to_bytes`` reading its own fields is not consumption."""
    if path.endswith(WIRE_PROTOCOL_MODULES) or path in WIRE_PROTOCOL_MODULES:
        return
    classes = model.wire_classes
    if not classes:
        return

    def class_of_value(expr: ast.expr, env: dict[str, str]) -> Optional[str]:
        if isinstance(expr, ast.Call):
            dotted = _dotted(expr.func)
            head = dotted.split(".", 1)[0]
            leaf = dotted.rsplit(".", 1)[-1]
            if head in classes and leaf in (head, "from_bytes", "from_json"):
                return head
            # C.from_bytes spelled via module alias: protocols.C.from_bytes
            for cname in classes:
                if f"{cname}.from_bytes" in dotted or f"{cname}.from_json" in dotted:
                    return cname
                if dotted == cname or dotted.endswith("." + cname):
                    return cname
        if isinstance(expr, ast.Name):
            return env.get(expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return env.get("self." + expr.attr)
        return None

    def ann_class(ann: Optional[ast.expr]) -> Optional[str]:
        if ann is None:
            return None
        try:
            txt = ast.unparse(ann)
        except Exception:  # noqa: BLE001
            return None
        txt = txt.strip("'\"")
        for cname in classes:
            if txt == cname or txt.endswith("." + cname) \
                    or txt == f"Optional[{cname}]" \
                    or txt.endswith(f"[{cname}]"):
                return cname
        return None

    # env is file-wide (self-attrs are class-scoped in reality; a file-
    # wide map over-approximates consumption, which is the safe
    # direction for a dead-field rule)
    env: dict[str, str] = {}
    for _pass in range(2):  # two passes reach one propagation hop
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                c = class_of_value(node.value, env)
                if c is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        env[tgt.id] = c
                    elif isinstance(tgt, ast.Attribute) and isinstance(
                        tgt.value, ast.Name
                    ) and tgt.value.id == "self":
                        env["self." + tgt.attr] = c
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                c = ann_class(node.annotation)
                if c is not None:
                    env[node.target.id] = c
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = list(node.args.args) + list(node.args.kwonlyargs)
                for a in args:
                    c = ann_class(a.annotation)
                    if c is not None:
                        env[a.arg] = c

    reads = model.wire_field_reads
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)):
            continue
        base = node.value
        cname = None
        if isinstance(base, ast.Name):
            cname = env.get(base.id)
        elif isinstance(base, ast.Attribute) and isinstance(
            base.value, ast.Name
        ) and base.value.id == "self":
            cname = env.get("self." + base.attr)
        if cname is None:
            continue
        wc = classes.get(cname)
        if wc is not None and node.attr in wc.fields:
            reads.setdefault(cname, {}).setdefault(node.attr, []).append(
                Site(path, node.lineno, f"{cname}.{node.attr} read")
            )


def _metric_renders(path: str, tree: ast.Module, model: ProjectModel) -> None:
    """Rendered-series extraction for the dashboard contract. The render
    modules declare their families instead of burying them in f-strings:
    ALL_CAPS string constants (and tuples of them) name series suffixes,
    ``gauge(...)``/``hist_rows(...)`` calls name gauges/histogram
    families, ``HistogramVec(...)`` names a labeled family. The set
    over-approximates (any underscore-bearing ALL_CAPS string counts),
    which keeps the rule quiet unless a queried series is genuinely
    absent from the whole render surface."""
    if not (path.endswith(METRIC_RENDER_MODULES)
            or path in METRIC_RENDER_MODULES):
        return

    def looks_like_metric(s: str) -> bool:
        import re

        return bool(re.fullmatch(r"[a-z][a-z0-9_]*", s)) and "_" in s

    def add(name: str, lineno: int, note: str) -> None:
        _add(model.metrics_rendered, name, Site(path, lineno, note))

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Name) and tgt.id.isupper()):
                continue
            vals = (
                node.value.elts
                if isinstance(node.value, (ast.Tuple, ast.List))
                else [node.value]
            )
            for v in vals:
                s = _str_const(v)
                if s is not None and looks_like_metric(s):
                    add(s, v.lineno, f"{tgt.id} constant")
        elif isinstance(node, ast.Call) and node.args:
            leaf = _dotted(node.func).rsplit(".", 1)[-1]
            if leaf in ("gauge", "hist_rows", "HistogramVec"):
                s = _str_const(node.args[0])
                if s is not None and looks_like_metric(s):
                    add(s, node.lineno, f"{leaf}() render")


def _conn_plane(path: str, tree: ast.Module, model: ProjectModel) -> None:
    """Connection-info capability advertisement (``conn["kv_ici"] = 1``)
    vs peer-side checks (``connection.get("kv_ici")``)."""

    def is_conn(expr: ast.expr) -> bool:
        name = ""
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute):
            name = expr.attr
        name = name.lower()
        return any(t in name for t in CONN_NAMES)

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and is_conn(tgt.value):
                    key = _str_const(tgt.slice)
                    if key is not None:
                        _add(model.conn_advertised, key,
                             Site(path, tgt.lineno, "advertised"))
            if isinstance(node.value, ast.Dict) and any(
                is_conn(t) for t in node.targets
            ):
                for k in node.value.keys:
                    key = _str_const(k) if k is not None else None
                    if key is not None:
                        _add(model.conn_advertised, key,
                             Site(path, k.lineno, "advertised (literal)"))
        elif isinstance(node, ast.Call):
            leaf = _dotted(node.func).rsplit(".", 1)[-1]
            if leaf == "get" and node.args and isinstance(
                node.func, ast.Attribute
            ) and is_conn(node.func.value):
                key = _str_const(node.args[0])
                if key is not None:
                    _add(model.conn_checked, key,
                         Site(path, node.lineno, "peer check"))


_DECL_RE = None


def _comment_tokens(source: str) -> list[tuple[int, str]]:
    import io
    import tokenize

    # real COMMENT tokens only — a docstring *describing* a marker
    # (this package's own docs) must not count as one
    try:
        return [
            (t.start[0], t.string)
            for t in tokenize.generate_tokens(io.StringIO(source).readline)
            if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []


def _subject_declarations(path: str, source: str, model: ProjectModel) -> None:
    """Explicit pub/sub declarations for sites the resolver can't trace
    (a subject handed through a constructor parameter, e.g. the
    BusExporter's)::

        # dynflow: publishes=TRACE_EVENTS_SUBJECT
        # dynflow: subscribes=KV_EVENT_SUBJECT,KV_PREFETCH_SUBJECT

    The named constant must exist; unknown names are ignored (the
    declared-but-unused rule would otherwise be gameable by comment).
    """
    import re

    global _DECL_RE
    if _DECL_RE is None:
        _DECL_RE = re.compile(
            r"dynflow:\s*(publishes|subscribes)\s*=\s*([\w,\s]+)"
        )
    for lineno, comment in _comment_tokens(source):
        m = _DECL_RE.search(comment)
        if not m:
            continue
        kind, names = m.group(1), m.group(2)
        target = (
            model.subjects_published if kind == "publishes"
            else model.subjects_subscribed
        )
        for name in (n.strip() for n in names.split(",")):
            if name in model.subject_constants:
                _add(target, name, Site(path, lineno, f"declared {kind}"))


def _commit_blocks(path: str, source: str, model: ProjectModel) -> None:
    comments = _comment_tokens(source)
    begin: Optional[int] = None
    note = ""
    for lineno, comment in comments:
        if COMMIT_BLOCK_END in comment:
            if begin is not None:
                model.commit_blocks.append(
                    CommitBlock(path, begin, lineno, note)
                )
                begin = None
        elif COMMIT_BLOCK_BEGIN in comment:
            begin = lineno
            note = comment.split("--", 1)[1].strip() if "--" in comment else ""
    if begin is not None:
        # unterminated marker: close at EOF so the rule still judges it
        # (and a missing end marker shows up as whatever follows failing)
        model.commit_blocks.append(
            CommitBlock(path, begin, len(source.splitlines()) + 1,
                        note + " [unterminated]")
        )


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def build_model(files: dict[str, str]) -> ProjectModel:
    """Extract the project model from ``{relpath: source}``. Paths use
    repo-shaped forward-slash form (``dynamo_tpu/...``); the per-plane
    scopes above match on suffixes, so absolute prefixes are fine."""
    model = ProjectModel()
    trees: dict[str, ast.Module] = {}
    for path, source in files.items():
        if not path.endswith(".py"):
            continue
        try:
            trees[path] = ast.parse(source)
        except SyntaxError as e:
            model.errors.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
    # pass 1: declarations (constants, classes, fields)
    for path, tree in trees.items():
        _subject_constants(path, tree, model)
        _wire_classes(path, tree, model)
        _workerload_plane(path, tree, model)
    # pass 2: uses (need the declarations)
    for path, tree in trees.items():
        _subject_uses(path, tree, model)
        _subject_declarations(path, files[path], model)
        _header_plane(path, tree, model)
        _stats_producers(path, tree, model)
        _workerload_uses(path, tree, model)
        _wire_class_reads(path, tree, model)
        _conn_plane(path, tree, model)
        _metric_renders(path, tree, model)
        _commit_blocks(path, files[path], model)
    return model

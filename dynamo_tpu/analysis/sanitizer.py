"""Asyncio hot-path sanitizer: the runtime half of dynlint.

The static rules catch the patterns we know; this module catches the
*behavior* — under the live test suite (and, via ``--sanitize``, in
production workers):

* **loop-stall detection with stack capture** — a heartbeat timer runs
  on the loop; a watchdog thread measures heartbeat age and, the moment
  it exceeds the threshold, snapshots the loop thread's Python stack
  (``sys._current_frames``) so the report names the blocking frame, not
  just "something took 1.3s". The loop side independently measures the
  exact gap when the heartbeat finally runs, so no stall is missed even
  if the watchdog samples unluckily. This generalizes the old conftest
  debug-mode stall guard (``DYN_LOOP_STALL_S``) without asyncio debug
  overhead.
* **per-lock hold-time histograms** — ``asyncio.Lock`` acquire/release
  are instrumented while active; holds are bucketed per acquire site
  (or per :func:`name_lock` label), so "the device lock was held >100ms
  N times" is a number, not a hunch.
* **leak detection at loop shutdown** — stream writers created while
  active that were never closed (the PR 6 fd-leak class), and tasks
  still pending when the loop winds down.

Zero global state is mutated while inactive; activation monkeypatches
are restored on deactivate. Counters aggregate process-wide in
:data:`COUNTERS` so the engine's ``load_metrics`` can export them (the
metrics component turns them into gauges — production stalls are
observable, not just test-time).
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time
import traceback
import weakref
from dataclasses import dataclass, field
from typing import Any, Optional

__all__ = [
    "LoopSanitizer",
    "SanitizerError",
    "SanitizerReport",
    "run_sanitized",
    "name_lock",
    "counters",
    "reset_counters",
    "register_executor",
    "executor_pending",
]

#: hold/stall histogram bucket upper bounds (seconds)
BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, float("inf"))


class SanitizerError(AssertionError):
    """Raised by :func:`run_sanitized` in strict mode on violations."""


def name_lock(lock: asyncio.Lock, name: str) -> asyncio.Lock:
    """Label a lock so its hold-time histogram is keyed by ``name``
    instead of the acquire site (engine.py names ``_device_lock``)."""
    lock._dyn_san_name = name  # type: ignore[attr-defined]
    return lock


@dataclass
class StallRecord:
    duration_s: float
    stack: str = ""  # loop-thread stack captured DURING the stall ("" if missed)

    def to_dict(self) -> dict:
        return {"duration_s": round(self.duration_s, 4), "stack": self.stack}


@dataclass
class Histogram:
    counts: list = field(default_factory=lambda: [0] * len(BUCKETS))
    total: int = 0
    sum_s: float = 0.0
    max_s: float = 0.0

    def observe(self, v: float) -> None:
        self.total += 1
        self.sum_s += v
        if v > self.max_s:
            self.max_s = v
        for i, ub in enumerate(BUCKETS):
            if v <= ub:
                self.counts[i] += 1
                break

    def to_dict(self) -> dict:
        return {
            "total": self.total,
            "sum_s": round(self.sum_s, 6),
            "max_s": round(self.max_s, 6),
            "buckets": dict(zip([str(b) for b in BUCKETS], self.counts)),
        }


@dataclass
class SanitizerReport:
    stalls: list = field(default_factory=list)
    lock_holds: dict = field(default_factory=dict)  # site/name -> Histogram
    leaked_writers: list = field(default_factory=list)  # creation sites
    pending_tasks: list = field(default_factory=list)  # repr strings

    @property
    def max_stall_s(self) -> float:
        return max((s.duration_s for s in self.stalls), default=0.0)

    def to_dict(self) -> dict:
        return {
            "stalls": [s.to_dict() for s in self.stalls],
            "lock_holds": {k: h.to_dict() for k, h in self.lock_holds.items()},
            "leaked_writers": list(self.leaked_writers),
            "pending_tasks": list(self.pending_tasks),
        }


#: process-wide counters (survive individual sanitizer runs) — exported
#: by engine.load_metrics -> WorkerLoad -> metrics-component gauges
COUNTERS = {
    "san_loop_stalls": 0,
    "san_loop_stall_max_ms": 0.0,
    "san_lock_holds": 0,
    "san_lock_hold_max_ms": 0.0,
    "san_writers_leaked": 0,
    # deepest pending-task backlog any registered executor has reached
    # (register_executor): the engine registers its device-dispatch
    # executor, the offload manager its d2h/disk pool — a wedged
    # executor shows up here before it shows up as TTFT
    "san_executor_pending_max": 0,
}


def counters() -> dict:
    """Snapshot of the process-wide sanitizer counters (load_metrics)."""
    return dict(COUNTERS)


def reset_counters() -> None:
    for k in COUNTERS:
        COUNTERS[k] = 0.0 if k.endswith("_ms") else 0
    _EXEC_PENDING.clear()
    _EXEC_PENDING_MAX.clear()


# ---------------- executor pressure ----------------

#: live + high-water pending-task depth per registered executor name.
#: _EXEC_LOCK serializes the read-modify-writes: submits land on the
#: event-loop thread while done-callbacks fire on executor worker
#: threads — unlocked, two simultaneous completions could both read N
#: and write N-1, permanently inflating the pending count (and with it
#: the gauge this plumbing exists to keep honest)
_EXEC_LOCK = threading.Lock()
_EXEC_PENDING: dict[str, int] = {}
_EXEC_PENDING_MAX: dict[str, int] = {}
_EXEC_REGISTERED: "weakref.WeakSet" = None  # type: ignore[assignment]


def register_executor(executor, name: str):
    """Track a ``ThreadPoolExecutor``'s pending-task depth: ``submit``
    is wrapped (instance attribute — the class stays untouched) to
    count tasks in, a done-callback counts them out, and the high-water
    mark exports as ``san_executor_pending_max`` through the normal
    counter plane (load_metrics -> WorkerLoad.executor_pending_max ->
    the ``executor_pending_max`` gauge). Re-registering the same
    executor is a no-op; tracking is always-on and O(1) per submit."""
    global _EXEC_REGISTERED
    if _EXEC_REGISTERED is None:
        _EXEC_REGISTERED = weakref.WeakSet()
    if executor is None or executor in _EXEC_REGISTERED:
        return executor
    orig_submit = executor.submit
    _EXEC_PENDING.setdefault(name, 0)
    _EXEC_PENDING_MAX.setdefault(name, 0)

    def _done(_fut, _name=name):
        with _EXEC_LOCK:
            _EXEC_PENDING[_name] = max(_EXEC_PENDING.get(_name, 1) - 1, 0)

    def submit(fn, /, *args, **kwargs):
        with _EXEC_LOCK:
            depth = _EXEC_PENDING.get(name, 0) + 1
            _EXEC_PENDING[name] = depth
            if depth > _EXEC_PENDING_MAX.get(name, 0):
                _EXEC_PENDING_MAX[name] = depth
                if depth > COUNTERS["san_executor_pending_max"]:
                    COUNTERS["san_executor_pending_max"] = depth
        try:
            fut = orig_submit(fn, *args, **kwargs)
        except Exception:
            _done(None)  # submit refused (shutdown): not pending
            raise
        fut.add_done_callback(_done)
        return fut

    executor.submit = submit
    _EXEC_REGISTERED.add(executor)
    return executor


def executor_pending() -> dict:
    """Per-executor live and high-water pending depths (diagnostics;
    the scrape plane carries only the global high-water counter)."""
    return {
        name: {"pending": _EXEC_PENDING.get(name, 0), "max": mx}
        for name, mx in _EXEC_PENDING_MAX.items()
    }


def _caller_site(skip_prefixes=("asyncio", "analysis/sanitizer")) -> str:
    """filename:lineno of the nearest frame outside asyncio/this module."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename.replace("\\", "/")
        if not any(p in fn for p in skip_prefixes):
            short = "/".join(fn.rsplit("/", 3)[1:])
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class LoopSanitizer:
    """One activation per event loop. See the module doc for what it
    watches. ``activate`` must run ON the target loop; ``deactivate``
    runs after the loop work is done (still inside the loop for pending-
    task inspection, or just after ``asyncio.run`` returns for writer
    leak accounting — :func:`run_sanitized` sequences this correctly)."""

    def __init__(
        self,
        stall_threshold_s: float = 1.0,
        capture_stacks: bool = True,
    ):
        self.stall_threshold_s = stall_threshold_s
        self.capture_stacks = capture_stacks
        self.report = SanitizerReport()
        self._active = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread_id: Optional[int] = None
        self._last_beat = 0.0
        self._beat_handle: Optional[asyncio.TimerHandle] = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop_watchdog = threading.Event()
        self._stall_stack: Optional[str] = None  # captured mid-stall
        self._orig_acquire = None
        self._orig_release = None
        self._orig_writer_init = None
        self._orig_writer_close = None
        self._holds: dict[int, tuple[float, str]] = {}
        #: id(writer) -> creation site, pruned on close/wait_closed
        self._writers: dict[int, str] = {}
        self._writer_refs: dict[int, Any] = {}

    # ---------------- lifecycle ----------------

    def activate(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> "LoopSanitizer":
        if self._active:
            return self
        self._active = True
        self._loop = loop or asyncio.get_running_loop()
        self._loop_thread_id = threading.get_ident()
        self._patch_locks()
        self._patch_writers()
        if self.stall_threshold_s > 0:
            self._last_beat = time.monotonic()
            self._schedule_beat()
            if self.capture_stacks:
                self._stop_watchdog.clear()
                self._watchdog = threading.Thread(
                    target=self._watch, name="dyn-san-watchdog", daemon=True
                )
                self._watchdog.start()
        return self

    def before_shutdown(self) -> None:
        """Call on the loop, after the workload, before the loop closes:
        snapshots tasks still pending (other than the caller's)."""
        try:
            current = asyncio.current_task()
            for t in asyncio.all_tasks():
                if t is current or t.done():
                    continue
                self.report.pending_tasks.append(repr(t))
        except RuntimeError:  # not on a loop — nothing to inspect
            pass

    def deactivate(self) -> SanitizerReport:
        if not self._active:
            return self.report
        self._active = False
        if self._beat_handle is not None:
            self._beat_handle.cancel()
            self._beat_handle = None
        self._stop_watchdog.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=1.0)
            self._watchdog = None
        self._unpatch_locks()
        self._unpatch_writers()
        # writers never closed = leaked (half-closed transports keep fds).
        # _writer_refs holds weakrefs: a ref that resolves to None was
        # GC'd without close() — still a leak (the fd lived until the
        # collector ran), but the tracking itself must not pin memory
        for wid, site in self._writers.items():
            ref = self._writer_refs.get(wid)
            w = ref() if ref is not None else None
            transport = getattr(w, "transport", None) if w is not None else None
            if transport is not None and transport.is_closing():
                continue  # peer-initiated teardown in flight — not a leak
            self.report.leaked_writers.append(site)
            COUNTERS["san_writers_leaked"] += 1
        self._writers.clear()
        self._writer_refs.clear()
        self._loop = None
        return self.report

    # ---------------- loop-stall detection ----------------

    def _schedule_beat(self) -> None:
        # fine-grained: the beat itself measures the true gap, the
        # interval only bounds watchdog detection latency
        interval = max(min(self.stall_threshold_s / 4.0, 0.05), 0.01)
        self._beat_handle = self._loop.call_later(interval, self._beat)

    def _beat(self) -> None:
        now = time.monotonic()
        gap = now - self._last_beat
        self._last_beat = now
        if gap > self.stall_threshold_s:
            # the loop just came back from a stall at least this long;
            # attach the stack the watchdog grabbed while it was stuck
            stack = self._stall_stack or ""
            self._stall_stack = None
            self.report.stalls.append(StallRecord(gap, stack))
            COUNTERS["san_loop_stalls"] += 1
            COUNTERS["san_loop_stall_max_ms"] = max(
                COUNTERS["san_loop_stall_max_ms"], gap * 1e3
            )
        if self._active:
            self._schedule_beat()

    def _watch(self) -> None:
        interval = max(min(self.stall_threshold_s / 4.0, 0.05), 0.01)
        while not self._stop_watchdog.wait(interval):
            age = time.monotonic() - self._last_beat
            if age > self.stall_threshold_s and self._stall_stack is None:
                frame = sys._current_frames().get(self._loop_thread_id)
                if frame is not None:
                    self._stall_stack = "".join(
                        traceback.format_stack(frame, limit=25)
                    )

    # ---------------- lock hold histograms ----------------

    def _patch_locks(self) -> None:
        san = self
        self._orig_acquire = asyncio.Lock.acquire
        self._orig_release = asyncio.Lock.release
        orig_acquire, orig_release = self._orig_acquire, self._orig_release

        async def acquire(lock):  # noqa: ANN001
            result = await orig_acquire(lock)
            key = getattr(lock, "_dyn_san_name", None) or _caller_site()
            san._holds[id(lock)] = (time.monotonic(), key)
            return result

        def release(lock):  # noqa: ANN001
            entry = san._holds.pop(id(lock), None)
            if entry is not None:
                t0, key = entry
                dt = time.monotonic() - t0
                hist = san.report.lock_holds.setdefault(key, Histogram())
                hist.observe(dt)
                COUNTERS["san_lock_holds"] += 1
                COUNTERS["san_lock_hold_max_ms"] = max(
                    COUNTERS["san_lock_hold_max_ms"], dt * 1e3
                )
            return orig_release(lock)

        asyncio.Lock.acquire = acquire
        asyncio.Lock.release = release

    def _unpatch_locks(self) -> None:
        if self._orig_acquire is not None:
            asyncio.Lock.acquire = self._orig_acquire
            asyncio.Lock.release = self._orig_release
            self._orig_acquire = self._orig_release = None
        self._holds.clear()

    # ---------------- writer leak tracking ----------------

    def _patch_writers(self) -> None:
        san = self
        StreamWriter = asyncio.streams.StreamWriter
        self._orig_writer_init = StreamWriter.__init__
        self._orig_writer_close = StreamWriter.close
        orig_init, orig_close = self._orig_writer_init, self._orig_writer_close

        def __init__(w, *args, **kwargs):  # noqa: ANN001,N807
            orig_init(w, *args, **kwargs)
            san._writers[id(w)] = _caller_site()
            # weakref only: a long-lived production sanitizer
            # (dynamo_run --sanitize) must never pin dropped writers —
            # the leak DETECTOR must not itself leak the transports
            san._writer_refs[id(w)] = weakref.ref(w)

        def close(w):  # noqa: ANN001
            san._writers.pop(id(w), None)
            san._writer_refs.pop(id(w), None)
            return orig_close(w)

        StreamWriter.__init__ = __init__
        StreamWriter.close = close

    def _unpatch_writers(self) -> None:
        if self._orig_writer_init is not None:
            asyncio.streams.StreamWriter.__init__ = self._orig_writer_init
            asyncio.streams.StreamWriter.close = self._orig_writer_close
            self._orig_writer_init = self._orig_writer_close = None


def run_sanitized(
    coro,
    stall_s: float = 1.0,
    strict_stalls: bool = False,
    strict_writers: bool = False,
    capture_stacks: bool = True,
) -> Any:
    """``asyncio.run`` with the sanitizer active around ``coro``.

    Raises :class:`SanitizerError` after the workload completes if a
    strict mode saw violations; the coroutine's own result/exception is
    otherwise passed through. ``stall_s <= 0`` disables stall tracking
    (lock/writer accounting stays on — it is nearly free)."""
    san = LoopSanitizer(stall_threshold_s=stall_s, capture_stacks=capture_stacks)

    async def _main():
        san.activate(asyncio.get_running_loop())
        try:
            return await coro
        finally:
            san.before_shutdown()

    try:
        result = asyncio.run(_main())
    finally:
        report = san.deactivate()
    problems: list[str] = []
    if strict_stalls and report.stalls:
        worst = max(report.stalls, key=lambda s: s.duration_s)
        problems.append(
            f"{len(report.stalls)} event-loop stall(s) beyond {stall_s}s "
            f"(worst {worst.duration_s:.2f}s) — scheduler/offload work "
            "blocked the loop (PR-1 async invariant)"
            + (f"\nstack during stall:\n{worst.stack}" if worst.stack else "")
        )
    if strict_writers and report.leaked_writers:
        problems.append(
            f"{len(report.leaked_writers)} stream writer(s) never closed "
            "(fd leak under churn — PR 6 invariant); created at:\n  "
            + "\n  ".join(report.leaked_writers)
        )
    if problems:
        raise SanitizerError("\n".join(problems))
    return result

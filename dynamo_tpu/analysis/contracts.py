"""dynflow contract rules: cross-file checks over the project model.

Each rule encodes a bug class a PR 6-12 review pass actually caught by
hand — a contract spanning 3+ files where one end silently went dead.
Findings carry an EVIDENCE CHAIN (both ``file:line`` ends), anchored for
suppression at the declaration end:

* ``subject-without-subscriber`` — a bus subject constant published with
  no subscriber anywhere (or subscribed with no publisher, or declared
  and used by nobody). History: every listener class filters its own
  subject; a typo'd or half-wired subject drops events on the floor
  with zero errors.
* ``header-write-without-tolerant-read`` — a wire header key written by
  a sender that no decoder reads via ``.get``/``header_field``. History:
  the codec forward-compat contract (PR 2/PR 6) says decoders read
  tolerantly; a key only ever subscripted (or never read at all) is one
  schema skew away from a mid-protocol ``KeyError`` — or is dead weight
  on every frame.
* ``unscraped-stat`` — a key deliberately placed on the scrape surface
  (``load_metrics`` / merged ``stats()``/``counters()`` producers) that
  ``WorkerLoad.from_stats`` never reads. History: PR 9 *documented*
  ``disk_corrupt_discards``/``peer_serve_blocks_total`` as gauges; the
  scrape mapping never picked them up and nobody noticed for three PRs.
* ``stat-scrape-without-producer`` — the inverse: ``from_stats`` reads a
  key nothing produces, so the WorkerLoad field is frozen at its
  default and every gauge built on it lies.
* ``unrendered-gauge`` — a ``WorkerLoad`` field that neither the metrics
  component renders nor any router/planner code reads: scrape plumbing
  with no consumer.
* ``dead-wire-field`` — a wire-dataclass field that is serialized but
  never attribute-read outside its protocol module. History: PR 12's
  ``MorphDecision.pool`` rode the wire for a whole PR while the
  listener ignored it — a decode-pool grow would have morphed prefill
  workers.
* ``version-advertised-unchecked`` — a capability key stamped into
  connection info that no peer-side code ever ``.get``-checks. History:
  ``kv_stream``/``kv_ici`` negotiation only works because BOTH ends
  exist; an advertised-but-unchecked flag is a fast path that silently
  never engages.
* ``dashboard-metric-without-producer`` — a ``dynamo_tpu_*`` series
  queried by any expr in the shipped Grafana dashboard that no render
  site produces (metric constants / ``gauge()`` / ``hist_rows()`` /
  ``HistogramVec()`` in the metric render modules). History: the
  dashboard shipped ``*_seconds_bucket`` panels for histogram families
  whose labels/render drifted across PRs — a flatlined panel raises no
  error anywhere, so the drift is machine-checked now (ISSUE 15).
* ``commit-block-purity`` — the engine-local flow rule: inside a
  ``# dynflow: commit-block`` region (the reshard commit PR 12
  established) nothing fallible is allowed — no calls, no awaits, no
  subscripts on non-locals. History: the whole crash-atomicity story
  ("a kill at any phase leaves the engine WHOLLY on one layout") rests
  on the commit being uninterruptible-by-exception; this checker's
  first real-tree run found a fallible call inside it.

Suppress exactly like dynlint, at the anchored line::

    "d2h_flush_pending": len(self._pending),  # dynlint: disable=unscraped-stat -- diagnostic depth, not a fleet gauge
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .program import (
    COMMIT_BLOCK_BEGIN,
    DASHBOARD_FILE,
    GAUGE_RENDER_MODULE,
    METRIC_RENDER_MODULES,
    ProjectModel,
    Site,
    build_model,
)
from .rules import Violation

__all__ = ["ContractRule", "CONTRACT_RULES", "check_contracts", "build_model"]


def _is_test_path(path: str) -> bool:
    name = path.rsplit("/", 1)[-1]
    return "/tests/" in path or path.startswith("tests/") \
        or name.startswith("test_") or name == "conftest.py"


def _prod(sites: Iterable[Site]) -> list[Site]:
    """Production sites only: a contract end that exists only in a test
    file is still dead in the serving stack."""
    return [s for s in sites if not _is_test_path(s.path)]


def _ev(sites: Iterable[Site], limit: int = 4) -> list[Site]:
    sites = list(sites)
    return sites[:limit]


class ContractRule:
    name: str = ""
    summary: str = ""

    def check(
        self, model: ProjectModel, files: dict[str, str]
    ) -> list[Violation]:  # pragma: no cover - interface
        return []


# ---------------------------------------------------------------------------
# 1. subject-without-subscriber
# ---------------------------------------------------------------------------


class SubjectWithoutSubscriberRule(ContractRule):
    name = "subject-without-subscriber"
    summary = "bus subject published/declared with no subscriber (or vice versa)"

    def check(self, model, files):
        out: list[Violation] = []
        for const, (value, decl) in sorted(model.subject_constants.items()):
            pubs = _prod(model.subjects_published.get(const, ()))
            subs = _prod(model.subjects_subscribed.get(const, ()))
            if pubs and not subs:
                out.append(Violation(
                    self.name, decl.path, decl.line,
                    f"subject {value!r} ({const}) is published but nothing "
                    "in the tree subscribes it — events drop silently",
                    evidence=_ev(pubs),
                ))
            elif subs and not pubs:
                out.append(Violation(
                    self.name, decl.path, decl.line,
                    f"subject {value!r} ({const}) is subscribed but nothing "
                    "publishes it — the consumer waits forever",
                    evidence=_ev(subs),
                ))
            elif not pubs and not subs:
                out.append(Violation(
                    self.name, decl.path, decl.line,
                    f"subject {value!r} ({const}) is declared but neither "
                    "published nor subscribed anywhere",
                ))
        return out


# ---------------------------------------------------------------------------
# 2. header-write-without-tolerant-read
# ---------------------------------------------------------------------------


class HeaderWriteWithoutTolerantReadRule(ContractRule):
    name = "header-write-without-tolerant-read"
    summary = "wire header key written but never .get()/header_field()-read"

    def check(self, model, files):
        out: list[Violation] = []
        for key, writes in sorted(model.header_writes.items()):
            writes = _prod(writes)
            if not writes:
                continue
            tol = _prod(model.header_tolerant_reads.get(key, ()))
            if tol:
                continue
            subs = _prod(model.header_subscript_reads.get(key, ()))
            anchor = writes[0]
            if subs:
                out.append(Violation(
                    self.name, anchor.path, anchor.line,
                    f"header key {key!r} is written here but only read "
                    "intolerantly (header[...]) — one schema skew from a "
                    "mid-protocol KeyError (codec forward-compat contract)",
                    evidence=_ev(subs),
                ))
            else:
                out.append(Violation(
                    self.name, anchor.path, anchor.line,
                    f"header key {key!r} is written on the wire but no "
                    "decoder reads it — dead weight on every frame, or a "
                    "consumer that was never wired",
                    evidence=_ev(writes[1:]),
                ))
        return out


# ---------------------------------------------------------------------------
# 3. unscraped-stat / 4. stat-scrape-without-producer
# ---------------------------------------------------------------------------


class UnscrapedStatRule(ContractRule):
    name = "unscraped-stat"
    summary = "stat key on the scrape surface never read by WorkerLoad.from_stats"

    def check(self, model, files):
        if model.from_stats_site is None:
            return []  # scrape mapping not in the file set — nothing to judge
        out: list[Violation] = []
        for key, sites in sorted(model.stats_produced.items()):
            sites = _prod(sites)
            if not sites or key in model.stats_scraped:
                continue
            anchor = sites[0]
            out.append(Violation(
                self.name, anchor.path, anchor.line,
                f"stat {key!r} is advertised on the scrape surface but "
                "WorkerLoad.from_stats never reads it — it reaches no "
                "gauge and no router/planner input",
                evidence=[model.from_stats_site],
            ))
        return out


class StatScrapeWithoutProducerRule(ContractRule):
    name = "stat-scrape-without-producer"
    summary = "from_stats reads a stat key nothing produces (field frozen at default)"

    def check(self, model, files):
        if not model.stats_produced:
            return []  # no producer modules in the file set
        out: list[Violation] = []
        for key, sites in sorted(model.stats_scraped.items()):
            if key in model.stats_produced:
                continue
            anchor = sites[0]
            out.append(Violation(
                self.name, anchor.path, anchor.line,
                f"from_stats reads {key!r} but no producer emits it — the "
                "WorkerLoad field stays at its default and every gauge "
                "built on it lies",
            ))
        return out


# ---------------------------------------------------------------------------
# 5. unrendered-gauge
# ---------------------------------------------------------------------------


class UnrenderedGaugeRule(ContractRule):
    name = "unrendered-gauge"
    summary = "WorkerLoad field with no gauge render and no routing/planner reader"

    #: plumbing fields, not metrics
    EXEMPT = ("worker_id", "ts")

    def check(self, model, files):
        if not model.workerload_fields:
            return []
        if not any(
            p.endswith(GAUGE_RENDER_MODULE) for p in files
        ):
            return []  # render module absent — partial file set
        out: list[Violation] = []
        for fname, decl in sorted(model.workerload_fields.items()):
            if fname in self.EXEMPT:
                continue
            if model.workerload_rendered.get(fname):
                continue
            consumed = _prod(model.workerload_consumed.get(fname, ()))
            if consumed:
                continue
            out.append(Violation(
                self.name, decl.path, decl.line,
                f"WorkerLoad.{fname} is scraped but neither rendered as a "
                "gauge nor read by any router/planner code — dead scrape "
                "plumbing",
            ))
        return out


# ---------------------------------------------------------------------------
# 6. dead-wire-field
# ---------------------------------------------------------------------------


class DeadWireFieldRule(ContractRule):
    name = "dead-wire-field"
    summary = "wire-dataclass field serialized but never consumed (MorphDecision.pool class)"

    def check(self, model, files):
        out: list[Violation] = []
        for cname, wc in sorted(model.wire_classes.items()):
            reads = model.wire_field_reads.get(cname, {})
            for fname, decl in sorted(wc.fields.items()):
                sites = _prod(reads.get(fname, ()))
                if sites:
                    continue
                out.append(Violation(
                    self.name, decl.path, decl.line,
                    f"{cname}.{fname} rides the wire but nothing in the "
                    "tree ever reads it — either a consumer was never "
                    "wired (the MorphDecision.pool bug class) or it is "
                    "dead schema",
                    evidence=[Site(wc.path, wc.line, f"class {cname}")],
                ))
        return out


# ---------------------------------------------------------------------------
# 7. version-advertised-unchecked
# ---------------------------------------------------------------------------


class VersionAdvertisedUncheckedRule(ContractRule):
    name = "version-advertised-unchecked"
    summary = "capability advertised in connection info but never peer-checked"

    def check(self, model, files):
        out: list[Violation] = []
        for key, writes in sorted(model.conn_advertised.items()):
            writes = _prod(writes)
            if not writes:
                continue
            checks = [
                s for s in _prod(model.conn_checked.get(key, ()))
                if not any(s.path == w.path and s.line == w.line
                           for w in writes)
            ]
            if checks:
                continue
            anchor = writes[0]
            out.append(Violation(
                self.name, anchor.path, anchor.line,
                f"connection-info key {key!r} is advertised here but no "
                "peer-side code checks it — the negotiated path silently "
                "never engages (kv_stream/kv_ici contract)",
                evidence=_ev(writes[1:]),
            ))
        return out


# ---------------------------------------------------------------------------
# 8. commit-block-purity
# ---------------------------------------------------------------------------


class CommitBlockPurityRule(ContractRule):
    name = "commit-block-purity"
    summary = "fallible code (call/await/non-local subscript) inside a commit block"

    def _local_names(self, fn: ast.AST) -> set[str]:
        names: set[str] = set()
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = fn.args
            for arg in (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])
            ):
                names.add(arg.arg)
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Name) and isinstance(
                sub.ctx, (ast.Store,)
            ):
                names.add(sub.id)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                for n in ast.walk(sub.target):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        return names

    def _judge_expr(
        self, expr: ast.expr, locals_: set[str], path: str,
        begin: Site, out: list[Violation]
    ) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                try:
                    what = ast.unparse(sub.func)
                except Exception:  # noqa: BLE001
                    what = "<call>"
                out.append(Violation(
                    self.name, path, sub.lineno,
                    f"call `{what}(...)` inside the commit block — a raise "
                    "here leaves the engine torn between layouts; compute "
                    "it before the block and assign the result",
                    evidence=[begin],
                ))
            elif isinstance(sub, (ast.Await, ast.Yield, ast.YieldFrom)):
                out.append(Violation(
                    self.name, path, sub.lineno,
                    "await/yield inside the commit block — the commit must "
                    "be uninterruptible (crash-atomicity contract)",
                    evidence=[begin],
                ))
            elif isinstance(sub, ast.Subscript):
                base = sub.value
                if not (isinstance(base, ast.Name) and base.id in locals_):
                    try:
                        what = ast.unparse(sub)
                    except Exception:  # noqa: BLE001
                        what = "<subscript>"
                    out.append(Violation(
                        self.name, path, sub.lineno,
                        f"`{what}` subscripts a non-local inside the commit "
                        "block — a KeyError/IndexError here leaves the "
                        "engine torn; read it into a local first",
                        evidence=[begin],
                    ))

    def _judge_stmt(
        self, stmt: ast.stmt, locals_: set[str], path: str,
        begin: Site, out: list[Violation]
    ) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._judge_expr(stmt, locals_, path, begin, out)
        elif isinstance(stmt, ast.If):
            self._judge_expr(stmt.test, locals_, path, begin, out)
            for s in list(stmt.body) + list(stmt.orelse):
                self._judge_stmt(s, locals_, path, begin, out)
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            pass  # docstring/ellipsis
        elif isinstance(stmt, ast.Pass):
            pass
        else:
            out.append(Violation(
                self.name, path, stmt.lineno,
                f"{type(stmt).__name__} statement inside the commit block — "
                "only plain assignments (and pure-guard ifs) are allowed",
                evidence=[begin],
            ))

    def check(self, model, files):
        out: list[Violation] = []
        trees: dict[str, ast.Module] = {}
        for cb in model.commit_blocks:
            if _is_test_path(cb.path):
                continue
            tree = trees.get(cb.path)
            if tree is None:
                try:
                    tree = trees[cb.path] = ast.parse(files[cb.path])
                except (KeyError, SyntaxError):
                    continue
            begin = Site(cb.path, cb.begin,
                         f"{COMMIT_BLOCK_BEGIN}" + (f" -- {cb.note}" if cb.note else ""))
            # enclosing function (innermost def containing the region)
            enclosing = None
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    end = getattr(node, "end_lineno", node.lineno)
                    if node.lineno <= cb.begin and end >= cb.end - 1:
                        if enclosing is None or node.lineno > enclosing.lineno:
                            enclosing = node
            scope = enclosing if enclosing is not None else tree
            locals_ = self._local_names(scope) if enclosing is not None else set()

            # walk the scope's statement tree, judging each top-level
            # statement that falls inside the marked region (the judge
            # itself recurses into allowed compound statements)
            def visit(body: list[ast.stmt]) -> None:
                for stmt in body:
                    end = getattr(stmt, "end_lineno", stmt.lineno)
                    if stmt.lineno > cb.begin and end < cb.end:
                        self._judge_stmt(stmt, locals_, cb.path, begin, out)
                        continue
                    for attr in ("body", "orelse", "finalbody"):
                        sub = getattr(stmt, attr, None)
                        if isinstance(sub, list):
                            visit([s for s in sub if isinstance(s, ast.stmt)])
                    for h in getattr(stmt, "handlers", None) or ():
                        visit(h.body)

            body = scope.body if hasattr(scope, "body") else []
            visit(body)
        return out


# ---------------------------------------------------------------------------
# 9. dashboard-metric-without-producer
# ---------------------------------------------------------------------------


class DashboardMetricWithoutProducerRule(ContractRule):
    name = "dashboard-metric-without-producer"
    summary = "Grafana dashboard queries a series no render site produces"

    #: the exposition prefix every shipped series carries
    PREFIX = "dynamo_tpu_"
    #: suffixes Prometheus derives from one histogram family
    _HIST_SUFFIX = re.compile(r"_(bucket|sum|count)$")

    def check(self, model, files):
        if not model.metrics_rendered:
            # no render module in the file set (fixture/partial tree):
            # there is no producer surface to judge queries against
            return []
        produced = set(model.metrics_rendered)
        out: list[Violation] = []
        render_sites = [
            Site(m, 1, "metric render surface")
            for m in METRIC_RENDER_MODULES
            if any(p.endswith(m) or p == m for p in files)
        ]
        for path, src in sorted(files.items()):
            if not path.endswith(DASHBOARD_FILE):
                continue
            queried = sorted(set(re.findall(
                self.PREFIX + r"([a-z0-9_]+)", src
            )))
            for qname in queried:
                base = self._HIST_SUFFIX.sub("", qname)
                if qname in produced or base in produced:
                    continue
                idx = src.find(self.PREFIX + qname)
                line = src.count("\n", 0, max(idx, 0)) + 1
                out.append(Violation(
                    self.name, path, line,
                    f"dashboard queries series "
                    f"'{self.PREFIX}{qname}' but no render site produces "
                    "it — the panel flatlines with zero errors anywhere "
                    "(declare the family in a metric render module, or "
                    "fix/prune the stale panel expr)",
                    evidence=_ev(render_sites),
                ))
        return out


CONTRACT_RULES: tuple[ContractRule, ...] = (
    SubjectWithoutSubscriberRule(),
    HeaderWriteWithoutTolerantReadRule(),
    UnscrapedStatRule(),
    StatScrapeWithoutProducerRule(),
    UnrenderedGaugeRule(),
    DeadWireFieldRule(),
    VersionAdvertisedUncheckedRule(),
    CommitBlockPurityRule(),
    DashboardMetricWithoutProducerRule(),
)


def check_contracts(
    files: dict[str, str],
    rules: tuple[ContractRule, ...] = CONTRACT_RULES,
) -> list[Violation]:
    """Run the contract rules over an in-memory file set (suppressions
    are the caller's job — :func:`.engine.check_program` applies them)."""
    model = build_model(files)
    out: list[Violation] = []
    for err in model.errors:
        path, _, rest = err.partition(":")
        out.append(Violation("syntax-error", path, 0, err))
    for rule in rules:
        out.extend(rule.check(model, files))
    return out

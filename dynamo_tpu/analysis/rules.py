"""The dynlint rule set: one rule per bug class PRs 1-6 shipped and fixed.

Every rule documents the historical incident that motivated it (see
docs/static_analysis.md for the operator-facing catalog). Rules are
deliberately narrow — they encode *this repo's* invariants, not generic
style. A finding is suppressed line-by-line with::

    offending_code()  # dynlint: disable=<rule-name> -- why this is safe

File-scope rules (path predicates) keep the noise down: the async
blocking rule only watches event-loop packages, the header rule only
watches wire decoders, the jit rule only watches serving code (tests
build throwaway jits all the time).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

__all__ = ["Rule", "Violation", "ALL_RULES"]


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str
    #: evidence chain for cross-file (dynflow) findings: the OTHER ends
    #: of the broken contract, as :class:`~.program.Site` objects —
    #: per-file rules leave it empty
    evidence: list = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
        if self.evidence:
            d["evidence"] = [
                s.to_dict() if hasattr(s, "to_dict") else s
                for s in self.evidence
            ]
        return d


#: packages whose code runs on (or adjacent to) the serving event loop —
#: the scope of the async-blocking and lock-discipline rules
EVENT_LOOP_PACKAGES = (
    "dynamo_tpu/engine/",
    "dynamo_tpu/disagg/",
    "dynamo_tpu/http/",
    "dynamo_tpu/kv_router/",
    "dynamo_tpu/planner/",
    "dynamo_tpu/resilience/",
    "dynamo_tpu/runtime/",
    "dynamo_tpu/observability/",
    "dynamo_tpu/tracing/",
    "dynamo_tpu/sdk/",
    "dynamo_tpu/launch/",
)

#: wire-decoder modules bound by the codec forward-compat contract
DECODER_MODULES = (
    "dynamo_tpu/runtime/codec.py",
    "dynamo_tpu/runtime/tcp.py",
    "dynamo_tpu/runtime/component.py",
    "dynamo_tpu/runtime/hub.py",
    "dynamo_tpu/disagg/transfer.py",
    "dynamo_tpu/disagg/worker.py",
)


def _dotted(func: ast.expr) -> str:
    """Best-effort dotted name for a call target: ``time.sleep``,
    ``np.asarray``, ``writer.wait_closed``...  Empty for complex
    expressions (subscripts, calls-of-calls)."""
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        # <expr>.attr — keep the attribute chain, mark the base opaque
        return "?." + ".".join(reversed(parts))
    return ""


def _base_source(func: ast.expr) -> str:
    """Source text of the receiver of an attribute call (``x.y`` of
    ``x.y.close()``) — used for name-pattern matching on lock/writer
    variables."""
    if isinstance(func, ast.Attribute):
        try:
            return ast.unparse(func.value)
        except Exception:  # noqa: BLE001 — unparse of exotic nodes
            return ""
    return ""


def _walk_same_scope(node: ast.AST) -> Iterable[ast.AST]:
    """Walk ``node`` without descending into nested function/class
    definitions: code inside an inner ``def`` does not execute in the
    enclosing scope, so scope-sensitive rules must not attribute it."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        yield sub
        if isinstance(
            sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(sub))


class Rule:
    """Base: per-file AST rule. ``project`` rules instead see the whole
    file set at once (cross-file invariants)."""

    name: str = ""
    summary: str = ""
    project: bool = False

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("dynamo_tpu/")

    def check(
        self, relpath: str, source: str, tree: ast.AST
    ) -> list[Violation]:
        return []

    def check_project(
        self, files: dict[str, str]
    ) -> list[Violation]:  # pragma: no cover - overridden by project rules
        return []


# ---------------------------------------------------------------------------
# 1. async-blocking-call
# ---------------------------------------------------------------------------


class AsyncBlockingCallRule(Rule):
    """Blocking host work inside ``async def`` bodies of event-loop
    modules. PR 1 moved the d2h gathers off the scheduler loop and PR 6
    moved the streamed sender's ``tobytes`` staging copies off it — both
    after shipping a build whose token streams froze for the duration of
    a host copy. ``asyncio.sleep`` is of course fine; ``time.sleep``,
    sync socket/subprocess ops, multi-MB host materialization
    (``.tobytes()`` / ``np.asarray`` of device buffers) and
    ``block_until_ready`` belong in ``run_in_executor``."""

    name = "async-blocking-call"
    summary = "blocking call on the event loop (PR 1/PR 6 invariant)"

    BLOCKING_DOTTED = {
        "time.sleep": "time.sleep blocks the event loop — use asyncio.sleep",
        "socket.create_connection":
            "sync socket connect on the loop — use asyncio.open_connection",
        "socket.getaddrinfo":
            "sync DNS resolution on the loop — use loop.getaddrinfo",
        "subprocess.run": "sync subprocess on the loop — use asyncio.create_subprocess_exec",
        "subprocess.check_output":
            "sync subprocess on the loop — use asyncio.create_subprocess_exec",
        "subprocess.check_call":
            "sync subprocess on the loop — use asyncio.create_subprocess_exec",
        "subprocess.call": "sync subprocess on the loop — use asyncio.create_subprocess_exec",
        "os.system": "sync subprocess on the loop — use asyncio.create_subprocess_exec",
        "jax.block_until_ready":
            "device sync on the loop — run_in_executor (PR 1 invariant)",
        "np.asarray":
            "host materialization on the loop — multi-MB device->host copies "
            "belong in run_in_executor (PR 6 streamed-sender fix)",
        "numpy.asarray":
            "host materialization on the loop — multi-MB device->host copies "
            "belong in run_in_executor (PR 6 streamed-sender fix)",
    }
    #: attribute calls flagged regardless of receiver
    BLOCKING_ATTRS = {
        "tobytes":
            ".tobytes() stages a full host copy on the loop — write buffer "
            "views (codec.write_frame_parts) or copy in an executor",
        "block_until_ready":
            "device sync on the loop — run_in_executor (PR 1 invariant)",
        "recv": "sync socket read on the loop",
        "recv_into": "sync socket read on the loop",
        "sendall": "sync socket write on the loop",
        "accept": "sync socket accept on the loop",
    }
    #: socket-shaped receiver names for the .recv/.sendall/.accept
    #: attribute checks (exact leaf "s", or substring "sock"/"conn" —
    #: NOT a bare "s" substring, which would match nearly any name)
    _SOCKETY = ("sock", "conn")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(EVENT_LOOP_PACKAGES)

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in _walk_same_scope(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                why = self.BLOCKING_DOTTED.get(dotted)
                if why is None and isinstance(sub.func, ast.Attribute):
                    attr = sub.func.attr
                    why = self.BLOCKING_ATTRS.get(attr)
                    if why is not None and attr in (
                        "recv", "recv_into", "sendall", "accept"
                    ):
                        leaf = _base_source(sub.func).rsplit(".", 1)[-1].lower()
                        if leaf != "s" and not any(
                            t in leaf for t in self._SOCKETY
                        ):
                            why = None
                if why is not None:
                    out.append(Violation(
                        self.name, relpath, sub.lineno,
                        f"`{dotted or ast.unparse(sub.func)}` in async "
                        f"`{node.name}`: {why}",
                    ))
        return out


# ---------------------------------------------------------------------------
# 2. await-in-lock
# ---------------------------------------------------------------------------


class AwaitInLockRule(Rule):
    """Network/queue awaits while holding an ``asyncio.Lock``. The
    engine's ``_device_lock`` serializes device mutations; PR 6's review
    found a half-open peer could wedge the prefill engine *under its
    device lock* because the segment send awaited the socket inside the
    critical section. Device dispatch (``run_in_executor``) under the
    lock is the designed pattern; socket/bus/queue waits are not —
    copy out, release, then send."""

    name = "await-in-lock"
    summary = "network/queue await while holding a lock (PR 6 review bug)"

    #: awaited call targets that park the coroutine on I/O another task
    #: (or a remote peer) must complete
    IO_ATTRS = {
        "open_connection", "start_server", "read_frame", "write_frame",
        "write_frame_parts", "drain", "wait_closed", "readexactly",
        "readline", "readuntil", "read", "publish", "subscribe",
        "request", "direct", "round_robin", "send_request", "sendall",
        "finish",
    }
    #: queue-shaped receivers whose get/put block on another task
    QUEUE_ATTRS = {"get", "put", "join"}
    _QUEUEY = ("queue", "_q", "sendq", "recvq", "waiting", "inbox")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(EVENT_LOOP_PACKAGES)

    def _is_lock_ctx(self, item: ast.withitem) -> bool:
        try:
            src = ast.unparse(item.context_expr)
        except Exception:  # noqa: BLE001
            return False
        return "lock" in src.lower()

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.AsyncWith):
                continue
            lock_item = next(
                (i for i in node.items if self._is_lock_ctx(i)), None
            )
            if lock_item is None:
                continue
            lock_src = ast.unparse(lock_item.context_expr)
            for stmt in node.body:
                if isinstance(
                    stmt,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    # a def inside the with-block runs later, outside
                    # the critical section
                    continue
                for sub in _walk_same_scope(stmt):
                    if not isinstance(sub, ast.Await):
                        continue
                    call = sub.value
                    if not isinstance(call, ast.Call):
                        continue
                    dotted = _dotted(call.func)
                    attr = (
                        call.func.attr
                        if isinstance(call.func, ast.Attribute)
                        else dotted
                    )
                    bad = attr in self.IO_ATTRS
                    if not bad and attr in self.QUEUE_ATTRS:
                        base = _base_source(call.func).lower()
                        bad = any(t in base for t in self._QUEUEY)
                    if bad:
                        out.append(Violation(
                            self.name, relpath, sub.lineno,
                            f"await `{dotted or attr}` while holding "
                            f"`{lock_src}`: I/O under a lock serializes the "
                            "loop on a peer — copy out, release, then send",
                        ))
                # the body's own awaits are what's held under the lock;
                # nested async-with lock blocks are walked on their own
        return out


# ---------------------------------------------------------------------------
# 3. jit-in-function
# ---------------------------------------------------------------------------


class JitInFunctionRule(Rule):
    """``jax.jit`` / ``pjit`` constructed inside a function. PR 3 found a
    per-admission ``jax.jit(sample_first_token)`` building a fresh
    wrapper (and tracing a fresh program) for every request — module
    scope amortizes trace+compile over the process. Memoized
    construction (compile once per bucket key into a cache) is the one
    sanctioned exception; suppress it with a justification."""

    name = "jit-in-function"
    summary = "jax.jit/pjit built at call time (PR 3 per-admission regression)"

    JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "shard_map.jit"}

    def applies_to(self, relpath: str) -> bool:
        # serving code only: tests build throwaway jits legitimately
        return relpath.startswith("dynamo_tpu/")

    def _is_jit_call(self, call: ast.Call) -> bool:
        dotted = _dotted(call.func)
        if dotted in self.JIT_NAMES:
            return True
        # functools.partial(jax.jit, ...) — the decorator spelling
        if dotted in ("functools.partial", "partial") and call.args:
            return _dotted(call.args[0]) in self.JIT_NAMES
        return False

    def check(self, relpath, source, tree):
        # decorators on module/class-level defs evaluate at import time —
        # that IS module scope. Only calls inside function BODIES (and
        # decorators of *nested* defs, which evaluate when the enclosing
        # function runs) build wrappers at call time.
        out: list[Violation] = []

        def scan(node: ast.AST, fn_name: str) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and self._is_jit_call(sub):
                    out.append(Violation(
                        self.name, relpath, sub.lineno,
                        f"`{_dotted(sub.func)}` constructed inside "
                        f"`{fn_name}`: builds a fresh traced wrapper per "
                        "call (PR 3 regression) — hoist to module scope, or "
                        "suppress if memoized per static key",
                    ))
                elif isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    # a NESTED def's bare `@jax.jit` decorator evaluates
                    # when the enclosing function runs (call decorators
                    # are Calls, already caught by the walk above)
                    for dec in sub.decorator_list:
                        if not isinstance(dec, ast.Call) and _dotted(
                            dec
                        ) in self.JIT_NAMES:
                            out.append(Violation(
                                self.name, relpath, dec.lineno,
                                f"`{_dotted(dec)}` decorates nested "
                                f"`{sub.name}` inside `{fn_name}`: traces "
                                "a fresh wrapper per call (PR 3 "
                                "regression) — hoist to module scope, or "
                                "suppress if memoized per static key",
                            ))

        def visit_module_scope(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # decorator_list/defaults run at def time (module
                    # scope here) — skip them, scan only the body
                    for stmt in child.body:
                        scan(stmt, child.name)
                elif isinstance(child, ast.ClassDef):
                    visit_module_scope(child)  # methods: same treatment
                else:
                    # module-level statements (incl. decorators already
                    # consumed above) are module scope by definition
                    pass

        visit_module_scope(tree)
        return out


# ---------------------------------------------------------------------------
# 4. raw-header-subscript
# ---------------------------------------------------------------------------


class RawHeaderSubscriptRule(Rule):
    """``header["key"]`` in a wire decoder. The codec's forward-compat
    contract (runtime/codec.py module doc): decoders read the keys they
    know and ignore the rest, via ``.get`` / ``header_field`` — a raw
    subscript turns a newer peer's extra or missing field into a
    ``KeyError`` mid-protocol (PR 2 and PR 6 both grew the header schema
    in flight; old builds kept decoding because of this rule)."""

    name = "raw-header-subscript"
    summary = "intolerant header[key] read in a wire decoder (codec contract)"

    _HEADER_NAMES = ("header", "hdr")
    _HEADER_SOURCES = ("header_json", "header_field")

    def applies_to(self, relpath: str) -> bool:
        return relpath.endswith(DECODER_MODULES) or any(
            relpath.endswith(m.rsplit("/", 1)[-1]) and m in relpath
            for m in DECODER_MODULES
        )

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # names assigned from header_json()/msg.header parses in this
            # function also carry the contract
            header_vars = set(self._HEADER_NAMES)

            def _from_header(value: ast.expr) -> bool:
                if isinstance(value, ast.Call):
                    dotted = _dotted(value.func)
                    return dotted.rsplit(".", 1)[-1] in self._HEADER_SOURCES
                if isinstance(value, ast.BoolOp):
                    # the `frame.header_json() or {}` idiom
                    return any(_from_header(v) for v in value.values)
                return False

            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and _from_header(sub.value):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            header_vars.add(tgt.id)
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Subscript):
                    continue
                if not isinstance(sub.slice, ast.Constant) or not isinstance(
                    sub.slice.value, str
                ):
                    continue
                if isinstance(sub.ctx, (ast.Store, ast.Del)):
                    continue  # building a header dict is fine
                base = sub.value
                name = base.id if isinstance(base, ast.Name) else (
                    base.attr if isinstance(base, ast.Attribute) else ""
                )
                is_hdr = name in header_vars
                if not is_hdr and isinstance(base, ast.Call):
                    is_hdr = (
                        _dotted(base.func).rsplit(".", 1)[-1]
                        in self._HEADER_SOURCES
                    )
                if is_hdr:
                    out.append(Violation(
                        self.name, relpath, sub.lineno,
                        f"`{ast.unparse(sub)}` destructures a wire header — "
                        "use .get()/header_field() (forward-compat contract, "
                        "runtime/codec.py)",
                    ))
        return out


# ---------------------------------------------------------------------------
# 5. writer-wait-closed
# ---------------------------------------------------------------------------


class WriterWaitClosedRule(Rule):
    """``writer.close()`` without ``await writer.wait_closed()`` in the
    same function. PR 6 fixed fd leaks under churn in the KV transfer
    server and the stream sender finallys: ``close()`` only *schedules*
    transport teardown — without ``wait_closed()`` a busy loop accretes
    half-closed sockets until the fd table blows. Applies to
    stream-writer-shaped names (``writer``, ``_writer``, ``w``) and
    asyncio servers (``_server``)."""

    name = "writer-wait-closed"
    summary = "close() without wait_closed() leaks fds under churn (PR 6 fix)"

    _WRITERY = ("writer", "_server", "server")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(EVENT_LOOP_PACKAGES)

    def _writer_targets(self, node) -> tuple[set[str], set[str]]:
        closed: dict[str, int] = {}
        waited: set[str] = set()
        for sub in _walk_same_scope(node):
            if not isinstance(sub, ast.Call):
                continue
            if not isinstance(sub.func, ast.Attribute):
                continue
            base = _base_source(sub.func)
            leaf = base.rsplit(".", 1)[-1].lower()
            if not any(leaf == t or leaf.endswith(t) for t in self._WRITERY):
                continue
            if sub.func.attr == "close":
                closed.setdefault(base, sub.lineno)
            elif sub.func.attr in ("wait_closed", "abort"):
                # abort() is the hard-teardown sibling: no graceful drain
                # to wait for, the transport drops synchronously
                waited.add(base)
        return closed, waited

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            closed, waited = self._writer_targets(node)
            for base, line in closed.items():
                if base not in waited:
                    out.append(Violation(
                        self.name, relpath, line,
                        f"`{base}.close()` without `await "
                        f"{base}.wait_closed()` in `{node.name}`: close only "
                        "schedules teardown — the fd lingers under churn "
                        "(PR 6 transfer-server leak)",
                    ))
        return out


# ---------------------------------------------------------------------------
# 6. faultpoint-test-coverage
# ---------------------------------------------------------------------------


class FaultpointCoverageRule(Rule):
    """Every faultpoint declared in resilience/faultpoints.py must be
    referenced by at least one test. A faultpoint nobody injects is a
    lifecycle stage whose death path silently stopped being exercised —
    the whole value of PR 4's deterministic harness is that worker loss
    at each stage stays a reproducible test input."""

    name = "faultpoint-test-coverage"
    summary = "declared faultpoint never exercised by any test (PR 4 harness)"
    project = True

    FAULTPOINTS_FILE = "dynamo_tpu/resilience/faultpoints.py"

    def check_project(self, files):
        src = None
        for path, text in files.items():
            if path.endswith("resilience/faultpoints.py"):
                src = (path, text)
                break
        if src is None:
            return []
        path, text = src
        try:
            tree = ast.parse(text)
        except SyntaxError:
            # the per-file pass already reported a syntax-error
            # violation for this file; nothing to judge here
            return []
        points: list[tuple[str, int]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "POINTS"
                for t in node.targets
            ):
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            points.append((elt.value, elt.lineno))
        test_blob = "\n".join(
            text for p, text in files.items()
            if p.split("/")[-1].startswith("test_") or "/tests/" in p
        )
        if not test_blob:
            return []  # tests not in the lint path set — nothing to judge
        out = []
        for name, line in points:
            if name not in test_blob:
                out.append(Violation(
                    self.name, path, line,
                    f"faultpoint `{name}` is declared but no test references "
                    "it — its kill/delay path is unexercised (PR 4 contract)",
                ))
        return out


# ---------------------------------------------------------------------------
# 7. swallowed-exception
# ---------------------------------------------------------------------------


class SwallowedExceptionRule(Rule):
    """``except Exception: pass`` (or bare ``except:``) with no logging.
    The scheduler and transfer loops are long-running: an invisible
    swallow turns a protocol bug into a silent stall that only a soak
    test's timeout finds (that is exactly how PR 4's parked-forever
    requests hid). Log at debug or narrow the exception type."""

    name = "swallowed-exception"
    summary = "silent except-pass hides loop failures (PR 4 parked requests)"

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            typ = node.type
            broad = (
                typ is None
                or (isinstance(typ, ast.Name)
                    and typ.id in ("Exception", "BaseException"))
            )
            if not broad:
                continue
            body_real = [
                s for s in node.body
                if not (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant))
            ]
            if all(
                isinstance(s, ast.Pass)
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and s.value.value is Ellipsis)
                for s in body_real
            ):
                out.append(Violation(
                    self.name, relpath, node.lineno,
                    "broad except with a silent pass — log at debug "
                    "(logger.debug(..., exc_info=True)) or narrow the type; "
                    "silent swallows in long-running loops become invisible "
                    "stalls",
                ))
        return out


# ---------------------------------------------------------------------------
# 8. blocking-disk-io
# ---------------------------------------------------------------------------


class BlockingDiskIoRule(Rule):
    """Filesystem I/O inside ``async def`` bodies of event-loop modules.
    The disk KV tier (PR 9) put block files one executor hop from the
    scheduler loop — a stray ``open()`` / ``os.remove`` / ``f.write()``
    on the loop stalls every token stream for a seek's worth of
    milliseconds (or a disk-contention eternity), the same bug class as
    async-blocking-call but for the new tier's I/O surface. Executor
    dispatch passes: ``run_in_executor(None, store.put, ...)`` hands a
    *reference*, so only direct calls in the async body fire. Sync
    helpers (DiskKvStore methods) are where the I/O belongs."""

    name = "blocking-disk-io"
    summary = "filesystem I/O on the event loop (disk-tier invariant)"

    #: direct calls that always hit the filesystem
    BLOCKING_DOTTED = {
        "open": "open() blocks the loop on the filesystem — read/write in "
                "a sync helper dispatched via run_in_executor",
        "os.read": "raw fd read on the loop",
        "os.write": "raw fd write on the loop",
        "os.fsync": "fsync on the loop can stall for a full disk flush",
        "os.remove": "unlink on the loop",
        "os.unlink": "unlink on the loop",
        "os.rename": "rename on the loop",
        "os.replace": "rename on the loop",
        "os.makedirs": "mkdir on the loop",
        "os.listdir": "directory scan on the loop",
        "shutil.rmtree": "recursive delete on the loop",
        "shutil.copyfile": "file copy on the loop",
    }
    #: pathlib's read/write conveniences — filesystem hits regardless of
    #: receiver (no other common type exposes these names)
    PATH_ATTRS = {"read_bytes", "write_bytes", "read_text", "write_text"}
    #: file-object methods, gated on a file-shaped receiver name so
    #: StreamWriter.write / reader.read (non-blocking asyncio) never fire
    FILE_ATTRS = {"write", "read", "readline", "flush"}
    _FILEY = ("file", "fp", "fh")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(EVENT_LOOP_PACKAGES)

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in _walk_same_scope(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                why = self.BLOCKING_DOTTED.get(dotted)
                if why is None and isinstance(sub.func, ast.Attribute):
                    attr = sub.func.attr
                    if attr in self.PATH_ATTRS:
                        why = (f".{attr}() hits the filesystem on the loop "
                               "— dispatch to an executor")
                    elif attr in self.FILE_ATTRS:
                        leaf = _base_source(sub.func).rsplit(".", 1)[-1].lower()
                        if leaf == "f" or any(t in leaf for t in self._FILEY):
                            why = (f"file .{attr}() on the loop — file I/O "
                                   "belongs in a sync helper on the "
                                   "offload executor")
                if why is not None:
                    out.append(Violation(
                        self.name, relpath, sub.lineno,
                        f"`{dotted or ast.unparse(sub.func)}` in async "
                        f"`{node.name}`: {why}",
                    ))
        return out


# ---------------------------------------------------------------------------
# 9. span-leak
# ---------------------------------------------------------------------------


class SpanLeakRule(Rule):
    """A trace span opened by hand (assigned, not ``with``) must be
    ``.end()``-ed in the same function. PR 2's decomposition depends on
    every opened span landing in the ring buffer — a leaked handle
    records nothing, and the TTFT component it covered silently reads as
    zero in /trace and the fleet percentiles."""

    name = "span-leak"
    summary = "span opened without with/end() drops its TTFT component (PR 2)"

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(EVENT_LOOP_PACKAGES) or relpath.startswith(
            "dynamo_tpu/llm/"
        )

    def _is_span_call(self, call: ast.Call) -> bool:
        dotted = _dotted(call.func)
        return dotted.rsplit(".", 1)[-1] == "span" and "span" != dotted

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            assigned: dict[str, int] = {}
            ended: set[str] = set()
            withed: set[str] = set()
            for sub in _walk_same_scope(node):
                if isinstance(sub, ast.Assign) and isinstance(
                    sub.value, ast.Call
                ) and self._is_span_call(sub.value):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            assigned.setdefault(tgt.id, sub.lineno)
                elif isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        ctx = item.context_expr
                        if isinstance(ctx, ast.Name):
                            withed.add(ctx.id)
                        elif isinstance(
                            ctx, ast.Call
                        ) and self._is_span_call(ctx):
                            pass  # direct `with tracing.span(...)` — fine
                elif isinstance(sub, ast.Expr) and isinstance(
                    sub.value, ast.Call
                ):
                    call = sub.value
                    if isinstance(call.func, ast.Attribute) and call.func.attr in (
                        "end", "__exit__"
                    ):
                        base = call.func.value
                        if isinstance(base, ast.Name):
                            ended.add(base.id)
                    elif self._is_span_call(call):
                        out.append(Violation(
                            self.name, relpath, sub.lineno,
                            "span opened and immediately discarded — it will "
                            "never be recorded; use `with tracing.span(...)` "
                            "or keep the handle and .end() it",
                        ))
            for name, line in assigned.items():
                if name not in ended and name not in withed:
                    out.append(Violation(
                        self.name, relpath, line,
                        f"span handle `{name}` is never .end()-ed (or used "
                        "as a context manager) in this function — the span "
                        "drops and its TTFT component reads as zero (PR 2)",
                    ))
        return out


# ---------------------------------------------------------------------------
# 10. mesh-capture
# ---------------------------------------------------------------------------


class MeshCaptureRule(Rule):
    """A concrete ``Mesh``/``NamedSharding`` captured at IMPORT time
    (module or class scope, or a top-level function's default argument)
    in the engine/ops/models/disagg packages. The bug class the elastic
    live-reshard refactor exists to kill (ISSUE 12): a placement
    resolved when the module loads survives a live morph and silently
    pins dispatch to the pre-morph layout — weights move, the captured
    sharding doesn't, and the next dispatch re-lays everything back (or
    crosses device sets and crashes). Placement must resolve at CALL
    time against the engine's current mesh: module scope may hold
    logical ``PartitionSpec``s (mesh-free by construction) and the
    rules tables in parallel/mesh.py; anything that binds devices
    belongs inside a function the reshard path re-runs
    (``LogicalLayout`` / ``MeshMorpher``)."""

    name = "mesh-capture"
    summary = "concrete Mesh/NamedSharding bound at import time (reshard invariant)"

    #: call targets that bind CONCRETE devices (PartitionSpec / P do
    #: not — they are the logical layer module scope is allowed)
    TARGETS = {
        "Mesh",
        "NamedSharding",
        "make_mesh",
        "cache_sharding",
        "param_sharding",
        "shard_params",
        "global_mesh",
    }

    PACKAGES = (
        "dynamo_tpu/engine/",
        "dynamo_tpu/ops/",
        "dynamo_tpu/models/",
        "dynamo_tpu/disagg/",
    )

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(self.PACKAGES)

    def _walk_import_time(self, node: ast.AST, relpath: str, where: str,
                          out: list[Violation]) -> None:
        """Visit exactly what EXECUTES at import: class bodies do;
        function/lambda bodies don't (call time) — but a def's default
        arguments evaluate when the def does, so wherever a def
        executes (module scope, class body, inside a module-level
        if/try), its defaults are import-time and its body is not."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in (
                list(node.args.defaults)
                + [d for d in node.args.kw_defaults if d is not None]
            ):
                self._walk_import_time(
                    default, relpath, f"function default ({node.name})",
                    out)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.ClassDef):
            for sub in ast.iter_child_nodes(node):
                self._walk_import_time(
                    sub, relpath, f"class scope ({node.name})", out)
            return
        if isinstance(node, ast.Call):
            leaf = _dotted(node.func).rsplit(".", 1)[-1]
            if leaf in self.TARGETS:
                out.append(Violation(
                    self.name, relpath, node.lineno,
                    f"`{leaf}(...)` at {where} binds a concrete device "
                    "placement at import time — it goes stale the "
                    "moment the engine morphs its mesh (elastic "
                    "resharding). Resolve placement at call time "
                    "against the current mesh (LogicalLayout) and keep "
                    "module scope to logical PartitionSpecs",
                ))
        for sub in ast.iter_child_nodes(node):
            self._walk_import_time(sub, relpath, where, out)

    def check(self, relpath, source, tree):
        out: list[Violation] = []
        for sub in ast.iter_child_nodes(tree):
            self._walk_import_time(sub, relpath, "module scope", out)
        return out


ALL_RULES: tuple[Rule, ...] = (
    AsyncBlockingCallRule(),
    AwaitInLockRule(),
    JitInFunctionRule(),
    RawHeaderSubscriptRule(),
    WriterWaitClosedRule(),
    FaultpointCoverageRule(),
    SwallowedExceptionRule(),
    BlockingDiskIoRule(),
    SpanLeakRule(),
    MeshCaptureRule(),
)

"""dynlint driver: file discovery, suppression comments, reporting.

Suppression grammar (pylint-style, justification encouraged)::

    code()  # dynlint: disable=rule-a,rule-b -- why this is safe
    # dynlint: disable=rule-a          <- alone on a line: next line
    # dynlint: disable-file=rule-a     <- whole file (first 25 lines)

Suppressions are counted and reported (``--json`` carries them), so a
tree that is "clean" by silencing everything is visible as such.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from .rules import ALL_RULES, Rule, Violation

_SUPPRESS_RE = re.compile(
    r"#\s*dynlint:\s*(disable(?:-file)?)\s*=\s*([\w\-*,\s]+?)\s*(?:--.*)?$"
)

#: directories never linted (fixtures, build junk)
_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv", "build"}


@dataclass
class _Suppressions:
    by_line: dict[int, set[str]] = field(default_factory=dict)
    file_wide: set[str] = field(default_factory=set)

    def covers(self, rule: str, line: int) -> bool:
        if rule in self.file_wide or "*" in self.file_wide:
            return True
        names = self.by_line.get(line, ())
        return rule in names or "*" in names


def _parse_suppressions(source: str) -> _Suppressions:
    sup = _Suppressions()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [
            (t.start[0], t.string, t.line)
            for t in tokens if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        comments = [
            (i + 1, line[line.index("#"):], line)
            for i, line in enumerate(source.splitlines()) if "#" in line
        ]
    for lineno, comment, full_line in comments:
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        kind, names_s = m.group(1), m.group(2)
        names = {n.strip() for n in names_s.split(",") if n.strip()}
        if kind == "disable-file":
            if lineno <= 25:
                sup.file_wide |= names
            continue
        target = lineno
        if full_line.strip().startswith("#"):
            # comment-only line: applies to the NEXT line
            target = lineno + 1
        sup.by_line.setdefault(target, set()).update(names)
    return sup


@dataclass
class LintReport:
    violations: list[Violation] = field(default_factory=list)
    files_checked: int = 0
    suppressed: int = 0
    errors: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.errors

    def to_json(self) -> str:
        return json.dumps(
            {
                "ok": self.ok,
                "files_checked": self.files_checked,
                "suppressed": self.suppressed,
                "errors": self.errors,
                "violations": [v.to_dict() for v in self.violations],
            },
            indent=2,
        )

    #: report label — the program (dynflow) pass overrides it
    tool: str = "dynlint"

    def render(self) -> str:
        lines = []
        for v in sorted(self.violations, key=lambda v: (v.path, v.line)):
            lines.append(f"{v.path}:{v.line}: [{v.rule}] {v.message}")
            for s in getattr(v, "evidence", ()) or ():
                note = f" ({s.note})" if getattr(s, "note", "") else ""
                lines.append(f"    evidence: {s.path}:{s.line}{note}")
        for e in self.errors:
            lines.append(f"error: {e}")
        n = len(self.violations)
        lines.append(
            f"{self.tool}: {self.files_checked} files, {n} violation"
            f"{'s' if n != 1 else ''}, {self.suppressed} suppressed"
        )
        return "\n".join(lines)


def _rel(path: str, root: Optional[str]) -> str:
    p = os.path.abspath(path)
    if root:
        try:
            p = os.path.relpath(p, root)
        except ValueError:  # different drive (windows) — keep absolute
            pass
    else:
        # anchor at the repo-shaped segment so path-scoped rules match
        # regardless of where the checkout lives
        for marker in ("dynamo_tpu", "tests"):
            idx = p.replace("\\", "/").find("/" + marker + "/")
            if idx >= 0:
                p = p[idx + 1:]
                break
    return p.replace("\\", "/")


def lint_source(
    relpath: str,
    source: str,
    rules: Sequence[Rule] = ALL_RULES,
) -> tuple[list[Violation], int]:
    """Lint one in-memory file. ``relpath`` drives rule scoping (use
    repo-shaped paths like ``dynamo_tpu/engine/engine.py``). Returns
    (violations, suppressed_count). Project rules are skipped — they
    need the whole file set (:func:`lint_paths`)."""
    out, suppressed, _sup = _lint_one(relpath, source, rules)
    return out, suppressed


def _lint_one(
    relpath: str, source: str, rules: Sequence[Rule]
) -> tuple[list[Violation], int, _Suppressions]:
    """Per-file pass, returning the parsed suppressions too so
    :func:`lint_paths` can reuse them for project-rule coverage without
    tokenizing every file a second time."""
    sup = _parse_suppressions(source)
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return (
            [Violation("syntax-error", relpath, e.lineno or 0, str(e))],
            0,
            sup,
        )
    out: list[Violation] = []
    suppressed = 0
    for rule in rules:
        if rule.project or not rule.applies_to(relpath):
            continue
        for v in rule.check(relpath, source, tree):
            if sup.covers(v.rule, v.line):
                suppressed += 1
            else:
                out.append(v)
    return out, suppressed, sup


def _iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames if d not in _SKIP_DIRS
                ]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)


def lint_paths(
    paths: Sequence[str],
    rules: Sequence[Rule] = ALL_RULES,
    root: Optional[str] = None,
) -> LintReport:
    """Lint files/directories. Project rules (cross-file invariants like
    faultpoint test coverage) run over the full collected file set."""
    report = LintReport()
    files: dict[str, str] = {}
    sups: dict[str, _Suppressions] = {}
    for path in _iter_py_files(paths):
        rel = _rel(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                files[rel] = f.read()
        except OSError as e:
            report.errors.append(f"{rel}: {e}")
    for rel, source in files.items():
        vs, sup_n, sup = _lint_one(rel, source, rules)
        report.violations.extend(vs)
        report.suppressed += sup_n
        sups[rel] = sup
        report.files_checked += 1
    for rule in rules:
        if not rule.project:
            continue
        for v in rule.check_project(files):
            sup = sups.get(v.path)
            if sup is not None and sup.covers(v.rule, v.line):
                report.suppressed += 1
            else:
                report.violations.append(v)
    return report


def _iter_dashboard_files(paths: Iterable[str]) -> Iterable[str]:
    """Grafana dashboard artifacts (the dashboard-metric-without-
    producer rule's query side). Only ``*dashboard*.json`` files are
    collected — bench artifacts and fixtures stay out of the model."""
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".json") and "dashboard" in os.path.basename(p):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames if d not in _SKIP_DIRS
                ]
                for f in sorted(filenames):
                    if f.endswith(".json") and "dashboard" in f:
                        yield os.path.join(dirpath, f)


def read_files(
    paths: Sequence[str], root: Optional[str] = None
) -> tuple[dict[str, str], list[str]]:
    """Collect ``{relpath: source}`` for the given files/directories
    (the same discovery as :func:`lint_paths`, plus Grafana dashboard
    JSON for the dashboard-producer contract)."""
    files: dict[str, str] = {}
    errors: list[str] = []
    for it in (_iter_py_files(paths), _iter_dashboard_files(paths)):
        for path in it:
            rel = _rel(path, root)
            try:
                with open(path, encoding="utf-8") as f:
                    files[rel] = f.read()
            except OSError as e:
                errors.append(f"{rel}: {e}")
    return files, errors


def check_program(
    paths: Sequence[str],
    rules=None,
    root: Optional[str] = None,
) -> LintReport:
    """The dynflow whole-program pass: build one project model over the
    file set and fire the cross-file contract rules
    (:mod:`.contracts`). Suppressions use the same grammar as dynlint,
    anchored at each finding's declaration end."""
    from .contracts import CONTRACT_RULES, check_contracts

    report = LintReport(tool="dynflow")
    files, errors = read_files(paths, root)
    report.errors.extend(errors)
    report.files_checked = len(files)
    sups = {rel: _parse_suppressions(src) for rel, src in files.items()}
    for v in check_contracts(files, rules or CONTRACT_RULES):
        sup = sups.get(v.path)
        if sup is not None and sup.covers(v.rule, v.line):
            report.suppressed += 1
        else:
            report.violations.append(v)
    return report


def changed_files(
    paths: Sequence[str], repo_root: Optional[str] = None
) -> Optional[list[str]]:
    """Files under ``paths`` that ``git diff --name-only HEAD`` (plus
    untracked) reports as touched — the ``--changed`` fast path for the
    pre-commit loop. Returns None when git is unavailable (callers fall
    back to the full walk)."""
    import subprocess

    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True,
            cwd=repo_root or os.getcwd(), timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if top.returncode != 0 or not top.stdout.strip():
        return None
    # resolve and run everything against the REPO ROOT: `git diff` emits
    # root-relative paths regardless of cwd (joining them onto a
    # subdirectory cwd silently dropped every touched file — a
    # false-clean fast path), and `git ls-files --others` is
    # cwd-relative, so both must share the root as their base
    cwd = top.stdout.strip()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, cwd=cwd, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, cwd=cwd, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    touched = {
        line.strip() for out in (diff.stdout, untracked.stdout)
        for line in out.splitlines() if line.strip().endswith(".py")
    }
    roots = []
    for p in paths:
        ap = os.path.normpath(os.path.abspath(p))
        if not os.path.exists(ap):
            # the default path set ("dynamo_tpu/ tests/") assumes the
            # repo root — re-anchor there when invoked from a subdir
            alt = os.path.normpath(os.path.join(cwd, p))
            if os.path.exists(alt):
                ap = alt
        roots.append(ap)
    out: list[str] = []
    for rel in sorted(touched):
        ap = os.path.normpath(os.path.join(cwd, rel))
        if not os.path.exists(ap):
            continue  # deleted file
        if any(ap == r or ap.startswith(r + os.sep) for r in roots):
            out.append(ap)
    return out

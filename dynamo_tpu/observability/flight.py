"""Flight recorder: bounded request-timeline ring + slow-request
autopsies.

Every finished request leaves one compact record (TTFT, status, SLO
class, and — when tracing is on — the assembled timeline with its
canonical decomposition, ``tracing/ttft.decompose``) in a bounded ring.
When a request breaches its SLO class's TTFT target, finishes in error,
or dies to a fault-point kill, the recorder persists an **autopsy**: the
timeline plus everything a human needs to name the cause without
reproducing it — the engine's stats snapshot at finish time, the
runtime-sanitizer counters (a loop stall shows up next to the request it
stalled), and the XLA compile-ledger tail (a 20-40s TTFT whose window
contains a compile entry IS the compile; docs/observability.md).

Autopsies are retrievable via ``GET /autopsy/{request_id}`` on the
frontend and optionally persisted as JSON files. Breaches count into
``Metrics.observe_breach`` -> ``slo_breaches_total{model,slo_class}``,
so the counter and the autopsy inventory can never drift apart.

The recorder is provider-wired, not import-coupled: stats / sanitizer /
compile-ledger callables are injected where the deployment shape has
them in-process (dynamo_run single-process serving) and simply absent
where it doesn't (a distributed frontend still records timelines and
breaches; its autopsies carry what the frontend can see).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger(__name__)

_SAFE_ID = re.compile(r"[^A-Za-z0-9._-]")

#: compile-ledger entries included in an autopsy (newest last)
LEDGER_TAIL = 8


def _autopsy_filename(request_id: str) -> str:
    """Filename-safe, collision-resistant name for a client-suppliable
    request id: unsafe characters flatten (no separators can traverse
    out of the dir) and a short hash of the RAW id disambiguates ids
    that flatten identically ('req:1' vs 'req_1')."""
    safe = _SAFE_ID.sub("_", request_id)[:96] or "autopsy"
    digest = hashlib.blake2s(request_id.encode(), digest_size=4).hexdigest()
    return f"{safe}-{digest}.json"


@dataclass
class SloPolicy:
    """Per-class TTFT targets in milliseconds. A request whose measured
    TTFT exceeds its class's target breaches. 0/absent = no target for
    that class (error finishes still autopsy)."""

    ttft_ms: dict[str, float] = field(default_factory=dict)
    default_ttft_ms: float = 0.0

    def target_for(self, slo_class: str) -> float:
        return self.ttft_ms.get(slo_class, self.default_ttft_ms)

    def breached(self, slo_class: str, ttft_ms: Optional[float]) -> bool:
        target = self.target_for(slo_class)
        return bool(target > 0 and ttft_ms is not None and ttft_ms > target)


class FlightRecorder:
    def __init__(
        self,
        policy: Optional[SloPolicy] = None,
        collector=None,
        autopsy_dir: Optional[str] = None,
        ring: int = 256,
        stats_provider: Optional[Callable[[], dict]] = None,
        sanitizer_provider: Optional[Callable[[], dict]] = None,
        ledger_provider: Optional[Callable[[], list]] = None,
        on_breach: Optional[Callable[[str, str], None]] = None,
    ):
        self.policy = policy or SloPolicy()
        #: tracing.TraceCollector (or anything with ``timeline``/``ttft``)
        self.collector = collector
        self.autopsy_dir = autopsy_dir
        self.stats_provider = stats_provider
        self.sanitizer_provider = sanitizer_provider
        self.ledger_provider = ledger_provider
        self.on_breach = on_breach
        self._ring: OrderedDict[str, dict] = OrderedDict()
        self._autopsies: OrderedDict[str, dict] = OrderedDict()
        self.max_ring = ring
        self.max_autopsies = max(ring // 4, 16)
        #: persisted-file retention (autopsy_dir): oldest files written
        #: by THIS recorder are unlinked past the cap, so an error-heavy
        #: workload bounds its disk footprint like it bounds its memory
        self.max_disk_autopsies = max(self.max_autopsies * 4, 64)
        self._disk_paths: deque[str] = deque()
        self.recorded_total = 0
        self.autopsies_total = 0
        # per-worker attribution (the router stamps routed_worker_id on
        # every request): cumulative finishes and unhealthy finishes
        # (breach OR error) per worker — the autopilot's quarantine
        # loop differences these to find the worker whose breach RATE
        # is spiking, instead of guessing from fleet-wide counters
        self._worker_records: dict[int, int] = {}
        self._worker_breaches: dict[int, int] = {}

    # ---------------- recording ----------------

    def finish(
        self,
        request_id: str,
        model: str,
        slo_class: str,
        status: str,
        ttft_ms: Optional[float],
        duration_ms: float,
        worker_id: Optional[int] = None,
    ) -> Optional[dict]:
        """Called once per finished request (the frontend's guard-done
        path). ``worker_id`` is the router's placement (the
        ``routed_worker_id`` annotation) when known — it attributes the
        finish to a worker for the quarantine loop and lands in the
        autopsy so a breach names its worker. Returns the autopsy dict
        when one was produced."""
        rec = {
            "request_id": request_id,
            "model": model,
            "slo_class": slo_class,
            "status": status,
            "ttft_ms": round(ttft_ms, 3) if ttft_ms is not None else None,
            "duration_ms": round(duration_ms, 3),
            "worker_id": worker_id,
            "ts": time.time(),
        }
        self.recorded_total += 1
        self._ring[request_id] = rec
        while len(self._ring) > self.max_ring:
            self._ring.popitem(last=False)

        breached = self.policy.breached(slo_class, ttft_ms)
        # fault-point kills surface as error finishes (FaultInjected
        # carries the worker-lost signature, so when migration is off —
        # or exhausted — the stream ends in status="error"); both paths
        # autopsy, tagged with their reason
        errored = status not in ("success", "disconnect", "shed")
        if worker_id is not None:
            self._worker_records[worker_id] = (
                self._worker_records.get(worker_id, 0) + 1
            )
            if breached or errored:
                self._worker_breaches[worker_id] = (
                    self._worker_breaches.get(worker_id, 0) + 1
                )
        if not breached and not errored:
            return None
        reason = "slo_breach" if breached else f"finish_{status}"
        autopsy = self._build_autopsy(rec, reason)
        self._autopsies[request_id] = autopsy
        while len(self._autopsies) > self.max_autopsies:
            self._autopsies.popitem(last=False)
        self.autopsies_total += 1
        if breached and self.on_breach is not None:
            try:
                self.on_breach(model, slo_class)
            except Exception:  # noqa: BLE001
                logger.debug("breach callback failed", exc_info=True)
        self._persist(request_id, autopsy)
        return autopsy

    def _build_autopsy(self, rec: dict, reason: str) -> dict:
        out = dict(rec)
        out["reason"] = reason
        out["slo_target_ms"] = self.policy.target_for(rec["slo_class"])
        if self.collector is not None:
            try:
                out["timeline"] = self.collector.timeline(rec["request_id"])
                out["ttft_decomposition"] = self.collector.ttft(
                    rec["request_id"]
                )
            except Exception:  # noqa: BLE001
                logger.debug("autopsy timeline failed", exc_info=True)
        for key, provider in (
            ("engine_stats", self.stats_provider),
            ("sanitizer", self.sanitizer_provider),
        ):
            if provider is None:
                continue
            try:
                out[key] = provider()
            except Exception:  # noqa: BLE001
                logger.debug("autopsy %s provider failed", key, exc_info=True)
        if self.ledger_provider is not None:
            try:
                out["compile_ledger_tail"] = list(
                    self.ledger_provider()
                )[-LEDGER_TAIL:]
            except Exception:  # noqa: BLE001
                logger.debug("autopsy ledger provider failed", exc_info=True)
        return out

    def _persist(self, request_id: str, autopsy: dict) -> None:
        if not self.autopsy_dir:
            return
        try:
            os.makedirs(self.autopsy_dir, exist_ok=True)
            path = os.path.join(
                self.autopsy_dir, _autopsy_filename(request_id)
            )
            with open(path, "w", encoding="utf-8") as f:
                json.dump(autopsy, f, indent=1, default=str)
        except OSError:
            logger.warning("autopsy persist failed", exc_info=True)
            return
        if path not in self._disk_paths:
            self._disk_paths.append(path)
        while len(self._disk_paths) > self.max_disk_autopsies:
            old = self._disk_paths.popleft()
            try:
                os.unlink(old)
            except OSError:
                logger.debug("stale autopsy unlink failed", exc_info=True)

    # ---------------- retrieval ----------------

    def autopsy(self, request_id: str) -> Optional[dict]:
        a = self._autopsies.get(request_id)
        if a is not None:
            return a
        if self.autopsy_dir:
            path = os.path.join(
                self.autopsy_dir, _autopsy_filename(request_id)
            )
            try:
                with open(path, encoding="utf-8") as f:
                    return json.load(f)
            except (OSError, ValueError):
                return None
        return None

    def record(self, request_id: str) -> Optional[dict]:
        return self._ring.get(request_id)

    def autopsy_ids(self) -> list[str]:
        return list(self._autopsies)

    def worker_counters(self) -> dict[int, tuple[int, int]]:
        """``worker_id -> (unhealthy_total, records_total)``, cumulative
        — the quarantine loop's per-tick evidence (it differences
        successive reads, so this stays allocation-cheap)."""
        return {
            wid: (self._worker_breaches.get(wid, 0), n)
            for wid, n in self._worker_records.items()
        }

    def counters(self) -> dict:
        """Plain-gauge scrape source (Metrics.register_source)."""
        return {
            "flight_records_total": self.recorded_total,
            "flight_autopsies_total": self.autopsies_total,
        }

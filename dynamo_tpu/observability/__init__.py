"""Observability plane (ref components/metrics, §2.3 + SURVEY §5).

Three tiers, like the reference:
 1. per-process Prometheus counters in the HTTP frontend
    (dynamo_tpu/http/metrics.py),
 2. per-endpoint stats handlers scraped over the bus
    (runtime/component.py stats subjects + kv_router KvMetricsAggregator),
 3. THIS package — the fleet-level aggregation component: scrapes every
    worker of an endpoint, subscribes the kv-hit-rate event plane, and
    serves Prometheus gauges (kv_blocks_active/total,
    requests_active/total, …) for ops dashboards
    (ref components/metrics/src/{main,lib}.rs:255,145-364).
"""

from .component import MetricsComponent, MockWorker

__all__ = ["MetricsComponent", "MockWorker"]

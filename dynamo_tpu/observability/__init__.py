"""Observability plane (ref components/metrics, §2.3 + SURVEY §5).

Four tiers (docs/observability.md):
 1. per-process Prometheus counters + latency histograms in the HTTP
    frontend (dynamo_tpu/http/metrics.py — the ``*_seconds_bucket``
    families the shipped Grafana dashboard queries),
 2. per-endpoint stats handlers scraped over the bus, including
    serialized worker-side histograms and TPU device telemetry
    (runtime/component.py stats subjects + kv_router KvMetricsAggregator),
 3. the fleet-level aggregation component: scrapes every worker of an
    endpoint, subscribes the kv-hit-rate event plane, and serves
    Prometheus gauges + per-worker histogram families
    (ref components/metrics/src/{main,lib}.rs:255,145-364),
 4. the flight recorder (flight.py): bounded request-timeline ring with
    slow-request autopsies on SLO breach / error / fault-point kill.

``hist.py`` is the shared fixed-bucket histogram every tier speaks.
"""

from .component import MetricsComponent, MockWorker
from .flight import FlightRecorder, SloPolicy
from .hist import Histogram, HistogramVec, WindowedHistogram

__all__ = [
    "FlightRecorder",
    "Histogram",
    "HistogramVec",
    "MetricsComponent",
    "MockWorker",
    "SloPolicy",
    "WindowedHistogram",
]

"""Fleet metrics aggregation component (ref components/metrics/src/lib.rs).

Scrapes the target endpoint's per-worker stats on an interval (via
KvMetricsAggregator), folds them into fleet gauges, subscribes
``kv-hit-rate`` events from routers, and serves a Prometheus text
endpoint:

    dynamo_tpu_kv_blocks_active{worker="..."} / kv_blocks_total
    dynamo_tpu_requests_active{worker="..."} / requests_total_slots
    dynamo_tpu_requests_waiting{worker="..."}
    dynamo_tpu_kv_hit_rate (running ratio of overlap to prompt blocks)
    dynamo_tpu_load_avg / dynamo_tpu_load_std (the scheduler's view)

Run standalone: ``python -m dynamo_tpu.observability --hub H ns.comp.ep``.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..kv_router.protocols import KV_HIT_RATE_SUBJECT, KVHitRateEvent
from ..kv_router.publisher import KvMetricsAggregator
from ..planner.protocols import (
    PLANNER_DECISION_SUBJECT,
    PLANNER_WATERMARK_SUBJECT,
    CapacityWatermark,
    PlannerDecision,
)

logger = logging.getLogger(__name__)

#: per-worker + fleet-merged histogram families rendered from workers'
#: serialized ``hists`` vectors (engine load_metrics -> WorkerLoad).
#: This constant IS the advertised render surface: render() only emits
#: families listed here, and the dynflow dashboard-metric-without-
#: producer rule reads it — a new worker distribution must be declared
#: here before a dashboard panel may query it.
WORKER_HIST_FAMILIES = (
    "worker_queue_wait_ms", "worker_prefill_ms",
    "worker_restore_ms", "worker_handoff_ms",
    "fleet_queue_wait_ms", "fleet_prefill_ms",
    "fleet_restore_ms", "fleet_handoff_ms",
    # per-model TTFT (multi-model serving): model-labelled families fed
    # from WorkerLoad.model_hists ("" = the base model) — trace replay's
    # per-model p99 assertions read the fleet merge of these
    "worker_ttft_ms", "fleet_ttft_ms",
)


class MetricsComponent:
    def __init__(
        self,
        drt,
        component,
        host: str = "0.0.0.0",
        port: int = 18090,
        interval: float = 1.0,
        prefix: str = "dynamo_tpu",
        tracing_collector=None,
        enable_tracing: bool = False,
    ):
        self.drt = drt
        self.component = component
        self.host = host
        self.port = port
        self.prefix = prefix
        self.aggregator = KvMetricsAggregator(drt, component, interval=interval)
        self.hit_events = 0
        self.hit_isl_blocks = 0
        self.hit_overlap_blocks = 0
        # transfer-cost routing plane: EWMA + last of the scheduler's
        # predicted TTFT for cost-aware decisions (rides the hit-rate
        # event; -1 entries are overlap-mode fallbacks and don't count)
        self.route_cost_events = 0
        self.route_predicted_ttft_ms = 0.0
        # planner plane: last decision + watermark seen on the bus
        self.planner_decision: Optional[PlannerDecision] = None
        self.planner_watermark: Optional[CapacityWatermark] = None
        self.planner_decisions_total = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._hit_task = None
        self._planner_task = None
        # per-request trace collector (tracing.TraceCollector): assembles
        # trace-events spans into timelines, feeds the TTFT-decomposition
        # percentile gauges and the /trace/{request_id} endpoint
        self.tracing = tracing_collector
        if self.tracing is None and enable_tracing:
            from ..tracing import TraceCollector

            # unpinned: subscribe the *.*.trace-events wildcard so
            # frontend anchors and disagg prefill-worker spans land in
            # the same timelines as the scraped component's workers
            self.tracing = TraceCollector(drt)

    async def start(self) -> "MetricsComponent":
        await self.aggregator.start()
        sub = self.drt.bus.subscribe(
            self.component.event_subject(KV_HIT_RATE_SUBJECT)
        )
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._hit_task = self.drt.runtime.spawn(self._consume_hits(sub))
        psub = self.drt.bus.subscribe(
            self.component.event_subject(PLANNER_DECISION_SUBJECT)
        )
        wsub = self.drt.bus.subscribe(
            self.component.event_subject(PLANNER_WATERMARK_SUBJECT)
        )
        for s in (psub, wsub):
            ready = getattr(s, "ready", None)
            if ready is not None:
                await ready
        self._planner_task = self.drt.runtime.spawn(
            self._consume_decisions(psub)
        )
        self._watermark_task = self.drt.runtime.spawn(
            self._consume_watermarks(wsub)
        )
        if self.tracing is not None and self.tracing.drt is not None:
            await self.tracing.start()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def close(self) -> None:
        if self._hit_task is not None:
            self._hit_task.cancel()
        for t in (self._planner_task, getattr(self, "_watermark_task", None)):
            if t is not None:
                t.cancel()
        if self.tracing is not None:
            await self.tracing.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _consume_hits(self, sub) -> None:
        async for msg in sub:
            try:
                ev = KVHitRateEvent.from_bytes(msg.payload)
                self.hit_events += 1
                self.hit_isl_blocks += ev.isl_blocks
                self.hit_overlap_blocks += ev.overlap_blocks
                if ev.predicted_ttft_ms >= 0:
                    self.route_cost_events += 1
                    a = 0.2 if self.route_cost_events > 1 else 1.0
                    self.route_predicted_ttft_ms = (
                        (1 - a) * self.route_predicted_ttft_ms
                        + a * ev.predicted_ttft_ms
                    )
            except Exception:  # noqa: BLE001
                logger.exception("bad kv-hit-rate event")

    async def _consume_decisions(self, sub) -> None:
        async for msg in sub:
            try:
                prev = self.planner_decision
                self.planner_decision = d = PlannerDecision.from_bytes(
                    msg.payload
                )
                self.planner_decisions_total += 1
                if prev is None or (
                    (prev.decode_replicas, prev.prefill_replicas)
                    != (d.decode_replicas, d.prefill_replicas)
                ):
                    # the decision's own audit trail: WHY the counts
                    # moved, next to the counts
                    logger.info(
                        "planner decision: decode=%d prefill=%d (%s)",
                        d.decode_replicas, d.prefill_replicas, d.reason,
                    )
            except Exception:  # noqa: BLE001
                logger.exception("bad planner decision event")

    async def _consume_watermarks(self, sub) -> None:
        async for msg in sub:
            try:
                self.planner_watermark = CapacityWatermark.from_bytes(
                    msg.payload
                )
            except Exception:  # noqa: BLE001
                logger.exception("bad planner watermark event")

    # ---------------- rendering ----------------

    def render(self) -> str:
        from .hist import Histogram

        p = self.prefix
        lines: list[str] = []

        def gauge(name: str, value, labels: str = "") -> None:
            lines.append(f"{p}_{name}{{{labels}}} {value}"
                         if labels else f"{p}_{name} {value}")

        def hist_rows(name: str, h, labels: str = "") -> None:
            """One histogram family instance (cumulative le buckets +
            _sum/_count) — the worker-side distributions' render."""
            lines.extend(h.render(f"{p}_{name}", labels))

        ep = self.aggregator.endpoints
        # fleet rollups of the worker-side latency distributions:
        # merged bucket vectors (exact — histogram merge is vector
        # addition), one family per component, plus per-worker rows
        fleet: dict[str, Histogram] = {}
        # per-model TTFT rollup (model name -> merged histogram) — the
        # model dimension stays a LABEL, not a family, so dashboards
        # query one family across any adapter census
        fleet_ttft: dict[str, Histogram] = {}
        for w in ep.loads:
            lb = f'worker="{w.worker_id:x}"'
            gauge("kv_blocks_active", w.kv_active_blocks, lb)
            gauge("kv_blocks_total", w.kv_total_blocks, lb)
            gauge("requests_active", w.active_requests, lb)
            gauge("requests_total_slots", w.total_slots, lb)
            gauge("requests_waiting", w.waiting, lb)
            # async offload tier (engine OffloadManager.stats): host-tier
            # residency, background d2h flushes, hinted prefetch claims,
            # and the fraction of restore latency hidden from TTFT
            gauge("offload_blocks_resident", w.offload_blocks_resident, lb)
            gauge("offload_d2h_flush_async", w.offload_d2h_flush_async, lb)
            gauge("offload_prefetch_hits", w.offload_prefetch_hits, lb)
            gauge(
                "offload_restore_hidden_frac",
                round(w.offload_restore_hidden_frac, 6), lb,
            )
            # third KV tier + fleet prefix cache (docs/kv_offload.md):
            # disk-tier residency and hits, the volume of blocks pulled
            # from peers' tiers, and the fraction of pulled blocks whose
            # cross-worker transfer stayed fully hidden from requests
            gauge("disk_blocks_resident", w.disk_blocks_resident, lb)
            gauge("disk_hit_blocks_total", w.disk_hit_blocks, lb)
            gauge("peer_pull_blocks_total", w.peer_pull_blocks, lb)
            gauge(
                "peer_pull_hidden_frac",
                round(w.peer_pull_hidden_frac, 6), lb,
            )
            # disk-tier health + host/disk fleet serves (the PR 9 keys
            # the dynflow unscraped-stat rule found dropped between
            # OffloadManager.stats and this render)
            gauge("disk_corrupt_discards_total", w.disk_corrupt_discards, lb)
            gauge("disk_demotions_total", w.disk_demotions, lb)
            gauge("peer_serve_blocks_total", w.peer_serve_blocks, lb)
            # per-block KV quantization (docs/kv_offload.md quantized
            # tier): blocks encoded to the int8/fp8 tier/wire codec,
            # the bytes that saved vs full width, and the worst logprob
            # drift the quality harness has recorded on this worker
            gauge("kv_quant_blocks_total", w.kv_quant_blocks, lb)
            gauge("kv_quant_bytes_saved_total", w.kv_quant_bytes_saved, lb)
            # bytes one block moves on this worker's tier/wire planes
            # (the quantized advertisement predict/choose_peer price
            # restore and pull legs with; == full width when codec off)
            gauge("kv_wire_block_bytes", w.wire_block_bytes, lb)
            gauge(
                "kv_quant_logprob_drift_max",
                round(w.kv_quant_logprob_drift_max, 6), lb,
            )
            # int8-with-scales DEVICE cache lane (docs/kv_offload.md
            # device tier): resident quantized pages, append-driven page
            # requantizations, HBM bytes saved vs full width, exports
            # forced off the device codec (0 with a matching int8 tier),
            # and the lane's measured decode throughput
            gauge("kv_device_quant_pages", w.kv_device_quant_pages, lb)
            gauge("kv_device_requants_total", w.kv_device_requants, lb)
            gauge(
                "kv_device_bytes_saved_total", w.kv_device_bytes_saved, lb
            )
            gauge(
                "kv_device_export_requant_total",
                w.kv_device_export_requants, lb,
            )
            gauge("lowprec_tok_s", round(w.lowprec_tok_s, 3), lb)
            # resilience plane: draining state + handoff/resume volume
            # (resilience subsystem; docs/resilience.md)
            gauge("draining", w.draining, lb)
            gauge("drains_total", w.drains_total, lb)
            gauge("drain_handoffs_total", w.drain_handoffs, lb)
            gauge("migration_resumes_total", w.migration_resumes, lb)
            # elastic live resharding: morph window flag + volume
            gauge("resharding", w.resharding, lb)
            gauge("resharded_total", w.resharded_total, lb)
            gauge("reshard_hold_ms", round(w.reshard_hold_ms, 3), lb)
            gauge(
                "reshard_kv_moved_blocks", w.reshard_kv_moved_blocks, lb
            )
            # disagg KV handoff: streamed (transfer hidden behind
            # prefill compute) vs legacy bulk deliveries, and how many
            # segments landed through the incremental scatter
            gauge("kv_stream_deliveries_total", w.kv_stream_deliveries, lb)
            gauge("kv_bulk_deliveries_total", w.kv_bulk_deliveries, lb)
            gauge("kv_stream_segments_total", w.kv_stream_segments, lb)
            # mixed-batch packing: fused steps + prefill segments packed
            # into them (segments/steps ~1 under a deep queue = HOL
            # blocking the multi-prompt packer should be absorbing)
            gauge("mixed_steps_total", w.mixed_steps, lb)
            gauge("mixed_prefill_segments_total", w.mixed_prefill_segments, lb)
            # cumulative serving counters (planner telemetry inputs)
            gauge("requests_served_total", w.requests_total, lb)
            gauge("tokens_generated_total", w.tokens_generated, lb)
            gauge("prompt_tokens_total", w.prompt_tokens_total, lb)
            # runtime-sanitizer plane (docs/static_analysis.md): loop
            # stalls + worst lock hold on the worker — a production
            # stall shows up here, not just in a failing test
            gauge("loop_stalls_total", w.loop_stalls, lb)
            gauge("loop_stall_max_ms", round(w.loop_stall_max_ms, 3), lb)
            gauge("lock_holds_total", w.lock_holds, lb)
            gauge("lock_hold_max_ms", round(w.lock_hold_max_ms, 3), lb)
            gauge("writers_leaked_total", w.writers_leaked, lb)
            # executor pressure (sanitizer.register_executor): deepest
            # pending backlog across the worker's registered executors —
            # a wedged offload/device executor surfaces here first
            gauge("executor_pending_max", w.executor_pending_max, lb)
            # transfer-cost calibration plane (docs/kv_cache_routing.md):
            # how many observations this worker's cost model has folded,
            # its per-link-class observed bandwidths, the ICI fast-path
            # volume, device-tier peer exports, and weight pre-stages
            gauge("kv_cost_obs_total", w.cost_obs, lb)
            for link, gbps in sorted((w.link_gbps or {}).items()):
                gauge(
                    "kv_link_gbps", round(gbps, 6),
                    lb + f',link="{link}"',
                )
            gauge("ici_handoffs_total", w.ici_handoffs, lb)
            gauge("peer_serve_d2h_blocks_total", w.peer_serve_d2h_blocks, lb)
            gauge(
                "weight_prestage_requests_total",
                w.weight_prestage_requests, lb,
            )
            # multi-model lane (docs/multi_model.md): adapter-weight
            # bytes staged ahead of traffic via prefetch hints, the
            # requests that found their adapter already resident, and
            # one serves_model row per advertised NAMED model — the
            # same advertisement select_worker filters on. A worker
            # advertising only "" (single-model fleet, the legacy
            # wildcard) renders no per-model rows at all: upgrading a
            # fleet that never configured --adapters must not change
            # its metric families
            gauge("weight_prestage_bytes_total", w.prestage_bytes, lb)
            gauge("weight_prestage_hits_total", w.prestage_hits, lb)
            multi_model = any(m for m in w.models)
            for m in w.models:
                if m:
                    gauge("serves_model", 1, lb + f',model="{m}"')
            # SLO observatory (docs/observability.md): XLA compile
            # ledger + warmup coverage and HBM telemetry per worker
            gauge("xla_compiles_total", w.xla_compiles, lb)
            gauge("xla_compile_ms_total", round(w.xla_compile_ms, 3), lb)
            gauge("xla_warm_buckets", w.xla_warm_buckets, lb)
            gauge("xla_reachable_buckets", w.xla_reachable_buckets, lb)
            gauge("hbm_bytes_in_use", w.hbm_bytes_in_use, lb)
            gauge("hbm_bytes_limit", w.hbm_bytes_limit, lb)
            gauge("hbm_kv_pool_bytes", w.hbm_kv_pool_bytes, lb)
            gauge("hbm_weights_bytes", w.hbm_weights_bytes, lb)
            # autopilot plane (docs/autopilot.md): pre-warm runs the
            # worker's actuator applied (and their wall cost), plus the
            # worker's current quarantine flag and lifetime trips — the
            # operator's view of WHICH worker the autopilot touched
            gauge("autopilot_warmups_applied", w.autopilot_warmups, lb)
            gauge(
                "autopilot_warmup_ms_total",
                round(w.autopilot_warmup_ms, 3), lb,
            )
            gauge("autopilot_quarantined", w.autopilot_quarantined, lb)
            gauge("autopilot_quarantines_total", w.autopilot_quarantines, lb)
            # worker latency distributions: per-worker histogram rows
            # and the exact fleet merge (vector addition; a vector whose
            # bucket bounds don't match the rollup's is rendered
            # per-worker but skipped from the merge rather than
            # corrupting it — schema-skewed peers degrade readable)
            for hname, vec in sorted((w.hists or {}).items()):
                if f"worker_{hname}" not in WORKER_HIST_FAMILIES:
                    continue  # undeclared family: see WORKER_HIST_FAMILIES
                h = Histogram.from_vec(vec)
                if h is None:
                    continue
                hist_rows(f"worker_{hname}", h, lb)
                fl = fleet.get(hname)
                if fl is None:
                    fleet[hname] = h
                elif fl.bounds == h.bounds:
                    fl.merge(h)
            # per-model TTFT distributions (engine hist_ttft_ms, keyed
            # by model name; "" = base): per-worker rows + exact fleet
            # merge per model, same schema-skew tolerance as above —
            # rendered only for multi-model workers (see serves_model)
            for m, vec in sorted(
                (w.model_hists or {}).items() if multi_model else ()
            ):
                h = Histogram.from_vec(vec)
                if h is None:
                    continue
                hist_rows("worker_ttft_ms", h, lb + f',model="{m}"')
                fl = fleet_ttft.get(m)
                if fl is None:
                    fleet_ttft[m] = h
                elif fl.bounds == h.bounds:
                    fl.merge(h)
        for hname, h in sorted(fleet.items()):
            hist_rows(f"fleet_{hname}", h)
        for m, h in sorted(fleet_ttft.items()):
            hist_rows("fleet_ttft_ms", h, f'model="{m}"')
        gauge("worker_count", len(ep.loads))
        gauge("load_avg", round(ep.load_avg, 6))
        gauge("load_std", round(ep.load_std, 6))
        if self.hit_isl_blocks:
            gauge(
                "kv_hit_rate",
                round(self.hit_overlap_blocks / self.hit_isl_blocks, 6),
            )
        gauge("kv_hit_events_total", self.hit_events)
        # cost-aware routing: the scheduler's predicted TTFT for its
        # chosen workers (EWMA over cost-mode decisions; absent until
        # the first calibrated decision lands). getattr: render-only
        # harnesses construct this component via __new__
        if getattr(self, "route_cost_events", 0):
            gauge("route_cost_decisions_total", self.route_cost_events)
            gauge(
                "route_predicted_ttft_ms",
                round(self.route_predicted_ttft_ms, 3),
            )
        # SLA planner plane (docs/planner.md): the last decision +
        # capacity watermark this component saw on the bus
        gauge("planner_decisions_total", self.planner_decisions_total)
        d = self.planner_decision
        if d is not None:
            gauge("planner_decode_replicas", d.decode_replicas)
            gauge("planner_prefill_replicas", d.prefill_replicas)
            gauge("planner_disagg_ratio", round(d.disagg_ratio, 6))
            gauge("planner_request_rate", round(d.request_rate, 6))
            gauge("planner_gen_token_rate", round(d.gen_token_rate, 6))
            # the SLO view that justified the counts (these rode the
            # wire unread until the dynflow dead-wire-field rule):
            # operators correlate a scale-up with the breach it answered
            gauge("planner_prompt_token_rate", round(d.prompt_token_rate, 6))
            gauge("planner_ttft_p99_ms", round(d.ttft_p99_ms, 3))
            gauge("planner_itl_p99_ms", round(d.itl_p99_ms, 3))
        w = self.planner_watermark
        if w is not None:
            gauge("planner_saturated_workers", len(w.saturated_workers))
            gauge("planner_cluster_utilization",
                  round(w.cluster_utilization, 6))
            gauge("planner_admission_rate_req_s",
                  round(w.admission_rate_req_s, 6))
        if self.tracing is not None:
            # per-request TTFT decomposition percentiles (tracing plane):
            # where TTFT actually went, fleet-wide — queue wait vs KV
            # transfer vs prefill compute, not just the total
            gauge("traces_spans_total", self.tracing.spans_total)
            for comp, qs in sorted(self.tracing.percentiles().items()):
                for q, v in sorted(qs.items()):
                    gauge(
                        "ttft_component_ms",
                        v, f'component="{comp}",quantile="{q}"',
                    )
        return "\n".join(lines) + "\n"

    async def _handle(self, reader, writer) -> None:
        try:
            # minimal HTTP: read request line + headers, serve GET /metrics
            line = await reader.readline()
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            path = line.split()[1].decode() if len(line.split()) > 1 else "/"
            path, _, query = path.partition("?")
            if path in ("/metrics", "/"):
                body = self.render().encode()
                status = b"200 OK"
                ctype = b"text/plain; version=0.0.4"
            elif path == "/health":
                body = b'{"status":"ok"}'
                status = b"200 OK"
                ctype = b"application/json"
            elif path.startswith("/trace/") and self.tracing is not None:
                import json as _json

                fmt = "chrome" if "format=chrome" in query else "timeline"
                obj = self.tracing.render_trace(path[len("/trace/"):], fmt=fmt)
                if obj is None:
                    body = b'{"error":"trace not found"}'
                    status = b"404 Not Found"
                else:
                    body = _json.dumps(obj).encode()
                    status = b"200 OK"
                ctype = b"application/json"
            else:
                body = b"not found"
                status = b"404 Not Found"
                ctype = b"text/plain"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\nContent-Type: " + ctype
                + b"\r\nContent-Length: " + str(len(body)).encode()
                + b"\r\nConnection: close\r\n\r\n" + body
            )
            await writer.drain()
        except Exception:  # noqa: BLE001
            logger.exception("metrics request failed")
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass  # scraper already gone — the fd is released either way


class MockWorker:
    """Registers a stats handler publishing synthetic load metrics —
    exercises the scrape/aggregate/Prometheus path with no real engine
    (ref components/metrics/src/bin/mock_worker.rs:36)."""

    def __init__(self, drt, namespace: str, component: str, endpoint: str, seed: int = 0):
        import random

        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self._rng = random.Random(seed)
        self._handle = None

    def _stats(self) -> dict:
        r = self._rng
        total = 128
        active = r.randrange(0, total)
        return {
            "kv_active_blocks": active,
            "kv_total_blocks": total,
            "gpu_cache_usage_perc": active / total,
            "request_active_slots": r.randrange(0, 8),
            "request_total_slots": 8,
            "num_requests_waiting": r.randrange(0, 4),
        }

    async def start(self) -> "MockWorker":
        from ..runtime.engine import AsyncEngine

        class _Noop(AsyncEngine):
            async def generate(self, request):
                yield {"mock": True}

        comp = self.drt.namespace(self.namespace).component(self.component)
        self._handle = await comp.endpoint(self.endpoint).serve(
            _Noop(), stats_handler=self._stats
        )
        return self

    async def close(self) -> None:
        if self._handle is not None:
            await self._handle.stop()

"""Standalone metrics component (ref components/metrics/src/main.rs):

    python -m dynamo_tpu.observability dynamo.backend.generate \
        --hub 127.0.0.1:18500 --port 18090
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os


async def main_async(args) -> None:
    from ..runtime.hub import connect_hub
    from ..runtime.runtime import DistributedRuntime
    from .component import MetricsComponent

    ns, comp, _ep = args.target.split(".")
    if args.hub:
        store, bus, _conn = await connect_hub(args.hub)
        drt = await DistributedRuntime.from_settings(store=store, bus=bus)
    else:
        drt = await DistributedRuntime.from_settings()
    component = drt.namespace(ns).component(comp)
    mc = await MetricsComponent(
        drt, component, host=args.host, port=args.port, interval=args.interval,
        enable_tracing=args.trace,
    ).start()
    print(f"metrics for {args.target} on http://{args.host}:{mc.port}/metrics"
          + (f" (+ /trace/{{request_id}})" if args.trace else ""),
          flush=True)
    await asyncio.Event().wait()


def main() -> None:
    p = argparse.ArgumentParser("dynamo-metrics")
    p.add_argument("target", help="ns.component.endpoint to scrape")
    p.add_argument("--hub", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=18090)
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--trace", action="store_true",
                   default=os.environ.get("DYN_TRACE", "") not in ("", "0"),
                   help="collect trace-events spans: TTFT-decomposition "
                        "gauges + /trace/{request_id} timelines")
    from ..utils.logging import setup_logging
    setup_logging()
    try:
        asyncio.run(main_async(p.parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()

"""Fixed-bucket Prometheus histograms — the SLO observatory's data type.

One small, dependency-free histogram shared by every layer of the
metrics plane (docs/observability.md):

  * the HTTP frontend's ``*_seconds`` families (the exact
    ``_bucket``/``_sum``/``_count`` series the shipped Grafana dashboard
    queries),
  * worker-side queue-wait / prefill / restore / handoff distributions,
    serialized as bucket vectors through ``load_metrics`` ->
    ``WorkerLoad.hists`` -> the metrics component's render,
  * the planner's TTFT/ITL p99s (``WindowedHistogram`` — merged bucket
    counts instead of bounded sample deques, so arbitrary sample rates
    keep bounded memory and merge across workers losslessly).

Buckets are log-spaced (latencies span 4+ decades: a 2ms cached ITL and
a 40s compile-stalled TTFT must both land in a resolvable bucket), with
an implicit ``+Inf`` overflow. Merging requires identical bounds and is
exact — histogram merge is just vector addition, which is what makes
the worker -> aggregator -> fleet rollup associative and lossless,
unlike percentile-of-percentiles.

Quantiles interpolate linearly inside the covering bucket and clamp to
the observed [min, max], so single-sample and single-bucket
distributions report exact values rather than bucket-edge artifacts.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Optional


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> tuple[float, ...]:
    """Log-spaced bucket upper bounds from ``lo`` to >= ``hi``
    (``per_decade`` bounds per factor of 10), deduplicated ascending."""
    out: list[float] = []
    b = lo
    ratio = 10.0 ** (1.0 / per_decade)
    while b < hi * (1 + 1e-9):
        r = float(f"{b:.6g}")
        if not out or r > out[-1]:
            out.append(r)
        b *= ratio
    return tuple(out)


#: HTTP-facing latencies in seconds: 1ms .. ~100s (XLA compile stalls
#: sit at 20-40s — the top decade must stay resolvable, not one +Inf)
TIME_BUCKETS_S = log_buckets(0.001, 100.0, per_decade=4)

#: worker-internal distributions in milliseconds: 0.05ms .. ~60s
MS_BUCKETS = log_buckets(0.05, 60_000.0, per_decade=4)


class Histogram:
    """Counts per fixed bucket + an implicit ``+Inf`` overflow slot."""

    __slots__ = ("bounds", "counts", "sum", "count", "min", "max")

    def __init__(self, bounds: Iterable[float] = TIME_BUCKETS_S):
        self.bounds = tuple(bounds)
        assert self.bounds == tuple(sorted(self.bounds)), "bounds must ascend"
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        lo, hi = 0, len(self.bounds)
        while lo < hi:  # first bound >= v (bisect; bounds are sorted)
            mid = (lo + hi) // 2
            if self.bounds[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        self.counts[lo] += 1

    # ---- merge / serialize ----

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` in (exact vector addition). Bounds must match —
        a schema-skewed peer's vector cannot be merged losslessly, so the
        caller skips it instead of corrupting the rollup."""
        if other.bounds != self.bounds:
            raise ValueError("histogram bucket bounds differ")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def to_vec(self) -> dict:
        """Wire form for ``load_metrics`` (JSON-safe, bounds included so
        merge stays checkable across worker versions)."""
        return {
            "b": list(self.bounds),
            "c": list(self.counts),
            "s": round(self.sum, 6),
            "n": self.count,
            "lo": (round(self.min, 6) if self.count else 0.0),
            "hi": round(self.max, 6),
        }

    @staticmethod
    def from_vec(v: dict) -> Optional["Histogram"]:
        """Tolerant decode (None on malformed input — a skewed peer's
        vector degrades to 'no histogram', never an exception on the
        scrape path)."""
        try:
            h = Histogram(tuple(float(b) for b in v["b"]))
            counts = [int(c) for c in v["c"]]
            if len(counts) != len(h.counts) or any(c < 0 for c in counts):
                return None
            h.counts = counts
            h.sum = float(v.get("s", 0.0))
            h.count = int(v.get("n", sum(counts)))
            h.min = float(v.get("lo", 0.0)) if h.count else float("inf")
            h.max = float(v.get("hi", 0.0))
            return h
        except (KeyError, TypeError, ValueError, AssertionError):
            return None

    # ---- quantiles ----

    def quantile(self, q: float) -> Optional[float]:
        """Linear interpolation inside the covering bucket, clamped to
        the observed [min, max] (exact for single-sample / single-value
        distributions). None when empty."""
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0.0
        lo = 0.0
        for i, c in enumerate(self.counts):
            if c == 0:
                lo = self.bounds[i] if i < len(self.bounds) else lo
                continue
            if cum + c >= rank:
                hi = self.bounds[i] if i < len(self.bounds) else self.max
                frac = (rank - cum) / c
                val = lo + (hi - lo) * frac
                return min(max(val, self.min), self.max)
            cum += c
            lo = self.bounds[i] if i < len(self.bounds) else lo
        return self.max

    # ---- rendering ----

    def render(self, name: str, labels: str = "") -> list[str]:
        """Prometheus exposition lines (cumulative ``le`` buckets +
        ``_sum``/``_count``). ``labels`` is the pre-rendered inner label
        string (``'model="m"'``), extended with ``le``."""
        sep = "," if labels else ""
        out = []
        cum = 0
        for i, b in enumerate(self.bounds):
            cum += self.counts[i]
            out.append(f'{name}_bucket{{{labels}{sep}le="{_fmt(b)}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {cum}')
        out.append(f"{name}_sum{{{labels}}} {round(self.sum, 6)}"
                   if labels else f"{name}_sum {round(self.sum, 6)}")
        out.append(f"{name}_count{{{labels}}} {self.count}"
                   if labels else f"{name}_count {self.count}")
        return out


def _fmt(b: float) -> str:
    """Stable ``le`` label text (no float repr noise)."""
    s = f"{b:.6g}"
    return s


class HistogramVec:
    """A labeled family of histograms sharing one bucket ladder."""

    def __init__(self, name: str, label_names: tuple[str, ...],
                 bounds: Iterable[float] = TIME_BUCKETS_S):
        self.name = name
        self.label_names = tuple(label_names)
        self.bounds = tuple(bounds)
        self._children: dict[tuple, Histogram] = {}

    def labels(self, *values: str) -> Histogram:
        key = tuple(str(v) for v in values)
        assert len(key) == len(self.label_names)
        h = self._children.get(key)
        if h is None:
            h = self._children[key] = Histogram(self.bounds)
        return h

    def items(self):
        return sorted(self._children.items())

    def render(self, prefix: str) -> list[str]:
        full = f"{prefix}_{self.name}"
        out = [f"# TYPE {full} histogram"]
        for key, h in self.items():
            labels = ",".join(
                f'{n}="{v}"' for n, v in zip(self.label_names, key)
            )
            out.extend(h.render(full, labels))
        return out


class WindowedHistogram:
    """Sliding-window histogram as two rotating halves: samples land in
    the current half; a half older than ``window_s / 2`` rotates out, so
    ``snapshot()`` always covers between half and one full window with
    bounded memory at any sample rate (the deque this replaces dropped
    samples past ``maxlen`` — under load, exactly when the tail matters).
    Clock-injected so scripted planner traces replay deterministically.
    """

    def __init__(self, window_s: float,
                 bounds: Iterable[float] = MS_BUCKETS,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = window_s
        self.bounds = tuple(bounds)
        self._clock = clock
        self._cur = Histogram(self.bounds)
        self._prev = Histogram(self.bounds)
        self._cur_start = clock()

    def _rotate(self, now: float) -> None:
        half = self.window_s / 2.0
        while now - self._cur_start >= half:
            self._prev = self._cur
            self._cur = Histogram(self.bounds)
            self._cur_start += half
            if now - self._cur_start >= self.window_s:
                # idle gap longer than the whole window: both halves are
                # stale — jump the window forward instead of looping
                self._prev = Histogram(self.bounds)
                self._cur_start = now

    def observe(self, v: float) -> None:
        self._rotate(self._clock())
        self._cur.observe(v)

    def snapshot(self) -> Histogram:
        """Merged view of the live window (fresh object, safe to merge
        further — e.g. with peer workers' vectors)."""
        self._rotate(self._clock())
        out = Histogram(self.bounds)
        out.merge(self._prev)
        out.merge(self._cur)
        return out

    def quantile(self, q: float) -> Optional[float]:
        return self.snapshot().quantile(q)

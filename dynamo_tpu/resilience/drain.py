"""Graceful worker drain.

:class:`DrainCoordinator` sequences a worker's retirement so that no
client stream ever observes it (ref: the reference framework's graceful
shutdown + the migration layer above):

  1. **stop admitting** — delete the endpoint's discovery keys
     (ServeHandle.deregister). The router stops picking this worker;
     requests racing the delete are bounced by the draining engine with
     the migration signal and re-dispatched by the frontend.
  2. **finish or hand off** — each engine drains: in-flight requests
     get ``deadline_s`` to finish naturally; stragglers are terminated
     with the migration signal (``prompt + tokens-so-far`` resumes on a
     surviving worker). Auxiliary loops (prefill consumers, listeners)
     are closed via ``closers``.
  3. **flush the response plane** — wait (bounded) for the ingress'
     in-flight streams to write their terminal chunks.
  4. **revoke the lease LAST** — ``drt.shutdown()``. The lease is the
     liveness primitive: revoking it earlier would erase discovery
     before the handoff chunks are on the wire, turning graceful drain
     into plain death.

``install_signal_handlers`` wires SIGTERM (and SIGINT if asked) to the
sequence — `kubectl delete pod` / instance preemption becomes a drain,
not a massacre (launch/dynamo_run.py, sdk/serve_worker.py).
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import signal as _signal
from typing import Callable, Iterable, Optional

from .. import tracing
from . import faultpoints

logger = logging.getLogger(__name__)


class DrainCoordinator:
    def __init__(
        self,
        drt,
        engines: Iterable = (),
        handles: Iterable = (),
        closers: Iterable[Callable] = (),
        deadline_s: float = 15.0,
        on_done: Optional[Callable[[], None]] = None,
    ):
        self.drt = drt
        self.engines = list(engines)
        self.handles = list(handles)
        self.closers = list(closers)
        self.deadline_s = deadline_s
        self.on_done = on_done
        self._task: Optional[asyncio.Task] = None
        self.stats = {"drains_total": 0, "drain_errors": 0}

    # ---- signal wiring ----

    def install_signal_handlers(self, signals=(_signal.SIGTERM,)) -> None:
        loop = asyncio.get_running_loop()
        for sig in signals:
            try:
                loop.add_signal_handler(sig, self.trigger)
            except (NotImplementedError, RuntimeError):  # non-unix loops
                _signal.signal(sig, lambda *_a: self.trigger())

    def trigger(self) -> asyncio.Task:
        """Idempotent: the first trigger starts the drain; later ones
        (operator mashing ctrl-C, duplicate TERM) return the same task."""
        if self._task is None:
            logger.info("drain triggered (deadline %.1fs)", self.deadline_s)
            self._task = asyncio.get_running_loop().create_task(self._run())
        return self._task

    async def _run(self) -> None:
        try:
            await self.drain()
        except faultpoints.FaultInjected:
            # the harness killed us mid-drain: die like a real mid-drain
            # crash — no further teardown; surviving streams abort on the
            # worker-death path and migrate anyway
            logger.warning("fault injected mid-drain; aborting drain")
            self.stats["drain_errors"] += 1
        except Exception:  # noqa: BLE001
            logger.exception("drain failed")
            self.stats["drain_errors"] += 1
        finally:
            if self.on_done is not None:
                self.on_done()

    # ---- the sequence ----

    async def drain(self) -> dict:
        self.stats["drains_total"] += 1
        loop = asyncio.get_running_loop()
        hard_deadline = loop.time() + self.deadline_s
        with tracing.span("drain.worker", deadline_s=self.deadline_s):
            # 1. stop admitting: vanish from discovery first
            for h in self.handles:
                await h.deregister()
            await faultpoints.hit("mid_drain")
            # auxiliary consumers (prefill queue loops etc.) stop taking
            # new work; their in-flight items redeliver elsewhere
            for c in self.closers:
                r = c()
                if inspect.isawaitable(r):
                    await r
            # 2. drain the engines: finish within the deadline, hand off
            # the rest with the migration signal
            handed_off = 0
            for e in self.engines:
                remaining = max(hard_deadline - loop.time(), 0.0)
                res = await e.drain(deadline_s=remaining, handoff=True)
                handed_off += (res or {}).get("handed_off", 0)
            # 3. let the ingress flush terminal chunks onto the response
            # plane before the transport goes away
            while loop.time() < hard_deadline + 2.0 and any(
                h.inflight_count() for h in self.handles
            ):
                await asyncio.sleep(0.02)
            for h in self.handles:
                await h.stop()
            # 4. lease revocation LAST (drt.shutdown revokes + joins)
            await self.drt.shutdown()
        logger.info("drain complete (%d streams handed off)", handed_off)
        return {"drained": True, "handed_off": handed_off}

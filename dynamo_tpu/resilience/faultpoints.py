"""Deterministic fault-injection harness.

Named fault points sit at the request-lifecycle stages where real
deployments lose workers — admission, mid-prefill, mid-decode,
mid-KV-transfer, mid-drain — and fire *deterministically*: an armed
point counts hits and triggers on the Nth, a fixed number of times.
No randomness, no wall clocks, so a test that kills "the 4th decode
step" kills the 4th decode step on every run and the bit-exact splice
assertions in tests/test_resilience.py stay meaningful.

Arming is programmatic (``faultpoints.arm(...)`` from a test) or via the
``DYN_FAULTPOINTS`` environment variable for subprocess workers::

    DYN_FAULTPOINTS="mid_decode:kill@4,mid_kv_transfer:delay=0.2"

Spec grammar (comma-separated): ``point:action[=delay_s][@after][xN]``
— *action* is ``kill`` (raise :class:`FaultInjected` at the site) or
``delay`` (async sites sleep ``delay_s``); ``@after`` fires on the
Nth hit (default 1st); ``xN`` fires N times (default once, ``x-1``
unlimited).

A ``kill`` raises :class:`FaultInjected`, whose message carries the
"fault injected" worker-lost signature (resilience/policy.py) — the
migration layer classifies it exactly like a real worker death, which
is the point: the harness makes worker loss a reproducible input
instead of a soak-test coincidence.

Unarmed sites cost one dict lookup on an empty registry; production
paths pay nothing measurable.
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass, field
from typing import Optional

logger = logging.getLogger(__name__)

#: the lifecycle stages instrumented across the stack (engine admission /
#: prefill / decode, disagg KV handoff, drain coordinator)
POINTS = (
    "admission",
    "mid_prefill",
    "mid_decode",
    "mid_kv_transfer",
    "mid_drain",
    # fleet prefix cache: the peer-side serve of a kv-peer-fetch —
    # killing here is a worker dying mid-peer-pull (the puller must
    # degrade to recompute, the peer's tiers must stay intact)
    "mid_peer_serve",
    # PRESERVE-style weight pre-stage on the prefetch-hint path: a kill
    # here is the pre-stage plumbing dying — the hint's KV restore must
    # proceed untouched (the pre-stage is advisory, guarded separately)
    "pre_stage_weights",
    # elastic live resharding (engine.reshard): hit once per morph PHASE
    # — pre_stage (weight staging off the hold window), quiesced (loop
    # at a step boundary, device lock held), kv_staged (new-layout
    # weights+KV real, nothing committed), committed (the assignment
    # block ran). Arming kill@N walks the matrix; a kill at any phase
    # must leave the engine wholly on the old layout (N<=3) or wholly
    # on the new one (N=4), never half (docs/elastic_resharding.md)
    "mid_reshard",
)

ACTIONS = ("kill", "delay")

ENV_VAR = "DYN_FAULTPOINTS"


class FaultInjected(RuntimeError):
    """Raised at an armed ``kill`` point. The message carries the
    worker-lost signature, so migration treats it as a worker death."""

    def __init__(self, point: str, hit: int):
        super().__init__(f"fault injected: worker killed at {point} (hit {hit})")
        self.point = point
        self.hit = hit


@dataclass
class _Arm:
    point: str
    action: str = "kill"
    after: int = 1  # fire on the Nth hit (1-based)
    times: int = 1  # firings before the arm goes inert (-1 = unlimited)
    delay_s: float = 0.0
    hits: int = 0
    fired: int = 0

    def take(self) -> bool:
        """Count one hit; True when this hit fires."""
        self.hits += 1
        if self.hits < self.after:
            return False
        if self.times >= 0 and self.fired >= self.times:
            return False
        self.fired += 1
        return True


class FaultPoints:
    """Process-global registry of armed fault points (see module doc)."""

    def __init__(self):
        self._arms: dict[str, _Arm] = {}
        #: (point, action, hit#) tuples of every firing — test forensics
        self.history: list[tuple[str, str, int]] = []

    # ---- arming ----

    def arm(
        self,
        point: str,
        action: str = "kill",
        after: int = 1,
        times: int = 1,
        delay_s: float = 0.0,
    ) -> _Arm:
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r}; known: {POINTS}")
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; known: {ACTIONS}")
        if after < 1:
            raise ValueError(f"after={after} must be >= 1")
        arm = _Arm(point, action, after=after, times=times, delay_s=delay_s)
        self._arms[point] = arm
        return arm

    def disarm(self, point: str) -> None:
        self._arms.pop(point, None)

    def reset(self) -> None:
        self._arms.clear()
        self.history.clear()

    def armed(self, point: Optional[str] = None) -> bool:
        if point is None:
            return bool(self._arms)
        return point in self._arms

    def arm_from_spec(self, spec: str) -> None:
        """Parse a ``DYN_FAULTPOINTS``-style spec (module doc grammar)."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            point, _, rest = part.partition(":")
            action, after, times, delay_s = "kill", 1, 1, 0.0
            if rest:
                if "x" in rest:
                    rest, _, t = rest.rpartition("x")
                    times = int(t)
                if "@" in rest:
                    rest, _, a = rest.partition("@")
                    after = int(a)
                if rest:
                    action, _, d = rest.partition("=")
                    if d:
                        delay_s = float(d)
            self.arm(point, action, after=after, times=times, delay_s=delay_s)

    # ---- firing ----

    def _fire(self, point: str) -> Optional[_Arm]:
        arm = self._arms.get(point)
        if arm is None or not arm.take():
            return None
        self.history.append((point, arm.action, arm.hits))
        logger.warning(
            "FAULT POINT %s fired: %s (hit %d)", point, arm.action, arm.hits
        )
        return arm

    def hit_sync(self, point: str, **ctx) -> None:
        """Synchronous site (scheduler loop, device paths). ``kill``
        raises; ``delay`` is ignored here — a sync sleep would stall the
        event loop, which is its own bug class, not this harness's."""
        if not self._arms:
            return
        arm = self._fire(point)
        if arm is None:
            return
        if arm.action == "kill":
            raise FaultInjected(point, arm.hits)
        logger.debug("delay fault at sync site %s ignored", point)

    async def hit(self, point: str, **ctx) -> None:
        """Async site. ``kill`` raises; ``delay`` sleeps ``delay_s``."""
        if not self._arms:
            return
        arm = self._fire(point)
        if arm is None:
            return
        if arm.action == "kill":
            raise FaultInjected(point, arm.hits)
        await asyncio.sleep(arm.delay_s)


#: the process-global registry every instrumented site consults
FAULTS = FaultPoints()

# module-level conveniences (the instrumented sites call these)
arm = FAULTS.arm
disarm = FAULTS.disarm
reset = FAULTS.reset
armed = FAULTS.armed
hit = FAULTS.hit
hit_sync = FAULTS.hit_sync

_env_spec = os.environ.get(ENV_VAR, "")
if _env_spec:
    # subprocess workers arm from the environment at import (the tests'
    # only lever into a worker they exec rather than construct)
    try:
        FAULTS.arm_from_spec(_env_spec)
    except Exception:  # noqa: BLE001 — a typo'd spec must not kill startup
        logger.exception("bad %s spec %r ignored", ENV_VAR, _env_spec)

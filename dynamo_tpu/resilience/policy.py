"""Migration policy knobs + disconnect classification.

The migration layer only ever retries failures that a *different worker*
can plausibly absorb. Classification is three-way:

  * ``WORKER_LOST`` — the stream died with a worker-death signature:
    the response-plane TCP connection truncated/reset, the worker's
    ingress aborted on shutdown, a drain handed the stream off, or the
    fault harness killed the worker. The worker's discovery key (bound
    to its primary lease) vanishes with it — re-dispatch immediately,
    the router will not pick the corpse.
  * ``TRANSIENT`` — the *dispatch* failed before or without a worker
    verdict (hub connection lost mid-request, no responders during a
    membership gap, connect-back timeout). Retry after a short jittered
    backoff; the control plane heals underneath.
  * ``FATAL`` — the worker answered with a deterministic engine error
    (bad request, capacity, model failure). Another worker would say
    the same thing: surface it to the client unchanged.

Lease loss vs. TCP blip: when the classifier is given the discovery
client and the routed worker id (kv_router stamps it into the request
annotations), a worker-lost signature is refined — instance gone from
the store watch means lease loss (``lease_lost``); instance still
registered means the stream broke while the worker lives, which retries
with the transient backoff instead (the same worker may legitimately be
re-picked).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

#: terminal-chunk text a draining worker attaches when it hands an
#: in-flight stream back (engine._handoff_seq); carries the
#: "worker draining" signature below so migration re-dispatches it
MIGRATION_SIGNAL = "worker draining: stream handed off for migration"

#: error-message signatures that mean "the worker is gone, the request
#: is not at fault" (tcp.py truncation, component.py shutdown abort,
#: drain handoff, fault harness, engine scheduler death)
WORKER_LOST_SIGNATURES = (
    "response stream truncated",
    "worker shutdown: stream aborted",
    "worker hung up",
    "worker draining",
    "fault injected",
    "engine stopped",
)


class FailureKind(str, enum.Enum):
    WORKER_LOST = "worker_lost"
    LEASE_LOST = "lease_lost"  # worker-lost refined by the store watch
    TRANSIENT = "transient"
    FATAL = "fatal"

    @property
    def retryable(self) -> bool:
        return self is not FailureKind.FATAL


@dataclass
class MigrationPolicy:
    """Frontend migration knobs (dynamo_run --no-migration /
    --max-migrations / --migration-deadline)."""

    #: master off-switch: disabled => every failure surfaces unchanged
    enabled: bool = True
    #: re-dispatch attempts per request before surfacing the failure
    max_migrations: int = 3
    #: wall-clock budget (s) from a request's FIRST failure — bounds how
    #: long a client stream may stall across migrations
    deadline_s: float = 30.0
    #: base backoff (s) between transient re-dispatches (jittered up to
    #: 2x by attempt ordinal — deterministic, no RNG)
    backoff_s: float = 0.05


def classify_failure(
    message: Optional[str] = None,
    exc: Optional[BaseException] = None,
    worker_id: Optional[int] = None,
    client=None,
) -> FailureKind:
    """Map a stream failure to a FailureKind (see module doc).

    ``client`` is the discovery client whose store watch tracks live
    instances; ``worker_id`` is the instance the router pinned the
    request to (absent for round-robin dispatches).
    """
    if exc is not None:
        from .faultpoints import FaultInjected

        if isinstance(exc, FaultInjected):
            return FailureKind.WORKER_LOST
        if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
            return FailureKind.TRANSIENT
        # NoResponders / hub StoreError: dispatch never reached a worker
        from ..runtime.bus import BusError

        if isinstance(exc, (BusError,)):
            return FailureKind.TRANSIENT
        import asyncio

        if isinstance(exc, asyncio.TimeoutError):
            return FailureKind.TRANSIENT
        message = message or str(exc)
    msg = message or ""
    if any(sig in msg for sig in WORKER_LOST_SIGNATURES):
        if client is not None and worker_id is not None:
            try:
                alive = worker_id in set(client.instance_ids())
            except Exception:  # noqa: BLE001 — classification must not throw
                alive = False
            if alive:
                # the worker still holds its lease: a TCP blip, not a
                # death — retry on the transient (backoff) path
                return FailureKind.TRANSIENT
            return FailureKind.LEASE_LOST
        return FailureKind.WORKER_LOST
    return FailureKind.FATAL

"""Request-lifecycle reliability: transparent in-flight migration,
graceful drain, and a deterministic fault-injection harness.

The subsystem that makes worker death invisible to clients
(docs/resilience.md):

  * :mod:`.migration` — :class:`MigratingEngine`, the frontend stream
    wrapper that checkpoints emitted tokens and re-dispatches broken
    streams as ``prompt + tokens-so-far`` (exactly-once splice, RNG /
    penalty continuity, KV-aware placement through the router);
  * :mod:`.policy` — :class:`MigrationPolicy` knobs + the disconnect
    classifier (lease loss vs. transient blip vs. engine error);
  * :mod:`.drain` — :class:`DrainCoordinator`, the SIGTERM sequence:
    stop admitting, finish or hand off in-flight work, revoke the
    lease last;
  * :mod:`.faultpoints` — named, deterministic kill/delay points at
    every lifecycle stage, armed programmatically or via
    ``DYN_FAULTPOINTS`` (the tests' and soak's worker-killing lever);
  * :mod:`.reshard` — :class:`ReshardListener`, the worker-side
    actuation of planner morph decisions (elastic live resharding,
    docs/elastic_resharding.md) with the drain-with-handoff fallback
    for engines that can't morph live.
"""

from . import faultpoints
from .drain import DrainCoordinator
from .faultpoints import FaultInjected
from .migration import MigratingEngine, ROUTED_WORKER_KEY
from .reshard import ReshardListener
from .policy import (
    MIGRATION_SIGNAL,
    WORKER_LOST_SIGNATURES,
    FailureKind,
    MigrationPolicy,
    classify_failure,
)

__all__ = [
    "DrainCoordinator",
    "FailureKind",
    "FaultInjected",
    "MIGRATION_SIGNAL",
    "MigratingEngine",
    "MigrationPolicy",
    "ROUTED_WORKER_KEY",
    "ReshardListener",
    "WORKER_LOST_SIGNATURES",
    "classify_failure",
    "faultpoints",
]

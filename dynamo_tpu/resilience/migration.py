"""Transparent in-flight request migration.

:class:`MigratingEngine` wraps the frontend's dispatch stage (normally
the KV-routed client engine) at the PreprocessedRequest ->
LLMEngineOutput level — *below* the detokenizer, so the incremental
decode / stop-jail state upstream never notices a seam — and makes
worker death invisible to clients:

  * **checkpoint**: every token id that reaches the client is recorded
    per in-flight request (the only state migration needs — the KV is
    recomputable, the tokens are not);
  * **classify**: a stream failure is classified (resilience/policy.py)
    as worker-lost (lease gone), transient (control-plane blip), or
    fatal (deterministic engine error);
  * **re-dispatch**: retryable failures re-enter the wrapped engine as
    ``prompt + tokens-so-far`` with a ``resume`` annotation carrying the
    original prompt length. The engine (engine/engine.py) restores the
    prompt/generated split from it, so

      - sampling continues the *same* RNG stream (per-step keys are
        ``fold_in(seed, generated)`` — generated resumes at the seam),
      - frequency/presence/repetition penalty state rebuilds from the
        true prompt/output split (not the spliced prompt),
      - ``max_tokens``/``min_tokens``/usage accounting count from the
        original prompt,

    which makes the splice exactly-once and, for greedy decoding,
    bit-exact against an undisturbed run (the engine's preemption
    replay path gives the same guarantee intra-worker);
  * **KV-aware placement**: the resumed request flows through the same
    KV router, whose radix index scores the (prompt + generated) chain
    against every surviving worker — the replacement lands where the
    longest prefix already sits, and the router's ``kv-prefetch`` hint
    (PR 1) starts warming the host tier before the request arrives.

The wrapper never retries a failure another worker cannot absorb: see
policy.classify_failure. ``max_migrations`` and ``deadline_s`` bound the
worst case; ``enabled=False`` restores the old die-with-the-worker
behavior.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Optional

from .. import tracing
from ..protocols.common import PreprocessedRequest
from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, Context
from .policy import (
    WORKER_LOST_SIGNATURES,
    FailureKind,
    MigrationPolicy,
    classify_failure,
)

logger = logging.getLogger(__name__)

#: request.annotations key the KV router stamps with its pinned worker id
#: (lets the classifier distinguish lease loss from a TCP blip)
ROUTED_WORKER_KEY = "routed_worker_id"

#: request.annotations key listing the worker ids this request already
#: failed on — the KV router soft-excludes them when re-scheduling, so a
#: killed worker whose lease has not yet expired (and whose radix prefix
#: affinity would otherwise win every re-pick) doesn't eat the migration
#: budget before discovery notices the death
AVOID_WORKER_KEY = "migration.avoid_workers"

#: PreprocessedRequest.annotations key carrying the resume state the
#: engine restores the prompt/generated split from
RESUME_KEY = "resume"


def _inspect_chunk(data) -> tuple[list[int], Optional[str], Optional[str]]:
    """-> (token_ids, finish_reason_value_or_None, text) for a stream
    chunk in either wire (dict) or in-process (LLMEngineOutput) shape."""
    if isinstance(data, dict):
        return (
            list(data.get("token_ids") or []),
            data.get("finish_reason"),
            data.get("text"),
        )
    fr = getattr(data, "finish_reason", None)
    if fr is not None:
        fr = getattr(fr, "value", fr)
    return (
        list(getattr(data, "token_ids", None) or []),
        fr,
        getattr(data, "text", None),
    )


def _is_handoff_text(text: Optional[str]) -> bool:
    return bool(text) and any(sig in text for sig in WORKER_LOST_SIGNATURES)


class MigratingEngine(AsyncEngine):
    """Migration-aware stream wrapper (see module doc).

    ``client`` (optional) is the discovery Client whose store watch
    tracks live instances — used only to refine worker-lost vs. TCP-blip
    classification; the wrapper works without it.
    """

    def __init__(
        self,
        inner: AsyncEngine,
        policy: Optional[MigrationPolicy] = None,
        client=None,
    ):
        self.inner = inner
        self.policy = policy or MigrationPolicy()
        self.client = client
        self.stats = {
            # successful + attempted re-dispatches (one per seam)
            "migrations_total": 0,
            # distinct requests that hit at least one retryable failure
            "requests_migrated": 0,
            # requests whose failure was surfaced (fatal / budget spent)
            "migration_failures": 0,
        }

    async def close(self) -> None:
        await self.inner.close()

    async def generate(self, request: Context) -> AsyncIterator:
        data = request.data
        if isinstance(data, PreprocessedRequest):
            base = data.to_dict()
        elif isinstance(data, dict) and "token_ids" in data:
            base = dict(data)
        else:
            # not a token-level request (text engines, custom payloads):
            # nothing to splice — pass straight through
            async for item in self.inner.generate(request):
                yield item
            return

        base_tokens = list(base.get("token_ids") or [])
        base_ann = dict(base.get("annotations") or {})
        # an already-resumed request (e.g. re-entering through a second
        # frontend) keeps its ORIGINAL prompt length
        prompt_len = len(base_tokens)
        prior = base_ann.get(RESUME_KEY) or {}
        if isinstance(prior, dict) and prior.get("prompt_len"):
            try:
                prompt_len = min(int(prior["prompt_len"]), prompt_len)
            except (TypeError, ValueError):
                pass

        emitted: list[int] = []  # every token id the client has seen
        avoid: set[int] = set()  # workers this request already failed on
        attempts = 0
        deadline: Optional[float] = None
        cur = request
        loop = asyncio.get_running_loop()

        while True:
            failure: Optional[str] = None
            exc: Optional[BaseException] = None
            try:
                async for item in self.inner.generate(cur):
                    a = (
                        item
                        if isinstance(item, Annotated)
                        else Annotated.from_data(item)
                    )
                    if a.is_error():
                        failure = a.error or "engine error"
                        break
                    if a.data is None:
                        yield item
                        continue
                    toks, fr, text = _inspect_chunk(a.data)
                    if fr == "error" and _is_handoff_text(text):
                        # a draining/dead worker terminated the stream
                        # with the migration signal — never client-visible
                        failure = text
                        break
                    emitted.extend(toks)
                    yield item
                    if fr is not None:
                        return  # clean terminal chunk: done
            except Exception as e:  # noqa: BLE001 — dispatch failures
                # (NoResponders, hub ConnectionError, connect timeouts)
                # and in-process FaultInjected kills land here
                exc = e
                failure = f"{type(e).__name__}: {e}"
            if failure is None and exc is None:
                # the stream ended with neither a finish chunk nor an
                # error: a silent truncation (in-process analogue of the
                # TCP sentinel-less EOF) — retryable
                failure = (
                    "response stream truncated: stream ended without a "
                    "finish chunk"
                )

            ctx_ann = cur.annotations if isinstance(cur.annotations, dict) else {}
            kind = classify_failure(
                failure,
                exc,
                worker_id=ctx_ann.get(ROUTED_WORKER_KEY),
                client=self.client,
            )
            if kind.retryable and isinstance(
                ctx_ann.get(ROUTED_WORKER_KEY), int
            ):
                # steer the re-dispatch away from the worker that just
                # failed — even a "transient" verdict may be a corpse
                # whose lease hasn't expired yet (soft exclusion: the
                # router falls back to it if nothing else is alive)
                avoid.add(ctx_ann[ROUTED_WORKER_KEY])
            if (
                not self.policy.enabled
                or not kind.retryable
                or request.context.is_killed()
            ):
                if self.policy.enabled and kind.retryable:
                    # killed mid-migration: the client is gone; end quietly
                    return
                self.stats["migration_failures"] += 1
                yield Annotated.from_error(failure)
                return

            now = loop.time()
            if deadline is None:
                deadline = now + self.policy.deadline_s
            attempts += 1
            if attempts > self.policy.max_migrations or now >= deadline:
                self.stats["migration_failures"] += 1
                logger.warning(
                    "request %s: migration budget exhausted after %d "
                    "re-dispatches (%s)", request.id, attempts - 1, failure,
                )
                yield Annotated.from_error(
                    f"migration budget exhausted after {attempts - 1} "
                    f"re-dispatches: {failure}"
                )
                return

            if attempts == 1:
                self.stats["requests_migrated"] += 1
            self.stats["migrations_total"] += 1
            logger.info(
                "request %s: %s (%s); re-dispatching with %d tokens so far "
                "(attempt %d/%d)", request.id, kind.value, failure,
                len(emitted), attempts, self.policy.max_migrations,
            )
            tracing.event(
                "migration.redispatch",
                request_id=request.id,
                attempt=attempts,
                kind=kind.value,
                tokens_so_far=len(emitted),
                reason=(failure or "")[:160],
            )
            if kind is FailureKind.TRANSIENT:
                # deterministic ordinal backoff: the control plane needs
                # a beat to heal (hub redial, membership settling)
                await asyncio.sleep(self.policy.backoff_s * attempts)
            elif attempts > 1:
                # repeated worker-lost bounces (rolling drain with no
                # survivor up yet) pace the same way — only the FIRST
                # re-dispatch is instant, so the attempt budget can't
                # burn out in microseconds while deadline_s has room
                await asyncio.sleep(self.policy.backoff_s * attempts)
            cur = self._resume_request(
                request, base, base_ann, base_tokens, emitted, prompt_len,
                attempts, avoid,
            )

    @staticmethod
    def _resume_request(
        request: Context,
        base: dict,
        base_ann: dict,
        base_tokens: list[int],
        emitted: list[int],
        prompt_len: int,
        attempts: int,
        avoid: set,
    ) -> Context:
        """Build the re-dispatch: prompt + tokens-so-far, with the resume
        annotation restoring the original prompt/generated split."""
        payload = dict(base)
        payload["token_ids"] = base_tokens + emitted
        ann = dict(base_ann)
        ann[RESUME_KEY] = {"prompt_len": prompt_len, "migrations": attempts}
        payload["annotations"] = ann
        ctx_ann = dict(request.annotations or {})
        ctx_ann.pop(ROUTED_WORKER_KEY, None)
        if avoid:
            ctx_ann[AVOID_WORKER_KEY] = sorted(avoid)
        # same AsyncEngineContext: request identity and stop/kill
        # propagation survive the seam
        return Context(payload, request.context, ctx_ann)

"""Worker-side actuation of planner morph decisions.

The planner publishes :class:`~dynamo_tpu.planner.protocols.
MorphDecision` events on the component's ``reshard`` subject (its third
verb beside scale-up/down); every worker runs a :class:`ReshardListener`
that filters for its own id (0 = pool-wide), maps the requested degree
onto its current mesh axes, and drives ``JaxEngine.reshard`` — the
quiesce/morph/resume protocol in docs/elastic_resharding.md.

Resilience contract:

  * morphs apply ONE AT A TIME per worker (a second decision arriving
    mid-morph waits; the engine itself also rejects overlapping
    reshard calls) — the planner-side ScaleGuard rails already pace
    the stream, this is the belt to those braces;
  * an engine that cannot morph live (multi-host mirrors raise
    ``ReshardUnsupported``) falls back to the PR 4 path: drain with
    handoff, so its streams migrate to workers that can serve the new
    layout — the decision is honored, just by replica churn instead of
    an in-place morph;
  * a failed morph (device shortage for the requested degree, a
    mid-morph fault) is counted and logged, never raised into the
    subscription loop — the engine stays wholly on its old layout and
    the next decision gets a fresh attempt.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..planner.protocols import PLANNER_RESHARD_SUBJECT, MorphDecision

logger = logging.getLogger(__name__)


class ReshardListener:
    """Subscribe the ``reshard`` subject and actuate morphs on one
    engine (see module doc)."""

    def __init__(self, drt, component, worker_id: int, engine,
                 drain_deadline_s: float = 10.0, pool: str = "decode"):
        self.drt = drt
        self.subject = component.event_subject(PLANNER_RESHARD_SUBJECT)
        self.worker_id = worker_id
        self.engine = engine
        #: the pool this worker belongs to — decisions for another pool
        #: are not ours even at worker_id=0 (a decode-pool TP grow must
        #: not morph prefill workers sharing the subject)
        self.pool = pool
        self.drain_deadline_s = drain_deadline_s
        self.morphs_applied = 0
        self.morphs_noop = 0
        self.morphs_failed = 0
        #: decisions honored via drain+handoff because the engine can't
        #: morph live (mirrors)
        self.morphs_drained = 0
        self._task: Optional[asyncio.Task] = None
        self._sub = None
        self._lock = asyncio.Lock()

    async def start(self) -> "ReshardListener":
        sub = self.drt.bus.subscribe(self.subject)
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._sub = sub
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()

    def _target_mesh(self, decision: MorphDecision):
        """Map the decision's degree onto this engine's mesh axes: keep
        every non-TP axis, swap TP. A fully-trivial result (every axis
        1) normalizes to None — the unsharded single-device fast path,
        so a shrink returns the engine to exactly the layout it would
        have been built with."""
        from ..parallel.mesh import MeshConfig

        cur = self.engine.cfg.mesh
        base = cur if cur is not None else MeshConfig()
        target = MeshConfig(dp=base.dp, pp=base.pp, sp=base.sp,
                            ep=base.ep, tp=max(int(decision.tp), 1))
        return target if target.num_devices > 1 else None

    async def _consume(self, sub) -> None:
        async for msg in sub:
            try:
                decision = MorphDecision.from_bytes(msg.payload)
                if decision is None:
                    continue
                if decision.worker_id not in (0, self.worker_id):
                    continue
                if decision.pool != self.pool:
                    continue
                await self._apply(decision)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad event must not
                logger.debug("bad reshard event", exc_info=True)

    async def _apply(self, decision: MorphDecision) -> None:
        from ..engine.engine import ReshardUnsupported

        async with self._lock:  # one morph at a time per worker
            target = self._target_mesh(decision)
            try:
                out = await self.engine.reshard(
                    target, hold=decision.hold, force=decision.force
                )
                if out.get("changed"):
                    self.morphs_applied += 1
                    logger.info(
                        "morph %s applied (%s): %s", decision.reason,
                        decision.tp, out,
                    )
                else:
                    self.morphs_noop += 1
            except ReshardUnsupported:
                # mirrors can't morph live: honor the decision through
                # the migration path — streams continue elsewhere while
                # this worker restarts on the new layout
                self.morphs_drained += 1
                logger.info(
                    "engine can't morph live; draining with handoff "
                    "for morph %s", decision.reason,
                )
                try:
                    await self.engine.drain(  # dynlint: disable=await-in-lock -- this lock exists to serialize morphs on one engine; the drain IS the morph being serialized, not incidental I/O under it
                        deadline_s=self.drain_deadline_s, handoff=True
                    )
                except Exception:  # noqa: BLE001
                    logger.exception("morph drain fallback failed")
            except Exception:  # noqa: BLE001 — engine stays on the old
                # layout; count it and let the next decision retry
                self.morphs_failed += 1
                logger.exception(
                    "morph %s (tp=%s) failed; engine unchanged",
                    decision.reason, decision.tp,
                )

    def stats(self) -> dict:
        return {
            "reshard_morphs_applied": self.morphs_applied,
            "reshard_morphs_noop": self.morphs_noop,
            "reshard_morphs_failed": self.morphs_failed,
            "reshard_morphs_drained": self.morphs_drained,
        }

"""Worker-side mirror of autopilot quarantine state.

The quarantine decision is ROUTER-side (the scheduler soft-excludes
quarantined workers, like ``resharding`` ones — held streams drain, a
lone-worker pool still serves); the worker itself needs no actuation.
What it needs is *visibility*: an operator looking at one worker's
scrape must see that the autopilot pulled it from rotation, and the
fleet metrics plane must be able to render quarantine state per worker
without reaching into the controller. The :class:`QuarantineListener`
subscribes the ``autopilot-health`` subject and mirrors this worker's
membership into ``engine.stats`` (``autopilot_quarantined`` flag,
``autopilot_quarantines_total`` transitions), which the existing
``load_metrics`` -> WorkerLoad -> metrics-render plane carries
fleet-wide.

Same shape as the reshard/warmup listeners: tolerant decode, one bad
event never ends the subscription loop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..autopilot.protocols import AUTOPILOT_HEALTH_SUBJECT, HealthDirective

logger = logging.getLogger(__name__)


class QuarantineListener:
    def __init__(self, drt, component, worker_id: int, engine):
        self.drt = drt
        self.subject = component.event_subject(AUTOPILOT_HEALTH_SUBJECT)
        self.worker_id = worker_id
        self.engine = engine
        #: this worker's current view of itself
        self.quarantined = False
        self.probing = False
        self.quarantines_seen = 0
        self.directives_seen = 0
        self._task: Optional[asyncio.Task] = None
        self._sub = None

    async def start(self) -> "QuarantineListener":
        sub = self.drt.bus.subscribe(self.subject)
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._sub = sub
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()

    async def _consume(self, sub) -> None:
        async for msg in sub:
            try:
                directive = HealthDirective.from_bytes(msg.payload)
                if directive is None:
                    continue
                self.apply(directive)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad event must not
                logger.debug("bad health directive", exc_info=True)

    def apply(self, directive: HealthDirective) -> None:
        """Fold one health view (full replacement — newest wins)."""
        self.directives_seen += 1
        was = self.quarantined
        self.quarantined = self.worker_id in directive.quarantined
        self.probing = self.worker_id in directive.probing
        if self.quarantined and not was:
            self.quarantines_seen += 1
            logger.warning("worker %x quarantined by autopilot",
                           self.worker_id)
        elif was and not self.quarantined:
            logger.info("worker %x %s by autopilot", self.worker_id,
                        "probing" if self.probing else "reinstated")
        self._mirror()

    def _mirror(self) -> None:
        stats = getattr(self.engine, "stats", None)
        if stats is None:
            return
        stats["autopilot_quarantined"] = int(self.quarantined)
        stats["autopilot_quarantines_total"] = self.quarantines_seen

    def stats(self) -> dict:
        return {
            "autopilot_quarantined": int(self.quarantined),
            "autopilot_probing": int(self.probing),
            "autopilot_quarantines_total": self.quarantines_seen,
            "autopilot_health_directives_seen": self.directives_seen,
        }

"""Multi-host bootstrap + single-controller SPMD step mirroring.

The reference spans nodes with engine-specific bootstrap — Ray for vLLM
(`lib/llm/src/engines/vllm/ray.rs`), one-process-per-rank for SGLang
(`engines/sglang.rs:59-76`), MPI for TRT-LLM — configured by
`MultiNodeConfig{num_nodes, node_rank, leader_addr}`
(`lib/llm/src/engines.rs:35-52`) and the `--num-nodes/--node-rank/
--leader-addr` flags (`launch/dynamo-run/src/flags.rs:59-92`).

The TPU-native equivalent is JAX's multi-controller runtime:

  * :func:`initialize` — `jax.distributed.initialize(coordinator,
    num_processes, process_id)`; after it, `jax.devices()` is the GLOBAL
    device list across all hosts and collectives ride ICI within a slice /
    DCN (gloo on CPU) across.
  * :func:`global_mesh` — a `jax.sharding.Mesh` over the global devices,
    ordered process-major so the leading mesh axes span hosts.
  * :class:`StepMirror` — serving is request-driven, but SPMD requires
    every process to enter every compiled program in lockstep. The leader
    (process 0) owns the scheduler (continuous batching, block allocation,
    admission) and, per device dispatch, broadcasts a tiny step descriptor
    + host inputs to the followers, which replay the identical jit call —
    single-controller scheduling, SPMD execution. Leases/HTTP/discovery
    live only on the leader; followers are pure compute ranks.

Wire protocol per dispatch: ONE `broadcast_one_to_all` of a fixed-size
frame packing [4B header length][JSON header][array payload bytes] —
the decode hot loop's host inputs (~10 small arrays) fit comfortably, so
the per-window cost is a single collective round (VERDICT r2 #5: the
two-round header+arrays scheme doubled the host sync per window). Ops
whose payload exceeds the frame (KV block data) mark ``inline: false``
and ship arrays in a second broadcast of exact size:

    leader: lead(op, arrays)  ->  followers: op, arrays = follow()

Both sides then call the same fused jit (decode+sample / prefill /
sample1 / verify / kv ops) on identically-sharded global arrays —
replicated inputs go through a content-keyed device_put cache, so
rarely-changing arrays (block tables, sampling params) skip the H2D
re-placement. Sampled tokens come back with replicated out_shardings so
the leader can read its local shard.
"""

from __future__ import annotations

import json
import logging
import struct
from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

# one-round frame: header + small-op payloads ride a single collective.
# The decode op's payload is dominated by the [B, M] int32 block tables
# (B*M*4 bytes): 64KB covers e.g. B=16 x M=512 or B=64 x M=128 plus the
# ~9 [B] vectors and header. Larger configs (and KV block payloads)
# silently take the two-round path — correct, one extra collective.
_FRAME_BYTES = 65536

# stable replicated inputs per mirrored op (broadcast-array index ->
# cache key): block tables change only on allocation, sampling params
# only on admission — their device placement is content-cached. Indices
# follow the lead_decode / lead_verify head_arrays order.
_PLACE_CACHE = {
    "decode": {2: "tables", 4: "seeds", 6: "temps", 7: "top_ks",
               8: "top_ps", 9: "freq", 10: "pres", 11: "rep"},
    "verify": {3: "tables", 5: "seeds", 7: "temps", 8: "top_ks",
               9: "top_ps", 10: "freq", 11: "pres", 12: "rep"},
}


@dataclass
class MultiHostConfig:
    """Mirrors the reference MultiNodeConfig (engines.rs:35-52)."""

    num_nodes: int = 1
    node_rank: int = 0
    coordinator: Optional[str] = None  # host:port of node 0 (leader_addr)

    @property
    def enabled(self) -> bool:
        return self.num_nodes > 1

    @property
    def is_leader(self) -> bool:
        return self.node_rank == 0


def initialize(cfg: MultiHostConfig) -> None:
    """Join the multi-controller runtime. Call BEFORE any jax device init
    (backend creation binds the process to its local devices only)."""
    if not cfg.enabled:
        return
    if cfg.coordinator is None:
        raise ValueError("--coordinator host:port is required with --num-nodes > 1")
    import os

    import jax

    plat = (os.environ.get("JAX_PLATFORMS") or "").lower()
    if "cpu" in plat:
        # newer jax (>=0.4.34-era) refuses multiprocess computations on
        # the CPU backend unless a cross-process collectives impl is
        # chosen explicitly; gloo is the one shipped in jaxlib. Must be
        # set BEFORE backend creation. Older versions lack the option
        # (and allowed multiprocess CPU without it) — ignore there.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — unknown config on old jax
            logger.debug("no jax_cpu_collectives_implementation option",
                         exc_info=True)

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_nodes,
        process_id=cfg.node_rank,
    )
    logger.info(
        "joined multihost runtime: process %d/%d, %d local / %d global devices",
        cfg.node_rank, cfg.num_nodes,
        jax.local_device_count(), jax.device_count(),
    )


def mesh_devices() -> list:
    """Global devices ordered process-major (leading mesh axes span hosts,
    trailing axes stay within a host — tp rides ICI, dp/pp span DCN)."""
    import jax

    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


def global_mesh(mesh_cfg):
    """Mesh over the global (all-hosts) device list."""
    from .mesh import make_mesh

    return make_mesh(mesh_cfg, devices=mesh_devices())


# ---------------- step mirroring ----------------


class StepMirror:
    """Leader/follower lockstep dispatch over a global mesh.

    One instance per engine (leader) or follower loop. All methods ending
    in ``lead_*`` run on the leader; :meth:`follow` runs on followers.
    The fused jits are shared by both sides so the compiled programs (and
    their collectives) are identical.
    """

    def __init__(self, mesh, model_cfg):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .mesh import cache_sharding

        self.mesh = mesh
        self.model_cfg = model_cfg
        self.is_leader = jax.process_index() == 0
        self._rep = NamedSharding(mesh, P())
        self._cache_sh = cache_sharding(mesh, model_cfg)
        self._fns = {}
        # content-keyed device_put cache for rarely-changing replicated
        # inputs (block tables, sampling params): leader and followers
        # each skip the per-window H2D when bytes are unchanged
        self._gcache: dict = {}

    # ---- array placement ----

    def to_global(self, host_array: np.ndarray):
        """Replicated global array from an identical-everywhere host
        value (collective-free placement — see mesh.put_global; the
        mirror protocol itself guarantees the identical-everywhere
        part, so no cross-process assert is needed or wanted)."""
        from .mesh import put_global

        return put_global(np.asarray(host_array), self._rep)

    def to_global_cached(self, key: str, host_array: np.ndarray):
        """to_global through a per-key content cache: unchanged bytes
        reuse the previously placed device array (the decode hot loop's
        tables/sampling params change only on admission)."""
        arr = np.asarray(host_array)
        b = arr.tobytes()
        hit = self._gcache.get(key)
        if hit is not None and hit[0] == b:
            return hit[1]
        g = self.to_global(arr)
        self._gcache[key] = (b, g)
        return g

    def place_inputs(self, op: str, arrays, skip=()) -> list:
        """Replicated device placement for a mirrored op's host inputs,
        caching the stable ones (_PLACE_CACHE). Used identically by the
        leader and the follower loop so both sides skip the same H2Ds.
        ``skip`` indices yield None (chained decode replaces the token
        input with a device slice — don't pay its H2D)."""
        keys = _PLACE_CACHE.get(op, {})
        return [
            None if i in skip
            else self.to_global_cached(f"{op}:{keys[i]}", a)
            if i in keys else self.to_global(a)
            for i, a in enumerate(arrays)
        ]

    def init_cache(self, num_blocks: int, block_size: int, dtype=None):
        """KV cache created directly with its global sharding (no host
        roundtrip; every process materializes only its shards)."""
        import jax
        import jax.numpy as jnp

        from ..models import llama

        cfg = self.model_cfg
        ks, vs = llama.kv_cache_shapes(cfg, num_blocks, block_size)
        dt = dtype or llama._dtype(cfg)
        make = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
            lambda: (jnp.zeros(ks, dt), jnp.zeros(vs, dt)),
            out_shardings=(self._cache_sh, self._cache_sh),
        )
        return make()

    def shard_params(self, params: dict) -> dict:
        """Place identically-initialized host params onto the global mesh
        (device_put with a multi-process sharding assumes every rank passes
        the same host value — guaranteed by same-seed init / same checkpoint)."""
        from .mesh import shard_params

        return shard_params(params, self.mesh)

    # ---- fused step programs (shared leader/follower) ----

    def _decode_fn(self, n_steps: int = 1, use_pallas: bool = False,
                   unroll: bool = True, merged: bool = True,
                   penalized: bool = False, with_logprobs: bool = False):
        key = ("decode", n_steps, use_pallas, unroll, merged, penalized,
               with_logprobs)
        if key not in self._fns:
            import jax

            from ..models import llama

            cfg = self.model_cfg
            mesh = self.mesh  # sharded pallas attention + ragged MoE

            # pin outputs: tokens/counts/logprobs replicated (the leader
            # reads their local shards), caches on the cache sharding (the
            # donation round-trip depends on a stable layout)
            out_sh = [self._rep, self._cache_sh, self._cache_sh]
            if penalized:
                out_sh.append(self._rep)
            if with_logprobs:
                out_sh.append((self._rep, self._rep, self._rep))
            out_sh = tuple(out_sh)

            if penalized:

                def step(params, tokens, positions, tables, seq_lens, seeds,
                         steps, temps, top_ks, top_ps, freq, pres, rep,
                         k_cache, v_cache, counts, prompt_mask):
                    return llama.decode_window.__wrapped__(
                        params, cfg, tokens, positions, tables, seq_lens,
                        seeds, steps, temps, top_ks, top_ps, k_cache,
                        v_cache, n_steps=n_steps, use_pallas=use_pallas,
                        mesh=mesh, unroll=unroll, merged=merged,
                        with_logprobs=with_logprobs, freq_pens=freq,
                        pres_pens=pres, rep_pens=rep, counts=counts,
                        prompt_mask=prompt_mask,
                    )

                self._fns[key] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                    step, donate_argnums=(13, 14, 15), out_shardings=out_sh
                )
            else:

                def step(params, tokens, positions, tables, seq_lens, seeds,
                         steps, temps, top_ks, top_ps, k_cache, v_cache):
                    return llama.decode_window.__wrapped__(
                        params, cfg, tokens, positions, tables, seq_lens,
                        seeds, steps, temps, top_ks, top_ps, k_cache,
                        v_cache, n_steps=n_steps, use_pallas=use_pallas,
                        mesh=mesh, unroll=unroll, merged=merged,
                        with_logprobs=with_logprobs,
                    )

                self._fns[key] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                    step, donate_argnums=(10, 11), out_shardings=out_sh
                )
        return self._fns[key]

    def _prefill_fn(self, use_pallas: bool = False, use_ring: bool = False):
        key = ("prefill", use_pallas, use_ring)
        if key not in self._fns:
            import jax

            from ..models import llama

            cfg = self.model_cfg
            mesh = self.mesh  # sharded pallas attention + ragged MoE

            def step(params, toks, table, pos, valid, k_cache, v_cache):
                return llama.prefill.__wrapped__(
                    params, cfg, toks, table, pos, valid, k_cache, v_cache,
                    use_pallas=use_pallas, mesh=mesh, use_ring=use_ring,
                )

            self._fns[key] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                step,
                donate_argnums=(5, 6),
                out_shardings=(self._rep, self._cache_sh, self._cache_sh),
            )
        return self._fns[key]

    def _verify_fn(self, n_spec: int, use_pallas: bool = False,
                   penalized: bool = False, with_logprobs: bool = False):
        """Speculative verify as a mirrored program (spec decode composes
        with multi-host — VERDICT r2 #4)."""
        key = ("verify", n_spec, use_pallas, penalized, with_logprobs)
        if key not in self._fns:
            import jax

            from ..models import llama

            cfg = self.model_cfg
            mesh = self.mesh

            out_sh = [self._rep, self._rep, self._cache_sh, self._cache_sh]
            if penalized:
                out_sh.append(self._rep)
            if with_logprobs:
                out_sh.append((self._rep, self._rep, self._rep))
            out_sh = tuple(out_sh)

            if penalized:

                def step(params, tokens, proposals, positions, tables,
                         seq_lens, seeds, steps, temps, top_ks, top_ps,
                         freq, pres, rep, k_cache, v_cache, counts,
                         prompt_mask):
                    return llama.verify_window.__wrapped__(
                        params, cfg, tokens, proposals, positions, tables,
                        seq_lens, seeds, steps, temps, top_ks, top_ps,
                        k_cache, v_cache, n_spec=n_spec,
                        use_pallas=use_pallas, mesh=mesh,
                        freq_pens=freq, pres_pens=pres, rep_pens=rep,
                        counts=counts, prompt_mask=prompt_mask,
                        with_logprobs=with_logprobs,
                    )

                self._fns[key] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                    step, donate_argnums=(14, 15, 16), out_shardings=out_sh
                )
            else:

                def step(params, tokens, proposals, positions, tables,
                         seq_lens, seeds, steps, temps, top_ks, top_ps,
                         k_cache, v_cache):
                    return llama.verify_window.__wrapped__(
                        params, cfg, tokens, proposals, positions, tables,
                        seq_lens, seeds, steps, temps, top_ks, top_ps,
                        k_cache, v_cache, n_spec=n_spec,
                        use_pallas=use_pallas, mesh=mesh,
                        with_logprobs=with_logprobs,
                    )

                self._fns[key] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                    step, donate_argnums=(11, 12), out_shardings=out_sh
                )
        return self._fns[key]

    def lead_verify(self, params, window, proposals, positions, tables,
                    seq_lens, seeds, steps, temps, top_ks, top_ps,
                    k_cache, v_cache, n_spec: int, use_pallas: bool = False,
                    penalties=None, pen_state=None,
                    with_logprobs: bool = False):
        """Mirror one speculative verify. Returns host (tokens, n_acc)
        plus device (k, v[, counts][, lp arrays])."""
        import jax

        penalized = penalties is not None
        head_arrays = [window, proposals, positions, tables, seq_lens,
                       seeds, steps, temps, top_ks, top_ps]
        if penalized:
            head_arrays += [np.asarray(a, np.float32) for a in penalties]
        self._lead("verify", tuple(head_arrays),
                   n=n_spec, pallas=use_pallas, penalized=penalized,
                   lp=with_logprobs)
        fn = self._verify_fn(n_spec, use_pallas, penalized, with_logprobs)
        base = [params] + self.place_inputs("verify", head_arrays)
        if penalized:
            out = fn(*base, k_cache, v_cache, pen_state[0], pen_state[1])
        else:
            out = fn(*base, k_cache, v_cache)
        toks = np.asarray(jax.device_get(out[0]))
        n_acc = np.asarray(jax.device_get(out[1]))
        rest = list(out[4:])
        lp_host = None
        if with_logprobs:
            lp_dev = rest.pop(-1)
            lp_host = tuple(
                np.asarray(a.addressable_data(0)) for a in lp_dev
            )
        result = [toks, n_acc, out[2], out[3]] + rest
        if with_logprobs:
            result.append(lp_host)
        return tuple(result)

    def _sample1_fn(self):
        if "sample1" not in self._fns:
            import jax

            from ..ops.sampling import make_keys, sample_first_token

            def step(logits, seed, step_no, temp, top_k, top_p,
                     freq, pres, rep, prompt_ids, gen_ids):
                keys = make_keys(seed, step_no)
                return sample_first_token(
                    logits[None, :], keys, temp, top_k, top_p,
                    freq, pres, rep, prompt_ids, gen_ids,
                )

            self._fns["sample1"] = jax.jit(step, out_shardings=self._rep)  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
        return self._fns["sample1"]

    # ---- KV block movement (offload tier + disagg transfer) ----

    def _kv_gather_fn(self, replicated_out: bool):
        """Gather [n] block indices out of the paged cache. Sharded output
        keeps the cache's layout (offload: each process parks its own
        shards in host DRAM); replicated output all-gathers (disagg
        extract: the leader ships full blocks over the transfer plane)."""
        key = ("kv_gather", replicated_out)
        if key not in self._fns:
            import jax

            from ..engine.offload import gather_blocks_core

            out = self._rep if replicated_out else self._stack_sh
            self._fns[key] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                gather_blocks_core, out_shardings=(out, out)
            )
        return self._fns[key]

    def _kv_scatter_fn(self):
        """Scatter a block stack into cache pages (donated). Serves both
        the offload restore (stack sharded like the cache) and the disagg
        remote-KV landing (stack replicated from broadcast host data) —
        jit specializes per input sharding."""
        if "kv_scatter" not in self._fns:
            import jax

            from ..engine.offload import scatter_blocks_core

            self._fns["kv_scatter"] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                scatter_blocks_core,
                donate_argnums=(0, 1),
                out_shardings=(self._cache_sh, self._cache_sh),
            )
        return self._fns["kv_scatter"]

    @property
    def _stack_sh(self):
        """[L, Hkv, n, bs, D] block-stack sharding == the cache's spec
        (the block axis is never sharded)."""
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._cache_sh.spec)

    def _stack_devices(self) -> list:
        """This process's devices of the block-stack sharding, in the
        stable order the piece helpers agree on."""
        return sorted(
            self._stack_sh.addressable_devices, key=lambda d: d.id
        )

    def _piece_map(self, global_shape) -> list[tuple]:
        """[(device, piece_key)] for this process's devices. The key is
        the device's global index range on the two shardable stack axes
        (layer, kv-head) — devices that replicate a shard (e.g. along dp)
        share a key, so host copies are stored ONCE per distinct shard,
        not once per device."""
        m = self._stack_sh.devices_indices_map(tuple(global_shape))
        out = []
        for d in self._stack_devices():
            idx = m[d]
            key = tuple(
                (s.start or 0, s.stop if s.stop is not None else dim)
                for s, dim in zip(idx[:2], global_shape[:2])
            )
            out.append((d, key))
        return out

    def local_pieces(self, arr) -> list[np.ndarray]:
        """Unique host copies of this process's shards of a global array,
        in canonical key order (the layout pieces_to_global reverses)."""
        shards = {s.device.id: s for s in arr.addressable_shards}
        pieces: dict = {}
        for d, key in self._piece_map(arr.shape):
            if key not in pieces:
                pieces[key] = np.asarray(shards[d.id].data)
        return [pieces[k] for k in sorted(pieces)]

    def pieces_to_global(self, pieces: list[np.ndarray], global_shape):
        """Rebuild a stack-sharded global array from this process's
        unique host pieces (every process calls this with ITS pieces).
        Replicating devices re-use the same host array."""
        import jax

        pm = self._piece_map(global_shape)
        keys = sorted({k for _d, k in pm})
        by_key = dict(zip(keys, pieces))
        arrs = [jax.device_put(by_key[k], d) for d, k in pm]
        return jax.make_array_from_single_device_arrays(
            tuple(global_shape), self._stack_sh, arrs
        )

    def lead_offload_flush(self, k_cache, v_cache, idxs, hashes, keep,
                           drop_hashes):
        """Mirror an offload-tier flush: every process gathers the evicted
        blocks (cache-sharded output) and parks ITS local shards in host
        DRAM. ``hashes`` aligns with the gathered stack positions;
        ``keep`` flags which survive the leader's LRU plan and
        ``drop_hashes`` are its evictions — followers apply the plan
        verbatim instead of running their own policy."""
        self._lead(
            "offload_flush",
            (np.asarray(idxs, np.int32),
             np.asarray(hashes, np.uint64),
             np.asarray(keep, np.uint8),
             np.asarray(drop_hashes, np.uint64)),
        )
        return self._kv_gather_fn(False)(
            k_cache, v_cache, self.to_global(np.asarray(idxs, np.int32))
        )

    def lead_offload_restore(self, k_cache, v_cache, idxs, take_hashes,
                             k_pieces, v_pieces, k_shape, v_shape,
                             drop_hashes=()):
        """Mirror an offload-tier restore: every process rebuilds the
        sharded block stacks from its own host pieces and runs the same
        scatter. k/v global shapes are passed separately — MLA's latent
        caches have different trailing dims. ``drop_hashes`` piggybacks
        deferred follower-tier drops (leader-side unreserve evictions,
        see OffloadManager)."""
        self._lead(
            "offload_restore",
            (np.asarray(idxs, np.int32),
             np.asarray(take_hashes, np.uint64),
             np.asarray(list(drop_hashes), np.uint64)),
        )
        kg = self.pieces_to_global(k_pieces, k_shape)
        vg = self.pieces_to_global(v_pieces, v_shape)
        return self._kv_scatter_fn()(
            k_cache, v_cache, self.to_global(np.asarray(idxs, np.int32)),
            kg, vg,
        )

    def lead_kv_gather_full(self, k_cache, v_cache, idxs):
        """Disagg prefill extract under mirror: all-gather the blocks to a
        replicated stack; the leader reads its local copy and ships it over
        the KV transfer plane (host numpy out)."""
        import jax

        self._lead("kv_gather_full", (np.asarray(idxs, np.int32),))
        kg, vg = self._kv_gather_fn(True)(
            k_cache, v_cache, self.to_global(np.asarray(idxs, np.int32))
        )
        return (
            np.asarray(jax.device_get(kg.addressable_data(0))),
            np.asarray(jax.device_get(vg.addressable_data(0))),
        )

    def lead_kv_scatter(self, k_cache, v_cache, idxs, k_host, v_host):
        """Disagg remote-KV landing under mirror: broadcast the host block
        stack to every process; all scatter it into their cache shards."""
        self._lead(
            "kv_scatter",
            (np.asarray(idxs, np.int32), np.asarray(k_host),
             np.asarray(v_host)),
        )
        g = self.to_global
        return self._kv_scatter_fn()(
            k_cache, v_cache, g(np.asarray(idxs, np.int32)),
            g(np.asarray(k_host)), g(np.asarray(v_host)),
        )

    # ---- broadcast plumbing ----

    def _bcast_frame(self, payload: Optional[bytes]) -> bytes:
        """One fixed-size broadcast: [4B length][payload][zero pad]."""
        from jax.experimental import multihost_utils

        buf = np.zeros(_FRAME_BYTES, np.uint8)
        if self.is_leader:
            if len(payload) + 4 > _FRAME_BYTES:
                raise ValueError(
                    f"frame payload {len(payload)}B exceeds {_FRAME_BYTES}"
                )
            buf[:4] = np.frombuffer(struct.pack("<I", len(payload)), np.uint8)
            buf[4 : 4 + len(payload)] = np.frombuffer(payload, np.uint8)
        # newer jax broadcasts through a psum whose type promotion can
        # return the uint8 frame as uint32 (values intact, one byte per
        # element) — cast back before reinterpreting as wire bytes
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf)).astype(
            buf.dtype, copy=False
        )
        (ln,) = struct.unpack("<I", bytes(out[:4]))
        return bytes(out[4 : 4 + ln])

    def _bcast_arrays(self, arrays: tuple) -> tuple:
        from jax.experimental import multihost_utils

        # cast each result back to its input dtype: the collective's
        # psum may promote (uint8 payload buffers come back uint32 on
        # newer jax), and the caller reinterprets raw bytes
        return tuple(
            np.asarray(out).astype(src.dtype, copy=False)
            for out, src in zip(
                multihost_utils.broadcast_one_to_all(arrays), arrays
            )
        )

    def _lead(self, op: str, arrays: tuple[np.ndarray, ...], **extra) -> None:
        """Leader: announce an op + ship its host inputs to followers.

        Arrays travel as raw bytes with logical dtype NAMES in the header
        — the collectives never see the element type, so uint64 block
        hashes (x64 is off) and bfloat16 KV data (numpy void dtype)
        broadcast losslessly alongside the int32/float32 step inputs.
        Small ops (the decode hot loop) inline the payload into the one
        header frame; oversized payloads take a second exact-size round."""
        arrays = tuple(np.asarray(a) for a in arrays)
        blobs = [a.tobytes() for a in arrays]
        head = {
            "op": op,
            "shapes": [list(a.shape) for a in arrays],
            "dtypes": [str(a.dtype) for a in arrays],
            **extra,
        }
        total = sum(len(b) for b in blobs)
        hdr = json.dumps({**head, "inline": True}).encode()
        if 4 + len(hdr) + 4 + total <= _FRAME_BYTES:
            self._bcast_frame(
                struct.pack("<I", len(hdr)) + hdr + b"".join(blobs)
            )
            return
        hdr = json.dumps({**head, "inline": False}).encode()
        self._bcast_frame(struct.pack("<I", len(hdr)) + hdr)
        self._bcast_arrays(
            tuple(np.frombuffer(b, np.uint8) for b in blobs)
        )

    @staticmethod
    def _np_dtype(name: str):
        try:
            return np.dtype(name)
        except TypeError:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name))

    def follow(self) -> tuple[dict, tuple[np.ndarray, ...]]:
        """Follower: receive the next (header, host inputs)."""
        frame = self._bcast_frame(None)
        (hlen,) = struct.unpack("<I", frame[:4])
        head = json.loads(frame[4 : 4 + hlen].decode())
        dts = [self._np_dtype(d) for d in head["dtypes"]]
        sizes = [
            int(np.prod(s)) * dt.itemsize
            for s, dt in zip(head["shapes"], dts)
        ]
        if head["inline"]:
            body = frame[4 + hlen :]
            out, off = [], 0
            for s, dt, size in zip(head["shapes"], dts, sizes):
                out.append(np.frombuffer(body[off : off + size], dt).reshape(s))
                off += size
            return head, tuple(out)
        bufs = self._bcast_arrays(
            tuple(np.zeros(size, np.uint8) for size in sizes)
        )
        return head, tuple(
            np.frombuffer(b.tobytes(), dt).reshape(s)
            for b, dt, s in zip(bufs, dts, head["shapes"])
        )

    # ---- leader-side dispatch (called from JaxEngine) ----

    def lead_pen_reset(self, slot: int, prompt_ids, gen_ids) -> None:
        """Mirror a penalty-state slot rebuild: followers apply the same
        deterministic reset so their [B, V] counts/mask device state stays
        identical to the leader's through every subsequent window."""
        self._lead(
            "pen_reset",
            (np.asarray(prompt_ids, np.int32), np.asarray(gen_ids, np.int32)),
            slot=slot,
        )

    def _slice_last_fn(self):
        """toks [n, B] -> toks[-1] as a compiled slice (eager indexing on
        a multi-process array is illegal; this keeps window chaining on
        device)."""
        if "slice_last" not in self._fns:
            import jax

            self._fns["slice_last"] = jax.jit(  # dynlint: disable=jit-in-function -- memoized: compiled once per static key
                lambda t: t[-1], out_shardings=self._rep
            )
        return self._fns["slice_last"]

    def lead_decode(self, params, last_tokens, positions, tables, seq_lens,
                    seeds, steps, temps, top_ks, top_ps, k_cache, v_cache,
                    n_steps: int = 1, use_pallas: bool = False,
                    unroll: bool = True, merged: bool = True,
                    penalties=None, pen_state=None,
                    with_logprobs: bool = False,
                    tokens_dev=None, sync: bool = True):
        """``penalties`` = (freq, pres, rep) host vectors; ``pen_state`` =
        (counts, prompt_mask) device arrays (leader's copy — followers
        hold their own mirrored state). Returns (tokens, k, v[, counts,
        logprob arrays]).

        ``tokens_dev`` chains a pipelined window: the token input is the
        previous window's [n, B] device output (sliced on device), the
        broadcast ``last_tokens`` is a placeholder, and followers use
        THEIR retained previous output (header flag ``chain``).
        ``sync=False`` returns the [n, B] replicated device array instead
        of host tokens — the leader materializes at emission, so dispatch
        of window k+1 overlaps window k's execution."""
        import jax

        penalized = penalties is not None
        chain = tokens_dev is not None
        head_arrays = [last_tokens, positions, tables, seq_lens,
                       seeds, steps, temps, top_ks, top_ps]
        if penalized:
            head_arrays += [np.asarray(a, np.float32) for a in penalties]
        self._lead("decode", tuple(head_arrays),
                   n=n_steps, pallas=use_pallas, unroll=unroll,
                   merged=merged, penalized=penalized, lp=with_logprobs,
                   chain=chain)
        fn = self._decode_fn(
            n_steps, use_pallas, unroll, merged, penalized, with_logprobs
        )
        placed = self.place_inputs(
            "decode", head_arrays, skip=(0,) if chain else ()
        )
        if chain:
            placed[0] = self._slice_last_fn()(tokens_dev)
        if penalized:
            out = fn(params, *placed, k_cache, v_cache,
                     pen_state[0], pen_state[1])
        else:
            out = fn(params, *placed, k_cache, v_cache)
        toks = out[0] if not sync else np.asarray(
            out[0].addressable_data(0)
        )
        return (toks,) + tuple(out[1:])

    def lead_prefill(self, params, toks, table, pos, valid, k_cache, v_cache,
                     use_pallas: bool = False, use_ring: bool = False):
        """``use_ring`` mirrors a sequence-parallel ring-attention prefill
        chunk over the mesh's sp axis (long-context x multi-host: the
        shard_map ring's ppermute hops ride ICI within a host and DCN
        across — the engine gates on sp>1 + history-free chunks)."""
        self._lead(
            "prefill",
            (toks, np.asarray(table),
             np.asarray(pos, np.int32), np.asarray(valid, np.int32)),
            pallas=use_pallas, ring=use_ring,
        )
        g = self.to_global
        return self._prefill_fn(use_pallas, use_ring)(
            params, g(toks), g(np.asarray(table)),
            g(np.asarray(pos, np.int32)), g(np.asarray(valid, np.int32)),
            k_cache, v_cache,
        )

    def lead_sample1(self, logits, seed, step_no, temp, top_k, top_p,
                     freq=0.0, pres=0.0, rep=1.0,
                     prompt_ids=None, gen_ids=None) -> int:
        arrays = (
            np.asarray([seed], np.int32), np.asarray([step_no], np.int32),
            np.asarray([temp], np.float32), np.asarray([top_k], np.int32),
            np.asarray([top_p], np.float32),
            np.asarray([freq], np.float32), np.asarray([pres], np.float32),
            np.asarray([rep], np.float32),
            np.asarray(
                prompt_ids if prompt_ids is not None else [2**31 - 1],
                np.int32,
            ),
            np.asarray(
                gen_ids if gen_ids is not None else [2**31 - 1], np.int32
            ),
        )
        self._lead("sample1", arrays)
        g = self.to_global
        tok = self._sample1_fn()(logits, *(g(a) for a in arrays))
        return int(np.asarray(tok.addressable_data(0))[0])

    def lead_halt(self) -> None:
        self._lead("halt", ())


def run_follower(engine_cfg, params: Optional[dict] = None, seed: int = 0) -> None:
    """Follower main loop: replay the leader's device dispatches forever
    (until a ``halt`` op). ``engine_cfg`` is the same EngineConfig the
    leader's JaxEngine was built with; params must be initialized the same
    way on every rank (same seed, or same checkpoint path)."""
    import jax
    import jax.numpy as jnp

    from ..models import llama

    from ..models.quant import kv_cache_dtype, quantize_params

    mcfg = engine_cfg.model
    mesh = global_mesh(engine_cfg.mesh)
    mirror = StepMirror(mesh, mcfg)
    if params is None:
        params = llama.init_params(mcfg, jax.random.key(seed))
    # same quantization as the leader: the mirrored jits must compile the
    # identical program on identically-typed params
    params = quantize_params(params, mcfg, engine_cfg.quantization,
                             experts=engine_cfg.quant_experts)
    params = mirror.shard_params(params)
    k_cache, v_cache = mirror.init_cache(
        engine_cfg.num_blocks, engine_cfg.block_size,
        dtype=kv_cache_dtype(mcfg, engine_cfg.kv_cache_dtype),
    )
    logits = None
    pen_counts = pen_mask = None  # mirrored sampling-penalty state
    last_decode_toks = None  # previous decode window's [n, B] output
    # (chained-window token source when the leader pipelines dispatches)
    # follower half of the host offload tier: seq_hash -> per-local-device
    # (k_pieces, v_pieces). Content mirrors the leader's HostKvPool — every
    # mutation arrives as an explicit store/drop/take in a mirrored op, so
    # the follower runs no eviction policy of its own.
    host_tier: dict[int, tuple[list, list]] = {}
    logger.info("follower %d ready", jax.process_index())
    while True:
        head, arrays = mirror.follow()
        op = head["op"]
        g = mirror.to_global
        if op == "halt":
            logger.info("follower %d halting", jax.process_index())
            return
        if op == "pen_reset":
            if pen_counts is None:
                V = mcfg.vocab_size
                B = engine_cfg.max_batch_size
                pen_counts = g(np.zeros((B, V), np.int32))
                pen_mask = g(np.zeros((B, V), bool))
            from ..engine.engine import _reset_pen_slot

            prompt_ids, gen_ids = arrays
            pen_counts, pen_mask = _reset_pen_slot(
                pen_counts, pen_mask, head["slot"],
                g(prompt_ids), g(gen_ids),
            )
        elif op == "decode":
            penalized = head.get("penalized", False)
            fn = mirror._decode_fn(head.get("n", 1), head.get("pallas", False),
                                   head.get("unroll", True),
                                   head.get("merged", True),
                                   penalized, head.get("lp", False))
            chain = head.get("chain", False)
            placed = mirror.place_inputs(
                "decode", arrays, skip=(0,) if chain else ()
            )
            if chain:
                placed[0] = mirror._slice_last_fn()(last_decode_toks)
            if penalized:
                out = fn(params, *placed, k_cache, v_cache,
                         pen_counts, pen_mask)
                k_cache, v_cache, pen_counts = out[1], out[2], out[3]
            else:
                out = fn(params, *placed, k_cache, v_cache)
                k_cache, v_cache = out[1], out[2]
            last_decode_toks = out[0]
        elif op == "verify":
            penalized = head.get("penalized", False)
            fn = mirror._verify_fn(head.get("n", 1),
                                   head.get("pallas", False),
                                   penalized, head.get("lp", False))
            placed = mirror.place_inputs("verify", arrays)
            if penalized:
                # a penalized verify can only follow a pen_reset op (the
                # engine broadcasts one when the first penalized request
                # is admitted) — anything else is a protocol bug
                assert pen_counts is not None, (
                    "penalized verify before any pen_reset"
                )
                out = fn(params, *placed, k_cache, v_cache,
                         pen_counts, pen_mask)
                k_cache, v_cache, pen_counts = out[2], out[3], out[4]
            else:
                out = fn(params, *placed, k_cache, v_cache)
                k_cache, v_cache = out[2], out[3]
        elif op == "prefill":
            logits, k_cache, v_cache = mirror._prefill_fn(
                head.get("pallas", False), head.get("ring", False)
            )(params, *(g(a) for a in arrays), k_cache, v_cache)
        elif op == "sample1":
            mirror._sample1_fn()(logits, *(g(a) for a in arrays))
        elif op == "offload_flush":
            idxs, hashes, keep, drop_hashes = arrays
            kg, vg = mirror._kv_gather_fn(False)(k_cache, v_cache, g(idxs))
            k_pc, v_pc = mirror.local_pieces(kg), mirror.local_pieces(vg)
            for h in drop_hashes.tolist():
                host_tier.pop(h, None)
            for i, h in enumerate(hashes.tolist()):
                if not keep[i] or h in host_tier:
                    continue
                host_tier[h] = (
                    [p[:, :, i].copy() for p in k_pc],
                    [p[:, :, i].copy() for p in v_pc],
                )
        elif op == "offload_restore":
            from ..engine.offload import stack_pieces

            idxs, take_hashes, drop_hashes = arrays
            for h in drop_hashes.tolist():
                host_tier.pop(h, None)
            entries = [host_tier.pop(h) for h in take_hashes.tolist()]
            k_pieces = stack_pieces(entries, 0)
            v_pieces = stack_pieces(entries, 1)

            # global stack shape = cache dims with the block axis =
            # the UNPADDED entry count (the scatter core pads on
            # device); k/v differ for MLA's latent caches
            def gs(cache):
                return (cache.shape[0], cache.shape[1], len(entries),
                        cache.shape[3], cache.shape[4])

            k_cache, v_cache = mirror._kv_scatter_fn()(
                k_cache, v_cache, g(idxs),
                mirror.pieces_to_global(k_pieces, gs(k_cache)),
                mirror.pieces_to_global(v_pieces, gs(v_cache)),
            )
        elif op == "kv_gather_full":
            (idxs,) = arrays
            mirror._kv_gather_fn(True)(k_cache, v_cache, g(idxs))
        elif op == "kv_scatter":
            idxs, k_host, v_host = arrays
            k_cache, v_cache = mirror._kv_scatter_fn()(
                k_cache, v_cache, g(idxs), g(k_host), g(v_host)
            )
        else:
            raise RuntimeError(f"unknown mirrored op {op!r}")

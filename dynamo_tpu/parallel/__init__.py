"""Parallelism: device meshes, sharding rules, multi-host init.

TPU-native replacement for the reference's engine-delegated parallelism
(Ray/MPI/torch.distributed bootstraps, SURVEY.md section 2.8): a
jax.sharding.Mesh with named axes + NamedSharding placement rules; XLA SPMD
inserts all collectives.
"""

from .mesh import MeshConfig, cache_sharding, make_mesh, param_sharding, shard_params

__all__ = ["MeshConfig", "cache_sharding", "make_mesh", "param_sharding", "shard_params"]

"""Parallelism: device meshes, sharding rules, multi-host init.

TPU-native replacement for the reference's engine-delegated parallelism
(Ray/MPI/torch.distributed bootstraps, SURVEY.md section 2.8): a
jax.sharding.Mesh with named axes + NamedSharding placement rules; XLA SPMD
inserts all collectives. ``LogicalLayout`` carries the placement rules
mesh-free (resolved at dispatch) and ``MeshMorpher`` compiles the
cross-mesh permutations that move live state between layouts
(docs/elastic_resharding.md).
"""

from .mesh import (
    LogicalLayout,
    MeshConfig,
    cache_sharding,
    make_mesh,
    param_sharding,
    shard_params,
)
from .morph import MeshMorpher

__all__ = [
    "LogicalLayout",
    "MeshConfig",
    "MeshMorpher",
    "cache_sharding",
    "make_mesh",
    "param_sharding",
    "shard_params",
]

"""Staged pipeline-parallel prefill: microbatches + ppermute over ``pp``.

The stacked-layer weight sharding (parallel/mesh.py: every [L, ...] param
leads with a ``pp``-sharded layer axis) distributes *memory*; this module
adds distributed *execution*: a GPipe-style schedule where prefill
microbatches flow through the pipeline stages over ICI, so all ``pp``
stages compute concurrently instead of all-gathering one stage's weights
per scan step. (The reference leans on its engines' Megatron-style PP for
the same role; TPU-native it is a shard_map + collective-permute loop —
"How to Scale Your Model"'s pipelining recipe.)

Schedule: ``m`` microbatches of ``T/m`` tokens, ``m + pp - 1`` ticks. At
tick ``t`` stage ``s`` processes microbatch ``t - s`` (when in range):
runs its local layer block (a scan over L/pp layers against its local
KV-cache shard — cache_sharding puts the layer axis on ``pp``, so stage
KV is resident), then hands the activations to stage ``s+1`` via
``lax.ppermute``. Stage 0 injects embeddings; the last stage collects
hidden states. Causality across microbatches comes for free: microbatch
``j`` passes stage ``s`` strictly before ``j+1`` arrives there, and its
K/V are already scattered into the stage-local cache (write-before-attend,
same invariant as llama.prefill).

The shard_map is fully manual over the mesh, so tensor parallelism is
carried explicitly Megatron-style inside each stage: column-parallel
qkv/gate/up (local head / hidden shards), head-parallel attention on the
tp-sharded kv cache, row-parallel wo/down with a psum over ``tp``. MoE
models keep the existing scan path (expert dispatch inside a manual
pipeline is a follow-up), as do shapes that don't divide evenly.

Inactive ticks compute on garbage but scatter through an all-zeros block
table, i.e. into the sacrificial trash block 0 — never-read by masking,
the allocator's existing convention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.config import ModelConfig
from ..ops._pallas_compat import shard_map
from .mesh import spec_tree


MIN_MICRO_TOKENS = 32  # below this, microbatch matmuls waste the MXU


def pick_n_micro(mesh, T: int) -> int:
    """More microbatches shrink the pipeline bubble — fraction
    (pp-1)/(n_micro+pp-1) — so prefer the largest multiple of pp that
    still leaves MXU-worthy microbatches. Returns 0 when no multiple
    meets the floor: the chunk is too small to pipeline profitably and
    the caller should keep the scan path."""
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    for mult in (8, 4, 2, 1):
        cand = mult * pp
        if T % cand == 0 and T // cand >= MIN_MICRO_TOKENS:
            return cand
    return 0


def can_pipeline(mesh, cfg: ModelConfig, T: int, n_micro: int) -> bool:
    if mesh is None or "pp" not in mesh.axis_names or "tp" not in mesh.axis_names:
        return False
    pp = mesh.shape["pp"]
    tp = mesh.shape["tp"]
    return (
        pp > 1
        and not cfg.is_moe
        and not cfg.is_mla  # MLA runs the absorbed-latent scan path
        # per-layer windows (gpt-oss) need an unrolled layer loop; the
        # pipeline's scanned stage body is homogeneous and sink-less
        and not cfg.layer_windows
        and not cfg.attn_sinks
        # gemma-2 softcap/sandwich norms live in the XLA unrolled paths
        and not cfg.attn_softcap
        and not cfg.post_norms
        and not cfg.norm_after
        and cfg.num_layers % pp == 0
        and n_micro >= 1
        and T % n_micro == 0
        and n_micro % pp == 0
        and (tp == 1 or (cfg.num_kv_heads % tp == 0 and cfg.num_heads % tp == 0
                         and cfg.intermediate_size % tp == 0))
    )


def _layers_specs(layers: dict) -> dict:
    """Full-manual in_specs for the layers subtree: exactly the placement
    rules' spec walk (incl. derived q/s specs of quantized leaves)."""
    return spec_tree(layers, "layers")


def pipelined_prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [T] padded chunk
    block_table: jnp.ndarray,  # [M]
    history_len: jnp.ndarray,  # scalar int32
    valid_len: jnp.ndarray,  # scalar int32
    k_cache: jnp.ndarray,  # [L, Hkv, N, bs, D]; layer axis pp-, head tp-sharded
    v_cache: jnp.ndarray,
    mesh,
    n_micro: int,
    use_pallas: bool = False,
):
    """Drop-in for llama.prefill's layer loop on a pp>1 mesh. Returns
    (last-token logits [V], k_cache, v_cache)."""
    from ..models import llama
    from ..ops import attention as att

    pp = mesh.shape["pp"]
    tp = mesh.shape["tp"]
    T = tokens.shape[0]
    Tm = T // n_micro
    D = cfg.head_dim
    inv_freq = llama._rope_freqs(cfg)
    scale = D**-0.5

    # embeddings + final norm/head run under GSPMD outside the stage loop
    from ..models.llama import _embed

    x_all = _embed(params, cfg, tokens).reshape(n_micro, Tm, -1)
    h_ax = "tp" if cfg.num_kv_heads % tp == 0 else None
    cache_spec = P("pp", h_ax, None, None, None)

    def stages(layers_local, kc_l, vc_l, x_all, table, hist, valid):
        s = lax.axis_index("pp")
        zero_table = jnp.zeros_like(table)  # trash-block writes when idle

        def stage_block(x, mb_idx, kc_l, vc_l, active):
            """This stage's L/pp layers on one microbatch (tp-local
            shards; row-parallel projections psum over tp).

            NOTE: this mirrors llama.prefill's layer body with the tp
            reductions made explicit (llama._qkv is shared — it derives
            head counts from the shard width); any change to the llama
            layer body must be applied here too."""
            start = hist + mb_idx * Tm
            positions = start + jnp.arange(Tm)
            mb_valid = jnp.clip(valid - mb_idx * Tm, 0, Tm)
            tbl = jnp.where(active, table, zero_table)

            def body(x, layer_in):
                lp, kc, vc = layer_in
                h = llama.rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
                q, k, v = llama._qkv(lp, cfg, h)
                q = llama.apply_rope(q, positions, inv_freq)
                k = llama.apply_rope(k, positions, inv_freq)
                kc = att.write_chunk_to_cache(kc, k, tbl, start)
                vc = att.write_chunk_to_cache(vc, v, tbl, start)
                o = att.chunk_attention_with_cache(
                    q, k, v, kc, vc, tbl, start, mb_valid, scale,
                    use_pallas=use_pallas, window=cfg.sliding_window,
                )
                x = x + lax.psum(llama._mm(o.reshape(Tm, -1), lp["wo"]), "tp")
                h = llama.rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
                gate = llama._mm(h, lp["w_gate"])
                gate = (
                    jax.nn.gelu(gate, approximate=True)
                    if cfg.hidden_act == "gelu_tanh" else jax.nn.silu(gate)
                )
                up = gate * llama._mm(h, lp["w_up"])
                x = x + lax.psum(llama._mm(up, lp["w_down"]), "tp")
                return x, (kc, vc)

            x, (kc_l, vc_l) = lax.scan(body, x, (layers_local, kc_l, vc_l))
            return x, kc_l, vc_l

        def tick(t, carry):
            x_cur, kc_l, vc_l, out = carry
            mb = t - s  # this stage's microbatch index this tick
            active = (mb >= 0) & (mb < n_micro)
            mb_c = jnp.clip(mb, 0, n_micro - 1)
            # stage 0 reads its input fresh from the embeddings
            inject = x_all[jnp.clip(t, 0, n_micro - 1)]
            x_in = jnp.where(s == 0, inject, x_cur)
            y, kc_l, vc_l = stage_block(x_in, mb_c, kc_l, vc_l, active)
            # last stage collects its finished microbatch
            out = lax.dynamic_update_index_in_dim(
                out,
                jnp.where(active & (s == pp - 1), y, out[mb_c]),
                mb_c,
                axis=0,
            )
            # hand activations downstream (ring permute; the wraparound
            # edge feeds stage 0, which ignores it and re-injects)
            x_next = lax.ppermute(y, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return (x_next, kc_l, vc_l, out)

        carry = (x_all[0], kc_l, vc_l, jnp.zeros_like(x_all))
        _, kc_l, vc_l, out = lax.fori_loop(0, n_micro + pp - 1, tick, carry)
        # finished hidden states live on the last stage; replicate them
        out = lax.psum(jnp.where(s == pp - 1, out, 0.0), "pp")
        return out, kc_l, vc_l

    x_out, k_cache, v_cache = shard_map(
        stages,
        mesh=mesh,
        in_specs=(
            _layers_specs(params["layers"]), cache_spec, cache_spec,
            P(), P(), P(), P(),
        ),
        out_specs=(P(), cache_spec, cache_spec),
        check_vma=False,
    )(params["layers"], k_cache, v_cache, x_all, block_table,
      jnp.asarray(history_len, jnp.int32), jnp.asarray(valid_len, jnp.int32))

    x_flat = x_out.reshape(T, -1)
    x_flat = llama.rms_norm(x_flat, params["final_norm"], cfg.rms_norm_eps)
    last = jnp.clip(valid_len - 1, 0, T - 1)
    logits = llama._logits(params, cfg, x_flat[last])
    return logits, k_cache, v_cache

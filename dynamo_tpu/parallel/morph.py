"""Compiled cross-mesh permutation programs — the one mover for every
"this array lives on layout A, I need it on layout B" moment.

Grown out of PR 11's ``IciSegmentMover``, which kept a private memo of
jitted ``out_shardings`` reshards for the disagg handoff's segment
geometry buckets. Elastic resharding (docs/elastic_resharding.md) needs
the same machinery for whole weight pytrees and the paged KV pool, so
the program construction and the memo live here now and every consumer
(the ICI segment mover, ``JaxEngine.reshard``) shares one rule set:

* **permute** — source and destination describe the same single-axis
  split onto the same devices in the same order (including the
  degenerate replicated / single-device case): an explicit ``shard_map``
  identity over those devices. The collective is the identity
  permutation and the shard_map body structurally forbids a host hop —
  this is the no-op-priced common case, kept separate so tests can
  assert the cheap path was taken.
* **reshard** — anything richer (a TP regroup, a PP re-stage, shards in
  a different device order, a grown/shrunk device set): a jitted
  identity with ``out_shardings``, the one re-layout API XLA lowers to
  the slice's own collective_permute / all-gather over ICI. On
  toolchains where the jitted cross-device-set form is rejected, the
  program degrades to ``jax.device_put`` (same bytes-level result, XLA
  still picks direct device→device paths where they exist) and the
  degraded program is memoized so the failed jit is never retried.
* **place** — destination ``None`` (an unsharded engine): a plain
  ``device_put`` onto the process default device.

Programs are memoized by (shape, dtype, src sharding, dst sharding).
Callers that stream varying geometries (the segment mover) bucket their
shapes BEFORE calling, so the memo stays bounded by geometry buckets —
the ``test_compiled_perf`` contract.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def one_axis_split(sharding, shape) -> Optional[tuple[int, list]]:
    """Describe ``sharding`` over ``shape`` as an even split of at most
    ONE array axis across its devices: returns (axis, devices in shard
    order) — axis -1 when every device holds the whole array
    (replicated / single device). None for anything richer (multi-axis
    splits take the reshard program instead)."""
    if sharding is None:
        return None
    try:
        idx_map = sharding.devices_indices_map(tuple(shape))
    except Exception:  # noqa: BLE001 — exotic sharding
        return None
    split_axis = None
    keyed = []
    for d, idx in idx_map.items():
        axes = [
            a for a, s in enumerate(idx)
            if not (s.start in (0, None) and s.stop in (None, shape[a]))
        ]
        if len(axes) > 1:
            return None
        if axes:
            a = axes[0]
            if split_axis is None:
                split_axis = a
            elif split_axis != a:
                return None
            keyed.append((idx[a].start or 0, d))
        else:
            keyed.append((0, d))
    if split_axis is None:
        return -1, sorted((d for _s, d in keyed), key=lambda d: d.id)
    keyed.sort(key=lambda t: t[0])
    starts = [s for s, _d in keyed]
    if len(set(starts)) != len(starts):
        return None  # partial replication inside the split
    return split_axis, [d for _s, d in keyed]


class MeshMorpher:
    """Memoized cross-mesh movers (module doc). One instance per
    consumer scope — the decode sink's segment mover owns one, the
    engine's reshard owns one — but all instances build programs by the
    same rules, so the permute/reshard split can't drift between the
    KV-handoff and live-reshard planes."""

    def __init__(self):
        self._fns: dict = {}
        self.permute_programs = 0
        self.reshard_programs = 0
        #: programs that degraded to device_put (jit rejected the
        #: src→dst pair on this toolchain) + every dst=None placement
        self.place_moves = 0
        self.moved_arrays = 0
        self.moved_bytes = 0

    def programs(self) -> int:
        return len(self._fns)

    # ---- program construction ----

    def _build(self, src_sharding, dst_sharding, shape, dtype):
        """One compiled mover program for this (geometry, src, dst)."""
        from ..ops._pallas_compat import shard_map as _smap

        src = one_axis_split(src_sharding, shape)
        dst = one_axis_split(dst_sharding, shape)
        matched = (
            src is not None and dst is not None and src[0] == dst[0]
            and src[1] == dst[1]
        )
        if not matched:
            self.reshard_programs += 1
            return jax.jit(  # dynlint: disable=jit-in-function -- memoized per geometry in self._fns (apply)
                lambda a: a, out_shardings=dst_sharding
            )
        axis, devs = dst
        mesh = Mesh(devs, ("morph",))
        spec = P() if axis < 0 else P(*([None] * axis), "morph")

        def body(a):
            # identity permutation: shards are already on the devices
            # the destination wants them on — the shard_map is the
            # structural no-host-hop guarantee, not a data move
            return a

        fn = _smap(body, mesh=mesh, in_specs=spec, out_specs=spec)
        self.permute_programs += 1
        return jax.jit(  # dynlint: disable=jit-in-function -- memoized per geometry in self._fns (apply)
            fn, out_shardings=dst_sharding
        )

    # ---- the mover API ----

    def apply(self, x, dst_sharding):
        """Move one array onto ``dst_sharding`` through the memoized
        program for its (shape, dtype, src, dst). ``None`` destination =
        unsharded placement on the default device. Callers with
        streaming geometries bucket/pad BEFORE calling."""
        self.moved_arrays += 1
        self.moved_bytes += int(getattr(x, "nbytes", 0))
        if dst_sharding is None:
            self.place_moves += 1
            return jax.device_put(x, jax.devices()[0])
        src = getattr(x, "sharding", None)
        key = (
            tuple(x.shape), str(x.dtype),
            repr(src) if src is not None else None,
            repr(dst_sharding),
        )
        fn = self._fns.get(key)
        if fn is None:
            fn = self._fns[key] = self._build(
                src, dst_sharding, x.shape, x.dtype
            )
        try:
            return fn(x)
        except (TypeError, ValueError, NotImplementedError):
            # trace/lowering rejection: this toolchain refuses the
            # jitted src→dst pair (e.g. a cross-device-set
            # out_shardings on older jax). Degrade THIS program to
            # device_put PERMANENTLY so the failed trace is never
            # retried per call. Execution errors (XlaRuntimeError, a
            # transient RESOURCE_EXHAUSTED mid-collective) deliberately
            # propagate instead — a one-off runtime failure must not
            # pin this geometry onto the slow host-mediated path for
            # the process lifetime
            logger.debug(
                "mover jit rejected %s -> %s; degrading to device_put",
                src, dst_sharding, exc_info=True,
            )
            self.place_moves += 1
            put = lambda a: jax.device_put(a, dst_sharding)  # noqa: E731
            self._fns[key] = put
            return put(x)

    def apply_tree(self, tree, shardings):
        """Move a params-shaped pytree onto a matching pytree of
        shardings (dict-of-dict leaves, the spec_tree structure)."""
        if isinstance(tree, dict):
            return {
                k: self.apply_tree(v, shardings[k]) for k, v in tree.items()
            }
        return self.apply(tree, shardings)

    def counters(self) -> dict:
        return {
            "morph_programs": self.programs(),
            "morph_permute_programs": self.permute_programs,
            "morph_reshard_programs": self.reshard_programs,
            "morph_place_moves": self.place_moves,
            "morph_moved_arrays": self.moved_arrays,
            "morph_moved_bytes": self.moved_bytes,
        }

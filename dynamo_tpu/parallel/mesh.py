"""Device mesh + sharding placement rules.

The mesh axes (any subset may be 1; all five always exist by name):

  * ``dp``   — data parallel (independent request batches / replicas)
  * ``pp``   — pipeline parallel (layer-stage sharding of the stacked
               [L, ...] parameter arrays — distributes weight memory
               across stages; the scan layer loop slices one stage's
               shard per step)
  * ``sp``   — sequence parallel (ring attention over ICI for
               long-context prefill, parallel/ring_attention.py)
  * ``ep``   — expert parallel (MoE expert axis of we_* weights;
               the combine einsum's contraction over experts becomes
               the all-reduce GSPMD inserts on ICI)
  * ``tp``   — tensor parallel (heads / mlp-hidden / vocab, over ICI)

Megatron-style placement (column-parallel qkv/gate/up, row-parallel
out/down, vocab-parallel embedding + lm_head) expressed purely as
NamedSharding annotations: jit propagates them and XLA SPMD inserts the
reduce-scatter/all-gather/all-reduce the reference gets from NCCL inside
vLLM. KV cache shards its kv-head axis over ``tp``; when tp exceeds the
kv-head count the cache axis is replicated (XLA handles the q-head split).

Multi-host: the same mesh built from jax.devices() spanning hosts (ICI
within a slice, DCN across slices via jax.distributed.initialize) — see
parallel.multihost.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig


@dataclass
class MeshConfig:
    dp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.ep * self.tp


AXES = ("dp", "pp", "sp", "ep", "tp")


def make_mesh(mesh_cfg: Optional[MeshConfig] = None, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if mesh_cfg is None:
        mesh_cfg = MeshConfig(dp=1, tp=len(devices))
    n = mesh_cfg.num_devices
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    shape = (mesh_cfg.dp, mesh_cfg.pp, mesh_cfg.sp, mesh_cfg.ep, mesh_cfg.tp)
    grid = np.asarray(devices[:n]).reshape(shape)
    return Mesh(grid, AXES)


# partition specs per parameter path; stacked layers lead with the L axis,
# which shards over "pp" (layer-stage sharding: each pipeline stage holds
# its layers' weights; the scan loop slices one step's shard at a time)
_PARAM_SPECS = {
    "embed": P("tp", None),  # vocab-parallel
    "lm_head": P(None, "tp"),  # vocab-parallel output
    "final_norm": P(None),
    "layers.attn_norm": P("pp", None),
    "layers.mlp_norm": P("pp", None),
    "layers.wq": P("pp", None, "tp"),  # column: heads
    "layers.wk": P("pp", None, "tp"),
    "layers.wv": P("pp", None, "tp"),
    "layers.wo": P("pp", "tp", None),  # row
    "layers.bq": P("pp", "tp"),
    "layers.bk": P("pp", "tp"),
    "layers.bv": P("pp", "tp"),
    # qwen3 per-head q/k norms [L, head_dim] (q_norm shares the MLA
    # entry below — same rank-2 layer-stacked shape, same placement)
    "layers.k_norm": P("pp", None),
    # gemma-2 sandwich norms
    "layers.attn_post_norm": P("pp", None),
    "layers.mlp_post_norm": P("pp", None),
    # gpt-oss: per-head attention sinks, o-projection bias, router logit
    # bias, per-expert projection biases (expert axis over ep)
    # sinks are per query head: shard with the head axis the attention
    # shard_maps split (their P("tp") operand spec)
    "layers.sinks": P("pp", "tp"),
    "layers.bo": P("pp", None),
    "layers.moe_router_bias": P("pp", None),
    # gate/up biases live on the Fm axis that tp shards (the ragged
    # shard_map adds them to tp-local activations); be_down replicates
    # its E axis like we_down's output
    "layers.be_gate": P("pp", "ep", "tp"),
    "layers.be_up": P("pp", "ep", "tp"),
    "layers.be_down": P("pp", "ep", None),
    "layers.w_gate": P("pp", None, "tp"),  # column: hidden
    "layers.w_up": P("pp", None, "tp"),
    "layers.w_down": P("pp", "tp", None),  # row
    # MoE (experts stacked on axis 1: [L, X, ...]; expert axis over "ep")
    "layers.moe_gate": P("pp", None, None),
    "layers.we_gate": P("pp", "ep", None, "tp"),
    "layers.we_up": P("pp", "ep", None, "tp"),
    "layers.we_down": P("pp", "ep", "tp", None),
    "layers.shared_gate": P("pp", None, "tp"),
    # qwen2moe sigmoid gate [L, E, 1]: tiny, replicated
    "layers.shared_egate": P("pp", None, None),
    "layers.shared_up": P("pp", None, "tp"),
    "layers.shared_down": P("pp", "tp", None),
    # MLA (models/mla.py): the q/kv down-projections and the shared
    # latent stream are small and replicated; head-parallel tp lives in
    # the q up-projection columns and the o row
    "layers.wq_a": P("pp", None, None),
    "layers.q_norm": P("pp", None),
    "layers.wq_b": P("pp", None, "tp"),
    "layers.wkv_a": P("pp", None, None),
    "layers.kv_norm": P("pp", None),
    "layers.wkv_b": P("pp", None, "tp"),
    "layers.moe_gate_bias": P("pp", None),
}


def _spec_alias(prefix: str) -> str:
    """DeepSeek's leading dense group (``dense_layers.*``) shares the
    stacked-layer placement rules (minus pp — see _spec_for)."""
    if prefix.startswith("dense_layers."):
        return "layers." + prefix[len("dense_layers."):]
    return prefix


def _spec_for(prefix: str) -> P:
    """Spec for a param path. Quantized weights (models/quant.py) nest
    ``{"q", "s"}`` under the weight's path: q keeps the parent's spec
    ([..., in, out] layout unchanged), s ([..., out], the contraction
    axis dropped) keeps every parent axis except the second-to-last."""
    dense_group = prefix.startswith("dense_layers.")
    prefix = _spec_alias(prefix)

    def out(spec: P) -> P:
        # the dense-first group is 1-3 layers (first_k_dense_replace):
        # pipeline-stage sharding of so few rows is meaningless and
        # rarely divisible — always replicate it over pp. Every other
        # indivisibility fails LOUDLY at device_put (silent replication
        # of multi-GB shards would surface only as a mystery OOM).
        if dense_group and len(spec) and spec[0] == "pp":
            return P(None, *tuple(spec)[1:])
        return spec

    if prefix in _PARAM_SPECS:
        return out(_PARAM_SPECS[prefix])
    parent = prefix.rsplit(".", 1)[0] if "." in prefix else ""
    if prefix.endswith(".q") and parent in _PARAM_SPECS:
        return out(_PARAM_SPECS[parent])
    if prefix.endswith(".s") and parent in _PARAM_SPECS:
        ps = tuple(_PARAM_SPECS[parent])
        return out(P(*ps[:-2], ps[-1]))
    return P()


# small-leaf bound for the undersized-axis fallback below: DeepSeek
# dense groups and tiny test stacks sit well under this; a real model's
# multi-GB layer stack stays above it and fails loudly
_FIT_MAX_BYTES = 1 << 26  # 64 MiB


def _fit_undersized(spec: P, leaf, mesh: Optional[Mesh]) -> P:
    """Replicate axes whose dimension is SMALLER than the mesh axis —
    physically unshardable (a 1-3 layer DeepSeek dense group on pp>=2,
    or a tiny test model's stack) — but ONLY for small leaves
    (_FIT_MAX_BYTES). Everything else, including indivisible-but-larger
    dims and undersized axes on big weights (e.g. pp=8 over a 4-layer
    real model), fails LOUDLY at placement: silently replicating
    multi-GB shards would surface only as a mystery OOM far from the
    misconfigured mesh."""
    shape = getattr(leaf, "shape", ())
    if (
        mesh is None
        or not shape
        or getattr(leaf, "nbytes", 0) > _FIT_MAX_BYTES
    ):
        return spec
    out = []
    for i, ax in enumerate(spec):
        if (
            ax is not None and i < len(shape)
            and shape[i] < mesh.shape.get(ax, 1)
        ):
            out.append(None)
        else:
            out.append(ax)
    return P(*out)


def spec_tree(tree, prefix: str = "", mesh: Optional[Mesh] = None) -> dict:
    """PartitionSpec pytree for a params subtree per the placement rules
    (the one walk; param_sharding/shard_params/pp all consume it). With
    ``mesh`` given, specs are fitted to the leaves' shapes
    (_fit_undersized); pp.py passes no mesh because can_pipeline already
    guarantees divisibility of every sharded dim."""
    if isinstance(tree, dict):
        return {
            k: spec_tree(v, f"{prefix}.{k}" if prefix else k, mesh)
            for k, v in tree.items()
        }
    return _fit_undersized(_spec_for(prefix), tree, mesh)


def param_sharding(mesh: Mesh) -> dict:
    """Pytree of NamedShardings matching the params structure."""

    def build(prefix: str, tree):
        specs = spec_tree(tree, prefix, mesh)

        def wrap(node):
            if isinstance(node, dict):
                return {k: wrap(v) for k, v in node.items()}
            return NamedSharding(mesh, node)

        return wrap(specs)

    return build


def put_global(x, sharding: NamedSharding):
    """``device_put`` onto a (possibly multi-process) sharding WITHOUT
    the hidden collective newer jax runs: ``device_put(host_value,
    non-addressable-sharding)`` broadcasts a cross-process
    ``assert_equal`` of the whole value, which both costs a collective
    per placement and — worse — deadlocks/crosses streams in lockstep
    protocols whose ranks place arrays at independent moments (the
    multihost mirror). The mirror protocol already guarantees identical
    host values on every rank, so build the global array from this
    process's addressable shards directly."""
    import numpy as np

    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    idx_map = sharding.addressable_devices_indices_map(x.shape)
    shards = [jax.device_put(x[idx], d) for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(
        x.shape, sharding, shards
    )


def shard_params(params: dict, mesh: Mesh) -> dict:
    """Place a params pytree onto the mesh per the placement rules."""

    def walk(leafs, specs):
        if isinstance(leafs, dict):
            return {k: walk(v, specs[k]) for k, v in leafs.items()}
        return put_global(leafs, NamedSharding(mesh, specs))

    return walk(params, spec_tree(params, mesh=mesh))


def cache_sharding(mesh: Mesh, cfg: ModelConfig) -> NamedSharding:
    """[L, Hkv, num_blocks, block_size, D]: layer axis shards over pp
    (stage-local KV), kv heads over tp — each when divisible, else
    replicated on that axis. MLA's latent cache is single-"head"
    (MQA-shaped — every query head reads the same latent stream), so it
    replicates over tp; tp parallelism lives in the query heads."""
    pp = mesh.shape.get("pp", 1)
    tp = mesh.shape["tp"]
    l_ax = "pp" if pp > 1 and cfg.num_layers % pp == 0 else None
    h_ax = (
        "tp" if not cfg.is_mla and cfg.num_kv_heads % tp == 0 else None
    )
    return NamedSharding(mesh, P(l_ax, h_ax, None, None, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


class LogicalLayout:
    """ONE logical sharding contract for an engine's weights and paged
    KV pool, carried mesh-free: the placement RULES (the spec tables
    above + ``cache_sharding``'s divisibility logic) are the layout;
    concrete ``NamedSharding``s are resolved at placement/dispatch time
    against whatever mesh currently backs the engine. This is what
    makes ``JaxEngine.reshard`` a first-class operation — the engine
    never captures a concrete ``Mesh``/``NamedSharding`` in long-lived
    state that a morph would silently invalidate (the dynlint
    ``mesh-capture`` rule guards the same invariant statically).

    ``mesh=None`` everywhere means "unsharded single-device engine":
    resolution returns ``None`` and movers place on the default device.
    """

    def __init__(self, model_cfg: ModelConfig):
        self.model = model_cfg

    # ---- weights ----

    def param_specs(self, params: dict, mesh: Optional[Mesh] = None) -> dict:
        """Logical PartitionSpec pytree for ``params`` (fitted to leaf
        shapes when a mesh is given — see ``spec_tree``)."""
        return spec_tree(params, mesh=mesh)

    def param_shardings(self, params: dict, mesh: Optional[Mesh]):
        """Resolve the logical weight layout against ``mesh``: a pytree
        of NamedShardings matching ``params``' structure, or a pytree of
        ``None`` leaves for the unsharded engine."""
        specs = self.param_specs(params, mesh=mesh)

        def wrap(node):
            if isinstance(node, dict):
                return {k: wrap(v) for k, v in node.items()}
            return NamedSharding(mesh, node) if mesh is not None else None

        return wrap(specs)

    def place_params(self, params: dict, mesh: Optional[Mesh]) -> dict:
        """Initial placement (load/init time): resolve + device_put."""
        if mesh is None:
            return params
        return shard_params(params, mesh)

    # ---- paged KV ----

    def cache_sharding(self, mesh: Optional[Mesh]):
        """Resolve the paged-KV layout rule against ``mesh`` (None for
        the unsharded engine)."""
        if mesh is None:
            return None
        return cache_sharding(mesh, self.model)

    # ---- small replicated device state (penalty planes etc.) ----

    def replicated_sharding(self, mesh: Optional[Mesh]):
        return replicated(mesh) if mesh is not None else None


#: memoized default-devices fingerprint, keyed by pid so a (rare)
#: fork doesn't inherit the parent's identity — the value is constant
#: for a process's backend, and the callers sit on per-stream paths
_slice_fp_cache: dict[int, str] = {}


def slice_fingerprint(devices=None) -> str:
    """Stable identity of the accelerator slice THIS process dispatches
    to — equal fingerprints mean KV can move device→device over ICI
    (disagg/ici.py) instead of gather→host→scatter.

    Built from the device topology (platform, owning process, device
    id/coords). Under a multi-controller runtime (jax.distributed) every
    rank sees the same global device list, so all ranks of one slice
    agree. WITHOUT one, each process owns an isolated local backend:
    two such processes are never one slice even on the same host, so
    the host+pid salt keeps their fingerprints distinct while two
    engines inside ONE process (the LocalKvPipe arrangement) still
    match. The default-devices value is memoized per process."""
    import hashlib
    import os
    import socket

    pid = os.getpid()
    if devices is None:
        cached = _slice_fp_cache.get(pid)
        if cached is not None:
            return cached
    devs = list(devices) if devices is not None else jax.devices()
    h = hashlib.blake2b(digest_size=8)
    if jax.process_count() <= 1:
        h.update(f"{socket.gethostname()}:{pid}|".encode())
    for d in devs:
        coords = getattr(d, "coords", None)
        h.update(
            f"{d.platform}:{getattr(d, 'process_index', 0)}:{d.id}:"
            f"{coords};".encode()
        )
    fp = h.hexdigest()
    if devices is None:
        _slice_fp_cache.clear()
        _slice_fp_cache[pid] = fp
    return fp

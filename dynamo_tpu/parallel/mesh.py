"""Device mesh + sharding placement rules.

The mesh axes:

  * ``dp``   — data parallel (independent request batches / replicas)
  * ``tp``   — tensor parallel (heads / mlp-hidden / vocab, over ICI)

Megatron-style placement (column-parallel qkv/gate/up, row-parallel
out/down, vocab-parallel embedding + lm_head) expressed purely as
NamedSharding annotations: jit propagates them and XLA SPMD inserts the
reduce-scatter/all-gather/all-reduce the reference gets from NCCL inside
vLLM. KV cache shards its kv-head axis over ``tp``; when tp exceeds the
kv-head count the cache axis is replicated (XLA handles the q-head split).

Multi-host: the same mesh built from jax.devices() spanning hosts (ICI
within a slice, DCN across slices via jax.distributed.initialize) — see
parallel.multihost.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig


@dataclass
class MeshConfig:
    dp: int = 1
    tp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp


def make_mesh(mesh_cfg: Optional[MeshConfig] = None, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if mesh_cfg is None:
        mesh_cfg = MeshConfig(dp=1, tp=len(devices))
    n = mesh_cfg.num_devices
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(mesh_cfg.dp, mesh_cfg.tp)
    return Mesh(grid, ("dp", "tp"))


# partition specs per parameter path (leading L axis on stacked layers)
_PARAM_SPECS = {
    "embed": P("tp", None),  # vocab-parallel
    "lm_head": P(None, "tp"),  # vocab-parallel output
    "final_norm": P(None),
    "layers.attn_norm": P(None, None),
    "layers.mlp_norm": P(None, None),
    "layers.wq": P(None, None, "tp"),  # column: heads
    "layers.wk": P(None, None, "tp"),
    "layers.wv": P(None, None, "tp"),
    "layers.wo": P(None, "tp", None),  # row
    "layers.bq": P(None, "tp"),
    "layers.bk": P(None, "tp"),
    "layers.bv": P(None, "tp"),
    "layers.w_gate": P(None, None, "tp"),  # column: hidden
    "layers.w_up": P(None, None, "tp"),
    "layers.w_down": P(None, "tp", None),  # row
    # MoE (experts stacked on axis 1: [L, X, ...])
    "layers.moe_gate": P(None, None, None),
    "layers.we_gate": P(None, None, None, "tp"),
    "layers.we_up": P(None, None, None, "tp"),
    "layers.we_down": P(None, None, "tp", None),
    "layers.shared_gate": P(None, None, "tp"),
    "layers.shared_up": P(None, None, "tp"),
    "layers.shared_down": P(None, "tp", None),
}


def param_sharding(mesh: Mesh) -> dict:
    """Pytree of NamedShardings matching the params structure."""

    def build(prefix: str, tree):
        if isinstance(tree, dict):
            return {k: build(f"{prefix}.{k}" if prefix else k, v) for k, v in tree.items()}
        spec = _PARAM_SPECS.get(prefix, P())
        return NamedSharding(mesh, spec)

    return build


def shard_params(params: dict, mesh: Mesh) -> dict:
    """Place a params pytree onto the mesh per the placement rules."""
    builder = param_sharding(mesh)

    def walk(prefix: str, tree):
        if isinstance(tree, dict):
            return {k: walk(f"{prefix}.{k}" if prefix else k, v) for k, v in tree.items()}
        spec = _PARAM_SPECS.get(prefix, P())
        return jax.device_put(tree, NamedSharding(mesh, spec))

    return walk("", params)


def cache_sharding(mesh: Mesh, cfg: ModelConfig) -> NamedSharding:
    """[L, Hkv, num_blocks, block_size, D]: shard kv heads over tp when
    divisible, else replicate that axis."""
    tp = mesh.shape["tp"]
    if cfg.num_kv_heads % tp == 0:
        return NamedSharding(mesh, P(None, "tp", None, None, None))
    return NamedSharding(mesh, P(None, None, None, None, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

"""Ring attention: sequence-parallel exact attention over an ICI ring.

The reference has NO sequence/context parallelism (SURVEY.md §2.8 — its
long-context story is paged KV + chunked prefill + disagg offload); this
is the TPU build's parity-plus capability for long-context prefill: shard
the sequence over the ``sp`` mesh axis, keep Q resident, and rotate KV
shards around the ring with ``lax.ppermute`` while accumulating exact
softmax attention blockwise (online/streaming softmax, the flash
-attention recurrence). Compute on each hop overlaps the next hop's
KV transfer on ICI.

Public papers behind the pattern: Liu et al., "Ring Attention with
Blockwise Transformers" (2023); the blockwise softmax recurrence from
Milakov & Gimelshein (2018) / flash attention.

All functions are shape-static and jit/shard_map friendly. Q/K/V are
``[T_local, H, D]`` inside each shard (one sequence, heads replicated or
tp-sharded orthogonally).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops._pallas_compat import shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, scale, q_pos, kv_pos, causal):
    """One blockwise attention contribution + its online-softmax stats.

    q: [Tq, H, D]; k/v: [Tk, Hkv, D] with Hkv == H (pre-repeated for GQA).
    Returns (contrib [Tq, H, D] — unnormalized exp-weighted values,
    m [Tq, H] row max, l [Tq, H] row sum)."""
    s = jnp.einsum("qhd,khd->hqk", q, k) * scale  # [H, Tq, Tk]
    if causal:
        mask = q_pos[None, :, None] >= kv_pos[None, None, :]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [H, Tq]
    # fully-masked rows (causal: shard ahead of all queries) would have
    # m = NEG_INF; pin m to 0 there so exp(s - m) underflows to 0 cleanly
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])  # [H, Tq, Tk]
    l = jnp.sum(p, axis=-1)  # noqa: E741
    contrib = jnp.einsum("hqk,khd->qhd", p, v)
    return contrib, jnp.transpose(m_safe), jnp.transpose(l)  # m,l -> [Tq, H]


def _merge(acc, m, l, contrib, m_new, l_new):  # noqa: E741
    """Merge a new block's (contrib, m, l) into running accumulators."""
    m_next = jnp.maximum(m, m_new)
    a = jnp.exp(m - m_next)  # rescale old
    b = jnp.exp(m_new - m_next)  # rescale new
    acc = acc * a[..., None] + contrib * b[..., None]
    l_next = l * a + l_new * b
    return acc, m_next, l_next


def _ring_reduce(axis_name, t_local, out_shape, stat_shape, rotated, attend):
    """The shared ring recurrence: ``rotated`` (a tuple of this shard's
    KV-side operands) hops the ring one step per iteration via ppermute
    while ``attend(kv_pos, *operands) -> (contrib, m, l)`` contributions
    merge into online-softmax accumulators; ``kv_pos`` [t_local] are the
    global positions of the operands currently held (the source shard's
    slots). One implementation for the GQA and MLA rings — the subtle
    parts (position/causality bookkeeping, the pcast varying-manual-axes
    workaround, compute/transfer overlap, the final out-of-loop attend
    so no ppermute result is discarded, the l-guarded normalize) cannot
    diverge between them. Returns the normalized [*, ...] f32 output.
    """
    p_size = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)

    # pvary: accumulators start as constants but the loop carry is
    # device-varying over the ring axis — mark them so shard_map's
    # varying-manual-axes check accepts the fori_loop carry. jax 0.4.x
    # has no lax.pcast (and its check_rep machinery doesn't need the
    # marking) — identity there.
    def _pvary(x):
        if hasattr(lax, "pcast"):
            return lax.pcast(x, (axis_name,), to="varying")
        return x

    acc = _pvary(jnp.zeros(out_shape, jnp.float32))
    m = _pvary(jnp.full(stat_shape, NEG_INF, jnp.float32))
    l = _pvary(jnp.zeros(stat_shape, jnp.float32))  # noqa: E741

    def kv_pos_at(step):
        src = (my - step) % p_size  # whose operands we hold this step
        return src * t_local + jnp.arange(t_local)

    def body(step, carry):
        acc, m, l, ops = carry  # noqa: E741
        acc, m, l = _merge(  # noqa: E741
            acc, m, l, *attend(kv_pos_at(step), *ops)
        )
        # rotate the KV-side operands around the ring for the next step
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        ops = tuple(lax.ppermute(o, axis_name, perm) for o in ops)
        return acc, m, l, ops

    # p_size - 1 rotations; the final shard attends outside the loop so
    # no ppermute result is ever discarded
    acc, m, l, ops = lax.fori_loop(  # noqa: E741
        0, p_size - 1, body, (acc, m, l, tuple(rotated))
    )
    acc, m, l = _merge(  # noqa: E741
        acc, m, l, *attend(kv_pos_at(p_size - 1), *ops)
    )
    return acc / jnp.maximum(l, 1e-30)[..., None]


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    scale: float,
    causal: bool = True,
) -> jnp.ndarray:
    """Exact attention with sequence sharded over ``axis_name``.

    Must run inside shard_map (or pmap) with q/k/v local shards
    [T_local, H, D]. Global sequence order follows the mesh axis index.
    Returns the local shard of the attention output [T_local, H, D].
    """
    t_local = q.shape[0]
    q_pos = lax.axis_index(axis_name) * t_local + jnp.arange(t_local)

    def attend(kv_pos, k_cur, v_cur):
        return _block_attend(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), scale, q_pos, kv_pos, causal,
        )

    out = _ring_reduce(
        axis_name, t_local, q.shape, q.shape[:2], (k, v), attend
    )
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    scale: float,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Driver: global [T, H, D] arrays in, ring attention over mesh axis
    ``axis_name`` (T must divide by its size), global [T, H, D] out."""
    spec = P(axis_name, None, None)
    fn = shard_map(
        partial(ring_attention, axis_name=axis_name, scale=scale, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


# ---------------- MLA (latent) ring attention ----------------


def _block_attend_latent(q_eff, q_pe, c, pe, scale, q_pos, kv_pos, causal):
    """Latent blockwise contribution: scores are the two-part absorbed
    dot ``q_eff . c + q_pe . pe`` and the VALUES are the latents
    themselves (models/mla.py) — the ring twin of _block_attend.
    q_eff: [Tq, H, C], q_pe: [Tq, H, R]; c: [Tk, C], pe: [Tk, R]
    (single shared latent stream — MQA shape, nothing to repeat)."""
    s = (
        jnp.einsum("qhc,kc->hqk", q_eff, c)
        + jnp.einsum("qhr,kr->hqk", q_pe, pe)
    ) * scale  # [H, Tq, Tk]
    if causal:
        mask = q_pos[None, :, None] >= kv_pos[None, None, :]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)  # noqa: E741
    contrib = jnp.einsum("hqk,kc->qhc", p, c)
    return contrib, jnp.transpose(m_safe), jnp.transpose(l)


def mla_ring_attention(
    q_eff: jnp.ndarray,  # [T_local, H, C] absorbed queries
    q_pe: jnp.ndarray,  # [T_local, H, R]
    c_kv: jnp.ndarray,  # [T_local, C] this shard's latents
    k_pe: jnp.ndarray,  # [T_local, R] head-shared rotated keys
    axis_name: str,
    scale: float,
    causal: bool = True,
) -> jnp.ndarray:  # [T_local, H, C] latent outputs (caller folds w_vc)
    """Ring attention over COMPRESSED latents for the MLA family.

    Identical recurrence to :func:`ring_attention`, but each hop rotates
    the (c_kv, k_pe) latent chunk instead of full K/V — C + R elements
    per token (576 for DeepSeek-V3, so 1152 B in bf16) versus 2*H*D
    elements of pre-repeated K/V (32768 for V3 geometry), a ~57x cut in
    ICI ring traffic at equal dtype. That asymmetry is the MLA trade
    carried to sequence parallelism: queries stay heavy and resident,
    the shared latent stream is what travels.
    """
    t_local = q_eff.shape[0]
    q_pos = lax.axis_index(axis_name) * t_local + jnp.arange(t_local)

    def attend(kv_pos, c_cur, pe_cur):
        return _block_attend_latent(
            q_eff.astype(jnp.float32), q_pe.astype(jnp.float32),
            c_cur.astype(jnp.float32), pe_cur.astype(jnp.float32),
            scale, q_pos, kv_pos, causal,
        )

    out_shape = q_eff.shape[:2] + (c_kv.shape[-1],)
    return _ring_reduce(
        axis_name, t_local, out_shape, q_eff.shape[:2], (c_kv, k_pe),
        attend,
    )


def mla_ring_attention_sharded(
    q_eff: jnp.ndarray,  # [T, H, C]
    q_pe: jnp.ndarray,  # [T, H, R]
    c_kv: jnp.ndarray,  # [T, C]
    k_pe: jnp.ndarray,  # [T, R]
    mesh: Mesh,
    scale: float,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Driver: global latent arrays in, ring over ``axis_name``, global
    [T, H, C] latent outputs out (f32; the caller folds through w_vc)."""
    spec3 = P(axis_name, None, None)
    spec2 = P(axis_name, None)
    fn = shard_map(
        partial(
            mla_ring_attention, axis_name=axis_name, scale=scale,
            causal=causal,
        ),
        mesh=mesh,
        in_specs=(spec3, spec3, spec2, spec2),
        out_specs=spec3,
    )
    return fn(q_eff, q_pe, c_kv, k_pe)

"""Ring attention: sequence-parallel exact attention over an ICI ring.

The reference has NO sequence/context parallelism (SURVEY.md §2.8 — its
long-context story is paged KV + chunked prefill + disagg offload); this
is the TPU build's parity-plus capability for long-context prefill: shard
the sequence over the ``sp`` mesh axis, keep Q resident, and rotate KV
shards around the ring with ``lax.ppermute`` while accumulating exact
softmax attention blockwise (online/streaming softmax, the flash
-attention recurrence). Compute on each hop overlaps the next hop's
KV transfer on ICI.

Public papers behind the pattern: Liu et al., "Ring Attention with
Blockwise Transformers" (2023); the blockwise softmax recurrence from
Milakov & Gimelshein (2018) / flash attention.

All functions are shape-static and jit/shard_map friendly. Q/K/V are
``[T_local, H, D]`` inside each shard (one sequence, heads replicated or
tp-sharded orthogonally).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, scale, q_pos, kv_pos, causal):
    """One blockwise attention contribution + its online-softmax stats.

    q: [Tq, H, D]; k/v: [Tk, Hkv, D] with Hkv == H (pre-repeated for GQA).
    Returns (contrib [Tq, H, D] — unnormalized exp-weighted values,
    m [Tq, H] row max, l [Tq, H] row sum)."""
    s = jnp.einsum("qhd,khd->hqk", q, k) * scale  # [H, Tq, Tk]
    if causal:
        mask = q_pos[None, :, None] >= kv_pos[None, None, :]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [H, Tq]
    # fully-masked rows (causal: shard ahead of all queries) would have
    # m = NEG_INF; pin m to 0 there so exp(s - m) underflows to 0 cleanly
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])  # [H, Tq, Tk]
    l = jnp.sum(p, axis=-1)  # noqa: E741
    contrib = jnp.einsum("hqk,khd->qhd", p, v)
    return contrib, jnp.transpose(m_safe), jnp.transpose(l)  # m,l -> [Tq, H]


def _merge(acc, m, l, contrib, m_new, l_new):  # noqa: E741
    """Merge a new block's (contrib, m, l) into running accumulators."""
    m_next = jnp.maximum(m, m_new)
    a = jnp.exp(m - m_next)  # rescale old
    b = jnp.exp(m_new - m_next)  # rescale new
    acc = acc * a[..., None] + contrib * b[..., None]
    l_next = l * a + l_new * b
    return acc, m_next, l_next


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    scale: float,
    causal: bool = True,
) -> jnp.ndarray:
    """Exact attention with sequence sharded over ``axis_name``.

    Must run inside shard_map (or pmap) with q/k/v local shards
    [T_local, H, D]. Global sequence order follows the mesh axis index.
    Returns the local shard of the attention output [T_local, H, D].
    """
    p_size = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[0]
    q_pos = my * t_local + jnp.arange(t_local)

    # pvary: accumulators start as constants but the loop carry is
    # device-varying over the ring axis — mark them so shard_map's
    # varying-manual-axes check accepts the fori_loop carry
    acc = lax.pcast(jnp.zeros(q.shape, jnp.float32), (axis_name,), to="varying")
    m = lax.pcast(
        jnp.full(q.shape[:1] + q.shape[1:2], NEG_INF, jnp.float32),
        (axis_name,), to="varying",
    )  # [Tq, H]
    l = lax.pcast(  # noqa: E741
        jnp.zeros(q.shape[:1] + q.shape[1:2], jnp.float32),
        (axis_name,), to="varying",
    )

    def attend(step, acc, m, l, k_cur, v_cur):  # noqa: E741
        src = (my - step) % p_size  # whose KV we hold this step
        kv_pos = src * t_local + jnp.arange(t_local)
        contrib, m_new, l_new = _block_attend(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), scale, q_pos, kv_pos, causal,
        )
        return _merge(acc, m, l, contrib, m_new, l_new)

    def body(step, carry):
        acc, m, l, k_cur, v_cur = carry  # noqa: E741
        acc, m, l = attend(step, acc, m, l, k_cur, v_cur)  # noqa: E741
        # rotate KV around the ring for the next step
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    # p_size - 1 rotations; the final shard attends outside the loop so no
    # ppermute result is ever discarded
    acc, m, l, k_last, v_last = lax.fori_loop(  # noqa: E741
        0, p_size - 1, body, (acc, m, l, k, v)
    )
    acc, m, l = attend(p_size - 1, acc, m, l, k_last, v_last)  # noqa: E741
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    scale: float,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Driver: global [T, H, D] arrays in, ring attention over mesh axis
    ``axis_name`` (T must divide by its size), global [T, H, D] out."""
    spec = P(axis_name, None, None)
    fn = jax.shard_map(
        partial(ring_attention, axis_name=axis_name, scale=scale, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)

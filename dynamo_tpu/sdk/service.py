"""Service model: decorators, dependency edges, graph resolution
(ref deploy/dynamo/sdk/src/dynamo/sdk/lib/{service,decorators,dependency}.py).
"""

from __future__ import annotations

import inspect
import json
import os
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Optional

CONFIG_ENV = "DYNAMO_SERVICE_CONFIG"  # per-service config JSON (ref service.py:96)


@dataclass
class ServiceSpec:
    name: str
    namespace: str
    component: str  # component name in the runtime path scheme
    config: dict = field(default_factory=dict)
    cls: Optional[type] = None

    def _attrs(self) -> dict[str, Any]:
        """Class attributes including inherited ones (MRO order, subclass
        wins) — a @service may factor endpoints into a base class."""
        merged: dict[str, Any] = {}
        for klass in reversed(self.cls.__mro__):
            merged.update(vars(klass))
        return merged

    def endpoints(self) -> dict[str, Callable]:
        """endpoint name -> unbound async-generator function."""
        out = {}
        for attr, val in self._attrs().items():
            ep = getattr(val, "_dynamo_endpoint", None)
            if ep:
                out[ep] = val
        return out

    def dependencies(self) -> dict[str, "Dependency"]:
        """attribute name -> Dependency declared on the class."""
        return {
            attr: val
            for attr, val in self._attrs().items()
            if isinstance(val, Dependency)
        }

    def runtime_config(self) -> dict:
        """Static config overlaid with DYNAMO_SERVICE_CONFIG[name]."""
        merged = dict(self.config)
        raw = os.environ.get(CONFIG_ENV)
        if raw:
            try:
                merged.update(json.loads(raw).get(self.name, {}))
            except (ValueError, AttributeError):
                pass
        return merged


def service(
    cls: Optional[type] = None,
    *,
    name: Optional[str] = None,
    namespace: str = "dynamo",
    **config: Any,
):
    """Class decorator registering a graph component (ref @service,
    lib/service.py:202). Usable bare or with arguments."""

    def wrap(c: type) -> type:
        svc_name = name or c.__name__
        c._dynamo_service = ServiceSpec(
            name=svc_name,
            namespace=namespace,
            component=svc_name.lower(),
            config=config,
            cls=c,
        )
        return c

    return wrap(cls) if cls is not None else wrap


def dynamo_endpoint(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    """Marks an async-generator method as a servable endpoint
    (ref @dynamo_endpoint, decorators.py:61)."""

    def wrap(f: Callable) -> Callable:
        if not inspect.isasyncgenfunction(f):
            raise TypeError(
                f"@dynamo_endpoint {f.__name__} must be an async generator "
                "(async def ... yield ...)"
            )
        f._dynamo_endpoint = name or f.__name__
        return f

    return wrap(fn) if fn is not None else wrap


class Dependency:
    """A graph edge declared as a class attribute (ref depends(),
    dependency.py:185). Resolved by the runner into a client proxy whose
    endpoint methods return streams of payloads."""

    def __init__(self, target: type):
        spec = getattr(target, "_dynamo_service", None)
        if spec is None:
            raise TypeError(f"depends() target {target!r} is not a @service")
        self.target = target
        self.spec: ServiceSpec = spec


def depends(target: type) -> Any:
    return Dependency(target)


def resolve_graph(leaf: type) -> list[ServiceSpec]:
    """Topological order (dependencies first) of the graph rooted at
    ``leaf`` (ref LinkedServices resolution)."""
    order: list[ServiceSpec] = []
    seen: set[type] = set()

    def visit(cls: type, path: tuple = ()):
        if cls in path:
            cycle = " -> ".join(c.__name__ for c in path + (cls,))
            raise ValueError(f"dependency cycle: {cycle}")
        if cls in seen:
            return
        spec: ServiceSpec = cls._dynamo_service
        for dep in spec.dependencies().values():
            visit(dep.target, path + (cls,))
        seen.add(cls)
        order.append(spec)

    visit(leaf)
    return order


class EndpointProxy:
    """``await proxy.generate(payload)`` -> async iterator of payloads."""

    def __init__(self, get_stream: Callable, endpoint: str):
        self._get_stream = get_stream
        self._endpoint = endpoint

    async def __call__(self, payload: Any) -> AsyncIterator[Any]:
        return await self._get_stream(self._endpoint, payload)


class ServiceClient:
    """What a ``depends()`` attribute becomes at runtime: endpoint-name
    attribute access yields callables streaming from the dependency."""

    def __init__(self, spec: ServiceSpec, get_stream: Callable):
        self._spec = spec
        self._get_stream = get_stream

    def __getattr__(self, name: str) -> EndpointProxy:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._spec.endpoints():
            raise AttributeError(
                f"{self._spec.name} has no endpoint {name!r} "
                f"(has: {sorted(self._spec.endpoints())})"
            )
        return EndpointProxy(self._get_stream, name)

"""``dynamo serve`` equivalent (ref deploy/dynamo/sdk cli/serve.py):

    python -m dynamo_tpu.sdk.cli pkg.module:Frontend -f config.yaml \
        [--hub HOST:PORT | --hub-port N]

Starts a hub control plane if no --hub is given, then supervises one
subprocess per service in the graph."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys


def _load_config(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        try:
            import yaml  # type: ignore

            return yaml.safe_load(text) or {}
        except ImportError:  # environment without pyyaml: JSON fallback
            pass
    return json.loads(text)


async def main_async(args) -> None:
    from .serving import Supervisor

    hub_proc = None
    hub = args.hub
    if hub is None:
        hub = f"127.0.0.1:{args.hub_port}"
        hub_proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "dynamo_tpu.launch.dynamo_run", "hub",
            "--hub-port", str(args.hub_port),
        )
        # hub startup pays the interpreter+jax import cost: poll until it
        # answers so workers don't burn their restart budget on the race
        from ..runtime.hub import connect_hub

        for _ in range(120):
            try:
                _store, _bus, conn = await connect_hub(hub)
                await conn.close()
                break
            except OSError:
                await asyncio.sleep(0.5)
        else:
            raise SystemExit(f"hub at {hub} never came up")
    config = _load_config(args.file) if args.file else {}
    sup = Supervisor(args.graph, hub, config=config)
    await sup.start()
    print(f"serving graph {args.graph} on hub {hub}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await sup.stop()
        if hub_proc is not None:
            hub_proc.terminate()


def main() -> None:
    p = argparse.ArgumentParser("dynamo-serve")
    p.add_argument("graph", help="pkg.module:LeafService")
    p.add_argument("-f", "--file", default=None, help="per-service config (yaml/json)")
    p.add_argument("--hub", default=None, help="existing hub host:port")
    p.add_argument("--hub-port", type=int, default=18500)
    args = p.parse_args()
    from ..utils.logging import setup_logging
    setup_logging()
    try:
        asyncio.run(main_async(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()

"""Application SDK: compose components into deployable serving graphs.

The TPU-native equivalent of the reference's BentoML-derived SDK
(deploy/dynamo/sdk: ``@service``, ``@dynamo_endpoint``, ``depends()``,
``dynamo serve`` — service.py:67-120, dependency.py:185, cli/serving.py).
Differences are deliberate: no BentoML base, no circus — a service is a
plain class, the graph is resolved from ``depends()`` edges, and the
supervisor is a small asyncio subprocess manager with restart-on-crash.

    from dynamo_tpu.sdk import service, dynamo_endpoint, depends

    @service(namespace="app")
    class Worker:
        @dynamo_endpoint
        async def generate(self, request):
            yield {"out": request["x"] * 2}

    @service(namespace="app")
    class Frontend:
        worker = depends(Worker)

        @dynamo_endpoint
        async def generate(self, request):
            async for it in await self.worker.generate(request):
                yield it

Run in-process (tests, notebooks) with ``serve_graph``; multi-process
with ``python -m dynamo_tpu.sdk.cli module:Frontend``.
"""

from .service import Dependency, ServiceSpec, depends, dynamo_endpoint, service
from .serving import GraphRunner, Supervisor, serve_graph

__all__ = [
    "Dependency",
    "GraphRunner",
    "ServiceSpec",
    "Supervisor",
    "depends",
    "dynamo_endpoint",
    "serve_graph",
    "service",
]

"""Graph runners: in-process (tests/notebooks) and multi-process
supervisor (ref cli/serving.py's circus watchers — here a plain asyncio
subprocess manager with restart-on-crash)."""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys
from typing import Any, AsyncIterator, Optional

from ..runtime.component import Client
from ..runtime.engine import AsyncEngine, Context
from ..runtime.runtime import DistributedRuntime
from .service import ServiceClient, ServiceSpec, resolve_graph

logger = logging.getLogger(__name__)


class _BoundEndpointEngine(AsyncEngine):
    """Adapts a bound @dynamo_endpoint async generator to AsyncEngine."""

    def __init__(self, bound_fn):
        self._fn = bound_fn

    async def generate(self, request: Context) -> AsyncIterator[Any]:
        async for item in self._fn(request.data):
            yield item


class GraphRunner:
    """Instantiates services, injects dependency clients, registers every
    endpoint on the DistributedRuntime. One runner can host the whole
    graph (in-process mode) or a single service (subprocess mode)."""

    def __init__(self, drt: DistributedRuntime):
        self.drt = drt
        self.instances: dict[str, object] = {}
        self._handles: list = []
        # one cached client per (namespace, component, endpoint) — created
        # on first use, reused for every subsequent dependency call
        self._clients: dict[tuple[str, str, str], Client] = {}
        self._client_locks: dict[tuple[str, str, str], asyncio.Lock] = {}

    async def serve_graph(self, leaf: type) -> None:
        for spec in resolve_graph(leaf):
            await self.serve_service(spec)

    async def serve_service(self, spec: ServiceSpec) -> None:
        instance = spec.cls()
        # config + dependency injection before user __init__ hooks run
        instance.dynamo_config = spec.runtime_config()
        for attr, dep in spec.dependencies().items():
            setattr(instance, attr, await self._client_for(dep.spec))
        if hasattr(instance, "async_init"):
            await instance.async_init()
        self.instances[spec.name] = instance
        component = self.drt.namespace(spec.namespace).component(spec.component)
        for ep_name, fn in spec.endpoints().items():
            engine = _BoundEndpointEngine(getattr(instance, fn.__name__))
            handle = await component.endpoint(ep_name).serve(
                engine,
                stats_handler=getattr(instance, "stats_handler", None),
            )
            self._handles.append(handle)
        logger.info(
            "service %s serving %s at %s/%s",
            spec.name, sorted(spec.endpoints()), spec.namespace, spec.component,
        )

    async def _cached_client(self, spec: ServiceSpec, endpoint: str) -> Client:
        key = (spec.namespace, spec.component, endpoint)
        lock = self._client_locks.setdefault(key, asyncio.Lock())
        async with lock:
            client = self._clients.get(key)
            if client is None:
                ep = (
                    self.drt.namespace(spec.namespace)
                    .component(spec.component)
                    .endpoint(endpoint)
                )
                client = await ep.client().start()
                await client.wait_for_instances()
                self._clients[key] = client
        return client

    async def _client_for(self, spec: ServiceSpec) -> ServiceClient:
        runner = self

        async def get_stream(endpoint: str, payload: Any):
            client = await runner._cached_client(spec, endpoint)
            stream = await client.generate(Context(payload))

            async def payloads():
                async for item in stream:
                    data = getattr(item, "data", item)
                    if getattr(item, "error", None):
                        raise RuntimeError(item.error)
                    if data is not None:
                        yield data

            return payloads()

        return ServiceClient(spec, get_stream)

    async def stop(self) -> None:
        for c in self._clients.values():
            c.stop()
        self._clients.clear()
        for h in self._handles:
            await h.stop()
        self._handles.clear()


async def serve_graph(drt: DistributedRuntime, leaf: type) -> GraphRunner:
    runner = GraphRunner(drt)
    await runner.serve_graph(leaf)
    return runner


class Supervisor:
    """One subprocess per service with restart-on-crash (ref circus
    watchers, cli/serving.py:118-157)."""

    def __init__(
        self,
        graph_target: str,  # "pkg.module:LeafService"
        hub: str,
        config: Optional[dict] = None,
        max_restarts: int = 5,
    ):
        self.graph_target = graph_target
        self.hub = hub
        self.config = config or {}
        self.max_restarts = max_restarts
        self._procs: dict[str, asyncio.subprocess.Process] = {}
        self._tasks: list[asyncio.Task] = []
        self._stopping = False

    @staticmethod
    def _load_leaf(target: str) -> type:
        import importlib

        mod_name, _, cls_name = target.partition(":")
        return getattr(importlib.import_module(mod_name), cls_name)

    async def start(self) -> None:
        leaf = self._load_leaf(self.graph_target)
        for spec in resolve_graph(leaf):
            self._tasks.append(
                asyncio.get_running_loop().create_task(self._watch(spec))
            )

    HEALTHY_RESET_S = 60.0  # a run this long forgives earlier crashes

    async def _watch(self, spec: ServiceSpec) -> None:
        restarts = 0
        while not self._stopping and restarts <= self.max_restarts:
            env = dict(os.environ)
            env["DYNAMO_SERVICE_CONFIG"] = json.dumps(self.config)
            started = asyncio.get_running_loop().time()
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "dynamo_tpu.sdk.serve_worker",
                self.graph_target, spec.name, "--hub", self.hub,
                env=env,
            )
            self._procs[spec.name] = proc
            rc = await proc.wait()
            if self._stopping:
                return
            uptime = asyncio.get_running_loop().time() - started
            if uptime >= self.HEALTHY_RESET_S:
                restarts = 0  # crash-looping, not an occasional crash
            restarts += 1
            logger.warning(
                "service %s exited rc=%s after %.0fs; restart %d/%d",
                spec.name, rc, uptime, restarts, self.max_restarts,
            )
            await asyncio.sleep(min(2.0 * restarts, 10.0))
        if not self._stopping:
            logger.error("service %s exceeded restart budget", spec.name)

    async def stop(self) -> None:
        self._stopping = True
        for proc in self._procs.values():
            if proc.returncode is None:
                proc.terminate()
        for proc in self._procs.values():
            try:
                await asyncio.wait_for(proc.wait(), timeout=10.0)
            except asyncio.TimeoutError:
                proc.kill()
        for t in self._tasks:
            t.cancel()

"""Subprocess entry for one SDK service (ref cli/serve_dynamo.py):
``python -m dynamo_tpu.sdk.serve_worker pkg.module:Leaf ServiceName --hub H``.
Connects to the hub control plane, serves exactly the named service from
the graph, and runs until terminated — SIGTERM triggers a graceful drain
(deregister from discovery, let in-flight endpoint streams flush, revoke
the lease last) instead of an abrupt death."""

from __future__ import annotations

import argparse
import asyncio
import logging

from ..resilience import DrainCoordinator
from ..runtime.hub import connect_hub
from ..runtime.runtime import DistributedRuntime
from .serving import GraphRunner, Supervisor
from .service import resolve_graph


async def main_async(args) -> None:
    leaf = Supervisor._load_leaf(args.graph)
    spec = next(
        (s for s in resolve_graph(leaf) if s.name == args.service), None
    )
    if spec is None:
        raise SystemExit(f"service {args.service!r} not in graph {args.graph}")
    store, bus, _conn = await connect_hub(args.hub)
    drt = await DistributedRuntime.from_settings(store=store, bus=bus)
    runner = GraphRunner(drt)
    await runner.serve_service(spec)
    print(f"sdk service {spec.name} up (worker {drt.worker_id:x})", flush=True)
    done = asyncio.Event()
    drain = DrainCoordinator(
        drt, handles=list(runner._handles),
        deadline_s=args.drain_deadline, on_done=done.set,
    )
    drain.install_signal_handlers()
    await done.wait()


def main() -> None:
    p = argparse.ArgumentParser("dynamo_tpu.sdk.serve_worker")
    p.add_argument("graph")
    p.add_argument("service")
    p.add_argument("--hub", required=True)
    p.add_argument("--drain-deadline", type=float, default=15.0,
                   help="SIGTERM graceful-drain budget (s)")
    args = p.parse_args()
    logging.basicConfig(level="INFO")
    try:
        asyncio.run(main_async(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()

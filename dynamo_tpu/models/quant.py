"""Post-training weight + KV-cache quantization for serving.

The reference's headline workloads are FP8 70B-class models served through
its wrapped engines (ref docs/architecture.md:57-91, TRT-LLM/vLLM FP8
paths); here quantization is native to the JAX engine.

TPU serving decode is HBM-bandwidth-bound: below the roofline knee every
decode step streams the full weight set from HBM once, so int8/fp8 storage
halves the bytes per token versus bf16. The dequantize — a convert plus a
per-output-channel scale multiply — fuses into the matmul's operand read
under XLA, so the win is pure bandwidth; compute stays bf16 on the MXU.

Scheme: symmetric per-output-channel absmax scaling over the contraction
axis. A quantized weight is a ``{"q": int8|float8 [..., in, out],
"s": f32 [..., out]}`` pytree node; ``models.llama._mm`` consumes either
form, and the stacked-layer scan slices the nested leaves like any other.
MoE expert stacks quantize the same way ([L, X, in, out]; scales
[L, X, out]) and are consumed by the grouped-dequant Pallas kernel
(``ops/moe_gmm_pallas.py`` via ``llama._ragged_mm``) — ``lax.ragged_dot``
has no sub-bf16 path, and dequantizing outside the kernel would cost
MORE bandwidth than bf16, so the kernel is what makes expert
quantization a win rather than a loss (VERDICT r4 weak #3: the
flagship EP-decode configs are exactly where halving the expert stream
matters most). The KV cache can independently be stored as
float8_e4m3fn (scale-free direct cast, vLLM's fp8 KV cache approach)
via ``EngineConfig.kv_cache_dtype``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .config import ModelConfig

WEIGHT_MODES = ("none", "int8", "fp8_e4m3", "int8_native")
KV_CACHE_DTYPES = ("model", "float8_e4m3", "bfloat16", "int8")

#: floor for every KV scale plane entry — a freshly-reset page's scale.
#: Matches engine/kvquant.py's codec epsilon so a device-plane scale is
#: always a valid tier-codec scale (zero re-encode on d2h export).
KV_SCALE_EPS = 1e-12
#: int8 symmetric range used by the device KV planes (same as the tier
#: codec's int8 qmax — one number across every plane)
KV_INT8_QMAX = 127.0

# the stacked-layer projection matrices worth quantizing ([L, in, out]
# layout, contraction on axis -2); embeddings/norms/biases/router stay
# high-precision (tiny, or quality-critical)
_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
               "shared_gate", "shared_up", "shared_down",
               # MLA projections (mla._wkv_b_parts dequants wkv_b for
               # the absorbed fold; the rest ride _mm's fused dequant)
               "wq_a", "wq_b", "wkv_a", "wkv_b")
# expert stacks ([L, X, in, out]): quantized by default, consumed by the
# grouped-dequant kernel; EngineConfig.quant_experts is the escape hatch
_EXPERT_QUANT_KEYS = ("we_gate", "we_up", "we_down")


def _qdtype(mode: str):
    if mode in ("int8", "int8_native"):
        return jnp.int8, 127.0
    if mode == "fp8_e4m3":
        return jnp.float8_e4m3fn, 448.0
    raise ValueError(f"unknown quantization mode {mode!r}")


def quantize_array(w: jnp.ndarray, mode: str) -> dict:
    """Symmetric per-output-channel quantization of a [..., in, out]
    matmul weight: scale = absmax over the contraction axis / dtype max."""
    dt, qmax = _qdtype(mode)
    wf = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.maximum(absmax / qmax, 1e-12)
    q = wf / scale
    if mode in ("int8", "int8_native"):
        q = jnp.clip(jnp.round(q), -127, 127)
    # "int8_native" stores the SAME numbers under the "qn" key: the
    # distinct pytree key routes llama._mm onto the native int8
    # dot_general path (int8 x int8 -> f32-accumulated) instead of the
    # dequant-at-operand-read path, and the structural difference keeps
    # the two modes' jit programs from colliding in the compile cache.
    qkey = "qn" if mode == "int8_native" else "q"
    return {qkey: q.astype(dt), "s": scale.squeeze(-2).astype(jnp.float32)}


def dequantize_array(qw: dict) -> jnp.ndarray:
    q = qw["qn"] if "qn" in qw else qw["q"]
    return q.astype(jnp.float32) * qw["s"][..., None, :]


def quantize_params(params: dict, cfg: ModelConfig, mode: str,
                    experts: bool = True) -> dict:
    """Quantize the serving-relevant projection weights in a params pytree
    (pure function; the engine applies it before mesh placement so the
    derived q/s leaves get their own shardings, parallel/mesh.py).
    ``experts=False`` keeps MoE expert stacks at the model dtype
    (EngineConfig.quant_experts escape hatch)."""
    if mode in (None, "none"):
        return params
    if mode not in WEIGHT_MODES:
        raise ValueError(f"quantization must be one of {WEIGHT_MODES}")
    keys = _QUANT_KEYS + (_EXPERT_QUANT_KEYS if experts else ())
    out = dict(params)
    for grp in ("layers", "dense_layers"):
        if grp not in params:
            continue
        layers = dict(params[grp])
        for key in keys:
            if key in layers and not isinstance(layers[key], dict):
                # expert stacks are consumed by the grouped-dequant
                # Pallas kernel, which wants the "q" form — the native
                # int8 dot path only covers the dense projections
                kmode = ("int8" if mode == "int8_native"
                         and key in _EXPERT_QUANT_KEYS else mode)
                layers[key] = quantize_array(layers[key], kmode)  # idempotent
        out[grp] = layers
    return out


def kv_cache_dtype(cfg: ModelConfig, name: str):
    """Resolve an EngineConfig.kv_cache_dtype name to a jnp dtype (None =
    the model's own dtype)."""
    if name in (None, "model"):
        return None
    if name == "float8_e4m3":
        return jnp.float8_e4m3fn
    if name == "bfloat16":
        return jnp.bfloat16
    if name == "int8":
        # int8-with-scales DEVICE cache: the engine allocates per-page
        # f32 scale planes alongside the paged k/v caches and threads
        # them through every write/read dispatch (engine/engine.py)
        return jnp.int8
    raise ValueError(f"kv_cache_dtype must be one of {KV_CACHE_DTYPES}")

"""Llama-family model: pure-JAX functional forward over a paged KV cache.

Covers Llama 2/3, DeepSeek-R1-Distill-Llama, Mistral, Qwen2 (bias), and
Gemma (GeGLU, (1+w) norms folded at load, sqrt(E)-scaled embeddings) — the
dense decoder families the reference serves through vLLM (README model
list). Design is TPU-first, not a port:

  * parameters are a pytree with layers **stacked on a leading axis** and
    the layer loop is ``lax.scan`` — one traced layer body, fast XLA
    compiles even at 80 layers;
  * the KV cache is two arrays ``[L, Hkv, num_blocks, block_size, D]``
    (head-major so each (head, page) is one contiguous DMA tile)
    threaded through scan functionally and **donated** by the engine's jit,
    so XLA updates it in place in HBM;
  * attention reads the cache through block tables (paged), masks do the
    ragged bookkeeping — all shapes static;
  * sharding is annotation-only: the engine places params/cache with
    NamedSharding over a ("dp", "tp") mesh and jit propagates (XLA SPMD
    inserts the collectives the reference gets from NCCL/Ray).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import attention as att
from ..ops._pallas_compat import shard_map
from .config import ModelConfig, yarn_mscale


def _dtype(cfg: ModelConfig):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32, "float16": jnp.bfloat16}[
        str(cfg.dtype)
    ]


# ---------------- parameter init / structure ----------------


def _init_layer_group(cfg: ModelConfig, key: jax.Array, L: int,
                      moe: bool) -> dict:
    """Stacked [L, ...] layer leaves for one homogeneous group (attention
    + one FFN kind). DeepSeek's first_k_dense_replace makes the model
    heterogeneous, so params carry up to two groups (``dense_layers``
    then ``layers``) — each scanned separately."""
    dt = _dtype(cfg)
    E, H, Hkv, D, F, V = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
        cfg.intermediate_size, cfg.vocab_size,
    )
    keys = jax.random.split(key, 12)

    def layer_stack(k, shape, scale=0.02):
        return (
            jax.random.normal(k, (L,) + shape, jnp.float32) * scale
        ).astype(dt)

    layers = {
        **(
            {} if cfg.norm_after else {
                "attn_norm": jnp.ones((L, E), dt),
                "mlp_norm": jnp.ones((L, E), dt),
            }
        ),
        **(
            {"attn_post_norm": jnp.ones((L, E), dt),
             "mlp_post_norm": jnp.ones((L, E), dt)}
            if cfg.post_norms else {}
        ),
    }
    if cfg.is_mla:
        Cq, C = cfg.q_lora_rank, cfg.kv_lora_rank
        dqk, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
        if Cq:
            layers["wq_a"] = layer_stack(keys[1], (E, Cq))
            layers["q_norm"] = jnp.ones((L, Cq), dt)
            layers["wq_b"] = layer_stack(keys[2], (Cq, H * (dqk + dr)))
        else:
            layers["wq"] = layer_stack(keys[1], (E, H * (dqk + dr)))
        layers["wkv_a"] = layer_stack(keys[3], (E, C + dr))
        layers["kv_norm"] = jnp.ones((L, C), dt)
        layers["wkv_b"] = layer_stack(keys[9], (C, H * (dqk + dv)))
        layers["wo"] = layer_stack(keys[4], (H * dv, E))
    else:
        layers["wq"] = layer_stack(keys[1], (E, H * D))
        layers["wk"] = layer_stack(keys[2], (E, Hkv * D))
        layers["wv"] = layer_stack(keys[3], (E, Hkv * D))
        layers["wo"] = layer_stack(keys[4], (H * D, E))
        if cfg.attention_bias:
            layers["bq"] = jnp.zeros((L, H * D), dt)
            layers["bk"] = jnp.zeros((L, Hkv * D), dt)
            layers["bv"] = jnp.zeros((L, Hkv * D), dt)
        if cfg.qk_norm_full:  # olmo-2: full projection width
            layers["q_norm"] = jnp.ones((L, H * D), dt)
            layers["k_norm"] = jnp.ones((L, Hkv * D), dt)
        elif cfg.qk_norm:
            layers["q_norm"] = jnp.ones((L, D), dt)
            layers["k_norm"] = jnp.ones((L, D), dt)
        if cfg.attn_sinks:
            layers["sinks"] = layer_stack(keys[10], (H,), 0.5)
        if cfg.o_bias:
            layers["bo"] = jnp.zeros((L, E), dt)
    if moe:
        X = cfg.num_experts
        Fm = cfg.moe_intermediate_size or F
        mk = jax.random.split(keys[5], 8)
        layers["moe_gate"] = layer_stack(mk[0], (E, X))
        if cfg.moe_gate_bias:
            layers["moe_gate_bias"] = jnp.zeros((L, X), jnp.float32)
        layers["we_gate"] = layer_stack(mk[1], (X, E, Fm))
        layers["we_up"] = layer_stack(mk[2], (X, E, Fm))
        layers["we_down"] = layer_stack(mk[3], (X, Fm, E))
        if cfg.moe_act == "gptoss_clamp":  # gpt-oss expert/router biases
            layers["moe_router_bias"] = jnp.zeros((L, X), jnp.float32)
            layers["be_gate"] = layer_stack(keys[8], (X, Fm), 0.05)
            layers["be_up"] = layer_stack(keys[9], (X, Fm), 0.05)
            layers["be_down"] = layer_stack(keys[11], (X, E), 0.05)
        if cfg.num_shared_experts:
            Fs = cfg.shared_expert_size or Fm * cfg.num_shared_experts
            layers["shared_gate"] = layer_stack(mk[4], (E, Fs))
            layers["shared_up"] = layer_stack(mk[5], (E, Fs))
            layers["shared_down"] = layer_stack(mk[6], (Fs, E))
            if cfg.shared_expert_gate:  # qwen2moe sigmoid gate [E, 1]
                layers["shared_egate"] = layer_stack(mk[7], (E, 1))
    else:
        layers["w_gate"] = layer_stack(keys[5], (E, F))
        layers["w_up"] = layer_stack(keys[6], (E, F))
        layers["w_down"] = layer_stack(keys[7], (F, E))
    return layers


def layer_groups(params: dict, cfg: ModelConfig):
    """[(stacked_layer_params, n_layers, layer_offset)] in forward order
    — one group for homogeneous models, (dense, moe) for DeepSeek-style
    first_k_dense_replace checkpoints."""
    k = cfg.first_dense_layers if "dense_layers" in params else 0
    out = []
    if k:
        out.append((params["dense_layers"], k, 0))
    out.append((params["layers"], cfg.num_layers - k, k))
    return out


def _scan_groups(body, x, params, cfg: ModelConfig, k_cache, v_cache):
    """lax.scan the layer body over every layer group, threading the
    cache slices; returns (x, k_cache, v_cache) with per-group ys
    re-concatenated on the layer axis. ONE implementation for prefill
    and both scan decode variants."""
    kcs, vcs = [], []
    for lps, n, off in layer_groups(params, cfg):
        x, (kc_g, vc_g) = lax.scan(
            body, x, (lps, k_cache[off : off + n], v_cache[off : off + n])
        )
        kcs.append(kc_g)
        vcs.append(vc_g)
    k_cache = jnp.concatenate(kcs) if len(kcs) > 1 else kcs[0]
    v_cache = jnp.concatenate(vcs) if len(vcs) > 1 else vcs[0]
    return x, k_cache, v_cache


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    """Random-init params (tests/benches; real weights via weights.py)."""
    dt = _dtype(cfg)
    E, V, L = cfg.hidden_size, cfg.vocab_size, cfg.num_layers
    keys = jax.random.split(key, 4)

    def norm_init(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    kd = cfg.first_dense_layers if cfg.is_moe else 0
    params = {
        "embed": norm_init(keys[0], (V, E), 0.02),
        "final_norm": jnp.ones((E,), dt),
        "layers": _init_layer_group(cfg, keys[1], L - kd, cfg.is_moe),
    }
    if kd:
        params["dense_layers"] = _init_layer_group(cfg, keys[3], kd, False)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm_init(keys[2], (E, V), 0.02)
    return params


def kv_cache_shapes(
    cfg: ModelConfig, num_blocks: int, block_size: int
) -> tuple[tuple, tuple]:
    """(k_shape, v_shape). MLA stores the compressed latent instead of
    per-head K/V: c_kv rides the k slot, the head-shared rotated k_pe the
    v slot — both single-"head" paged arrays, so every block-table /
    allocator / offload / transfer path works unchanged (models/mla.py)."""
    L = cfg.num_layers
    if cfg.is_mla:
        return (
            (L, 1, num_blocks, block_size, cfg.kv_lora_rank),
            (L, 1, num_blocks, block_size, cfg.qk_rope_head_dim),
        )
    s = (L, cfg.num_kv_heads, num_blocks, block_size, cfg.head_dim)
    return s, s


def init_kv_cache(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype=None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    ks, vs = kv_cache_shapes(cfg, num_blocks, block_size)
    dt = dtype or _dtype(cfg)
    return jnp.zeros(ks, dt), jnp.zeros(vs, dt)


# ---------------- building blocks ----------------


def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * w


def attn_query_scale(cfg: ModelConfig) -> float:
    """Query scale: head_dim**-0.5, or gemma-2's fixed
    query_pre_attn_scalar**-0.5."""
    return (cfg.attn_scale_base or cfg.head_dim) ** -0.5


def pre_norm(lp: dict, key: str, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Pre-sublayer RMS norm — identity for norm-AFTER families (OLMo-2
    carries no input/pre-FFN norms; normalization happens on the
    sublayer output via post_norm)."""
    w = lp.get(key)
    return x if w is None else rms_norm(x, w, cfg.rms_norm_eps)


def post_norm(lp: dict, key: str, v: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Gemma-2 sandwich norm: normalize the sublayer OUTPUT before the
    residual add (post_attention/post_feedforward_layernorm). No-op for
    every other family (no post-norm weights in lp)."""
    w = lp.get(key)
    return v if w is None else rms_norm(v, w, cfg.rms_norm_eps)


def window_for_layer(cfg: ModelConfig, l: int) -> int:
    """Layer l's sliding-window width (0 = full). gpt-oss alternates
    sliding/full per layer (cfg.layer_windows); every other family is
    homogeneous (cfg.sliding_window). Call sites must be UNROLLED —
    the value is trace-static per layer."""
    return cfg.layer_windows[l] if cfg.layer_windows else cfg.sliding_window


def _rope_attention_scaling(cfg: ModelConfig) -> float:
    """YaRN multiplies cos/sin by an attention factor (transformers
    _compute_yarn_parameters); 1.0 for every other rope flavor."""
    import math

    scaling = cfg.rope_scaling or {}
    kind = scaling.get("rope_type") or scaling.get("type")
    if kind == "longrope":
        # Phi-3: sqrt(1 + log(ctx growth)/log(orig)) on cos/sin —
        # applied in BOTH factor regimes (HF computes it once at init).
        af = scaling.get("attention_factor")
        if af is not None:
            return float(af)
        orig = scaling.get("original_max_position_embeddings")
        if orig:
            factor = cfg.max_position_embeddings / orig
            log_base = orig
        else:
            # no original context recorded: HF falls back to the
            # explicit rope_scaling["factor"] over max_position
            factor = scaling.get("factor", 1.0)
            log_base = cfg.max_position_embeddings
        if factor <= 1.0:
            return 1.0
        return math.sqrt(1.0 + math.log(factor) / math.log(log_base))
    if kind != "yarn":
        return 1.0
    factor = scaling.get("factor", 1.0)
    af = scaling.get("attention_factor")
    if af is not None:
        return float(af)
    msc, mad = scaling.get("mscale"), scaling.get("mscale_all_dim")
    if msc and mad:
        return float(yarn_mscale(factor, msc) / yarn_mscale(factor, mad))
    if factor <= 1.0:
        return 1.0
    return 0.1 * math.log(factor) + 1.0


def _rope_freqs(cfg: ModelConfig) -> jnp.ndarray:
    import math

    D = cfg.rope_partial_dim or cfg.head_dim
    inv = 1.0 / (cfg.rope_theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    scaling = cfg.rope_scaling or {}
    if (scaling.get("rope_type") or scaling.get("type")) == "yarn":
        # YaRN (transformers _compute_yarn_parameters): interpolate the
        # low-frequency dims by ``factor``, extrapolate the high ones,
        # linear ramp across the correction range (gpt-oss ships
        # truncate=False, so the range bounds stay fractional)
        factor = scaling.get("factor", 1.0)
        beta_fast = scaling.get("beta_fast") or 32
        beta_slow = scaling.get("beta_slow") or 1
        orig = (scaling.get("original_max_position_embeddings")
                or cfg.max_position_embeddings)

        def corr_dim(n_rot):
            return (D * math.log(orig / (n_rot * 2 * math.pi))) / (
                2 * math.log(cfg.rope_theta)
            )

        low, high = corr_dim(beta_fast), corr_dim(beta_slow)
        if scaling.get("truncate", True):
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, D - 1)
        ramp = jnp.clip(
            (jnp.arange(D // 2, dtype=jnp.float32) - low)
            / max(high - low, 0.001),
            0.0, 1.0,
        )
        extrap = 1.0 - ramp
        return (inv / factor) * (1 - extrap) + inv * extrap
    kind = scaling.get("rope_type") or scaling.get("type")
    if kind == "linear":
        # position-interpolation scaling (gemma-3 global layers et al.)
        return inv / scaling.get("factor", 1.0)
    if kind == "longrope":
        # Phi-3 LongRoPE: two per-dim rescale-factor sets, selected PER
        # POSITION at the original-context boundary (vLLM's
        # Phi3LongRoPEScaledRotaryEmbedding semantics — the serving
        # standard; HF instead re-ropes the WHOLE sequence when its
        # length crosses the boundary, which an incremental KV cache
        # cannot replay). apply_rope consumes the (stacked-sets,
        # threshold) form.
        orig = (scaling.get("original_max_position_embeddings")
                or cfg.max_position_embeddings)
        short = inv / jnp.asarray(scaling["short_factor"], jnp.float32)
        long = inv / jnp.asarray(scaling["long_factor"], jnp.float32)
        return (jnp.stack([short, long]), orig)
    if scaling.get("rope_type") == "llama3" or scaling.get("type") == "llama3":
        # llama-3.1 NTK-by-parts frequency remap
        factor = scaling.get("factor", 8.0)
        lo = scaling.get("low_freq_factor", 1.0)
        hi = scaling.get("high_freq_factor", 4.0)
        old_ctx = scaling.get("original_max_position_embeddings", 8192)
        wavelen = 2 * jnp.pi / inv
        ratio = old_ctx / wavelen
        smooth = jnp.clip((ratio - lo) / (hi - lo), 0.0, 1.0)
        inv = jnp.where(
            ratio < lo, inv / factor,
            jnp.where(ratio > hi, inv, (1 - smooth) * inv / factor + smooth * inv),
        )
    return inv


def _rope_freqs_local(cfg: ModelConfig):
    """Gemma-3 local rope: sliding layers rotate at rope_local_base_freq
    with NO scaling; None when the model has a single rope."""
    if not cfg.rope_local_theta:
        return None
    D = cfg.head_dim
    return 1.0 / (
        cfg.rope_local_theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D)
    )


def rope_freqs_for_layer(cfg: ModelConfig, l: int, inv_global, inv_local):
    """Layer l's rope frequencies: the LOCAL set on sliding layers when
    the model defines one (gemma-3), the global set elsewhere. Static
    per layer — callers are the unrolled layer loops."""
    if inv_local is None:
        return inv_global
    return inv_local if window_for_layer(cfg, l) > 0 else inv_global


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, inv_freq,
               mscale: float = 1.0) -> jnp.ndarray:
    """x: [..., T, Hx, D] rotated at absolute positions [..., T];
    ``mscale`` is the cos/sin attention factor (YaRN / LongRoPE; 1.0
    elsewhere). ``inv_freq`` is a [D/2] array, or LongRoPE's
    ``([2, D/2] stacked short/long sets, original-context threshold)``
    — each position uses the set its side of the threshold, so an
    incrementally-written KV cache stays self-consistent."""
    if isinstance(inv_freq, tuple):
        sets, orig = inv_freq
        inv = jnp.where(positions[..., None] < orig, sets[0], sets[1])
    else:
        inv = inv_freq
    R = 2 * inv.shape[-1]  # rotary dims; < head_dim = partial rotary
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    xr, x_pass = xf[..., :R], xf[..., R:]
    angles = positions[..., None].astype(jnp.float32) * inv  # [..., T, R/2]
    cos = jnp.cos(angles)[..., None, :] * mscale  # [..., T, 1, R/2]
    sin = jnp.sin(angles)[..., None, :] * mscale
    x1, x2 = jnp.split(xr, 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out.astype(dtype)


def _embed(params: dict, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Token embedding lookup; gemma scales activations by sqrt(E) (the
    table itself must stay unscaled — it is tied to the lm head)."""
    x = params["embed"][tokens]
    if cfg.scale_embed:
        x = (x.astype(jnp.float32) * (cfg.hidden_size ** 0.5)).astype(x.dtype)
    return x


def _mm(x: jnp.ndarray, w) -> jnp.ndarray:
    """Matmul against a plain or quantized weight. Quantized weights are
    ``{"q": int8|float8 [in, out], "s": f32 [out]}`` (models/quant.py);
    the convert fuses into the dot's operand read and the per-channel
    scale into its epilogue, so int8/fp8 storage halves HBM traffic with
    bf16 MXU compute.

    ``{"qn": int8, "s": f32}`` (quantization="int8_native") instead runs
    a REAL int8 dot: activations are dynamically quantized per row
    (absmax/127 over the contraction axis), the s8 x s8 dot accumulates
    in int32 on the MXU, and both scales apply in the f32 epilogue —
    the measured low-precision compute lane, not just narrow storage."""
    if isinstance(w, dict):
        if "qn" in w:
            xf = x.astype(jnp.float32)
            s_x = jnp.maximum(
                jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0, 1e-12
            )
            xq = jnp.clip(jnp.round(xf / s_x), -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, w["qn"],
                (((xq.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            out = acc.astype(jnp.float32) * s_x * w["s"]
            return out.astype(x.dtype)
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def _mm_b(x: jnp.ndarray, lp: dict, w_key: str, b_key: str) -> jnp.ndarray:
    """_mm plus an optional bias leaf (gpt-oss: o_proj carries one)."""
    out = _mm(x, lp[w_key])
    b = lp.get(b_key)
    return out if b is None else out + b


def swiglu(x, w_gate, w_up, w_down, act: str = "silu"):
    gate = _mm(x, w_gate)
    gate = (
        jax.nn.gelu(gate, approximate=True) if act == "gelu_tanh"
        else jax.nn.silu(gate)
    )
    return _mm(gate * _mm(x, w_up), w_down)


def _moe_route(lp: dict, cfg: ModelConfig, x: jnp.ndarray):
    """Top-k routing + expert-sorted dispatch order (shared by the single-
    device and ep-sharded ragged paths). Returns (t_sorted, w_sorted,
    group_sizes): token row per assignment in expert order, its combine
    weight, and per-expert assignment counts.

    Covers Mixtral/Qwen softmax routing AND the DeepSeek variants: V2
    softmax, V3 sigmoid scoring with the no-aux-loss gate bias (bias
    picks the experts, the UNBIASED score is the combine weight) and
    group-limited top-k (score the n_group blocks by their top-2 sum,
    route only within the best topk_group blocks), with
    routed_scaling_factor applied to the final weights."""
    k = cfg.num_experts_per_tok
    vals, idx = _route_topk(lp, cfg, x)
    e_flat = idx.reshape(-1)  # [T*k] row-major: assignment r -> token r//k
    order = jnp.argsort(e_flat)  # stable: deterministic within an expert
    t_sorted = order // k
    w_sorted = vals.reshape(-1)[order]
    e_sorted = e_flat[order]  # expert id per sorted row (expert biases)
    group_sizes = jnp.bincount(e_flat, length=cfg.num_experts)
    return t_sorted, w_sorted, e_sorted, group_sizes


def _route_topk(lp: dict, cfg: ModelConfig, x: jnp.ndarray):
    """(combine weights [T, k], expert indices [T, k]) — ONE scoring
    implementation shared by the ragged, sharded-ragged and dense
    dispatch paths."""
    k = cfg.num_experts_per_tok
    X = cfg.num_experts
    gate_logits = x.astype(jnp.float32) @ lp["moe_gate"].astype(jnp.float32)
    if lp.get("moe_router_bias") is not None:
        # gpt-oss: a LOGIT bias (pre-softmax, affects selection AND
        # combine) — unlike V3's moe_gate_bias, which biases selection
        # on post-score values only
        gate_logits = gate_logits + lp["moe_router_bias"].astype(jnp.float32)
    if cfg.moe_scoring == "sigmoid":
        scores = jax.nn.sigmoid(gate_logits)
    else:
        scores = jax.nn.softmax(gate_logits, axis=-1)  # [T, X]
    sel = scores
    if lp.get("moe_gate_bias") is not None:
        sel = scores + lp["moe_gate_bias"]
    if cfg.n_group > 1 and cfg.topk_group:
        T = sel.shape[0]
        g = sel.reshape(T, cfg.n_group, X // cfg.n_group)
        if cfg.moe_group_score == "top2":  # V3 noaux_tc
            g_score = jnp.sum(lax.top_k(g, 2)[0], axis=-1)  # [T, n_group]
        else:  # V2 group_limited_greedy: the group's max score
            g_score = jnp.max(g, axis=-1)
        _, g_idx = lax.top_k(g_score, cfg.topk_group)
        g_mask = jnp.zeros((T, cfg.n_group), bool).at[
            jnp.arange(T)[:, None], g_idx
        ].set(True)
        # masked groups score 0.0, not -inf — the HF routers mask to 0,
        # and a NEGATIVE biased in-group score must lose to an
        # out-of-group 0 exactly as it does there
        sel = jnp.where(
            jnp.repeat(g_mask, X // cfg.n_group, axis=1), sel, 0.0
        )
    _, idx = lax.top_k(sel, k)  # selection by (biased, group-limited) score
    vals = jnp.take_along_axis(scores, idx, axis=1)  # combine: raw score
    if cfg.norm_topk_prob:
        vals = vals / (jnp.sum(vals, axis=-1, keepdims=True) + 1e-20)
    return vals * cfg.routed_scaling_factor, idx


def _expert_act(cfg: ModelConfig, g: jnp.ndarray, u: jnp.ndarray):
    """Expert gating nonlinearity. gpt-oss clamps both streams and uses
    an alpha-sigmoid GLU with a +1 on the linear stream:
    glu = min(g, 7) * sigmoid(1.702 * min(g, 7)); out = (clip(u) + 1) * glu."""
    if cfg.moe_act == "gptoss_clamp":
        g = jnp.clip(g, None, 7.0)
        u = jnp.clip(u, -7.0, 7.0)
        return (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
    return jax.nn.silu(g) * u


def _ragged_mm(xs, w, group_sizes, use_pallas: bool, interpret: bool):
    """Grouped matmul against a plain or int8/fp8-quantized expert stack
    — the ragged twin of ``_mm``. Quantized stacks ({"q", "s"} nodes,
    models/quant.py) ride the in-repo grouped-dequant Pallas kernel so
    the weights stream from HBM at storage width
    (ops/moe_gmm_pallas.py); without use_pallas they fall back to the
    dequantize-then-ragged_dot XLA reference (CPU tests — the fallback
    materializes the bf16 stack and exists for correctness only)."""
    if isinstance(w, dict):
        from ..ops.moe_gmm_pallas import ragged_int8_gmm, ragged_int8_xla

        if use_pallas:
            out = ragged_int8_gmm(xs, w["q"], w["s"], group_sizes,
                                  interpret=interpret)
        else:
            out = ragged_int8_xla(xs, w["q"], w["s"], group_sizes)
        return out.astype(xs.dtype)
    return lax.ragged_dot(xs, w, group_sizes)


def _dense_expert_mm(x, w, spec: str):
    """Dense-dispatch einsum against a plain or quantized expert stack:
    both dispatch einsums produce [T, X, out] with scales [X, out], so
    one broadcast covers gate/up and down."""
    if isinstance(w, dict):
        out = jnp.einsum(spec, x, w["q"].astype(x.dtype))
        return out * w["s"][None].astype(out.dtype)
    return jnp.einsum(spec, x, w)


def _moe_combine(o, t_sorted, w_sorted, T: int, dtype):
    """Scatter-add expert outputs back to token rows. ``t_sorted`` entries
    of masked rows point at the sacrificial row T, sliced off."""
    out = jnp.zeros((T + 1, o.shape[-1]), dtype)
    out = out.at[t_sorted].add(o * w_sorted[:, None].astype(dtype))
    return out[:T]


def moe_ffn(
    lp: dict, cfg: ModelConfig, x: jnp.ndarray, mesh=None,
    use_pallas: bool = False, interpret: bool = False,
) -> jnp.ndarray:
    """Mixtral/DeepSeek-style sparse MoE FFN with RAGGED dispatch (ref
    serves these via vLLM's fused_moe grouped-GEMM CUDA kernels; the TPU
    equivalent is ``lax.ragged_dot`` — XLA's grouped matmul).

    Tokens are sorted by assigned expert and each expert contracts only
    its own contiguous row group, so per-token FLOPs scale with top-k, not
    with the expert count (dense dispatch computed every expert for every
    token — X/k times the work, fatal at Mixtral-8x22B scale). Exact: no
    capacity factor, no token dropping.

    With a mesh, the dispatch runs under shard_map over (ep, tp): experts
    are ep-sharded (parallel/mesh.py we_* specs) so each device slices the
    expert-sorted rows at its own traced offset — a static [T*k]-row
    window, masked to its true count — and the token-scatter combine
    psum-reduces over ep (the expert combine) and tp (the down-projection
    contraction). Routing is computed replicated per device: T×X scalar
    work, negligible beside the expert GEMMs.

    Three paths: no mesh -> plain ragged_dot; mesh + divisible shapes ->
    shard_map ragged; mesh but indivisible shapes (or ep/tp axes absent)
    -> dense dispatch. The last is deliberate: ragged_dot's group axis is
    opaque to GSPMD, so running it on ep-sharded weights would all-gather
    every expert onto every device — the dense einsum's contraction over
    experts IS GSPMD's expert-parallel reduce, making it the safe (if
    FLOP-heavier) fallback for odd shapes.
    """
    T = x.shape[0]
    out_dt = x.dtype
    if mesh is None:
        t_sorted, w_sorted, e_sorted, group_sizes = _moe_route(lp, cfg, x)
        xs = x[t_sorted]
        g = _ragged_mm(xs, lp["we_gate"], group_sizes, use_pallas, interpret)
        u = _ragged_mm(xs, lp["we_up"], group_sizes, use_pallas, interpret)
        if "be_gate" in lp:  # gpt-oss per-expert projection biases
            g = g + lp["be_gate"][e_sorted]
            u = u + lp["be_up"][e_sorted]
        o = _ragged_mm(_expert_act(cfg, g, u), lp["we_down"], group_sizes,
                       use_pallas, interpret)
        if "be_down" in lp:
            o = o + lp["be_down"][e_sorted]
        out = _moe_combine(o, t_sorted, w_sorted, T, out_dt)
    elif _moe_can_shard(mesh, cfg):
        out = _moe_ragged_sharded(lp, cfg, x, mesh, use_pallas, interpret)
        if "be_down" in lp:
            # the down-projection bias is added OUTSIDE the shard_map:
            # inside, the tp psum over the Fm contraction would count it
            # tp times. Per token it is sum_k w_k * be_down[e_k] — the
            # replicated routing matrix against [X, E], trivially
            # GSPMD-safe and exact.
            vals, idx = _route_topk(lp, cfg, x)
            w = jnp.sum(
                jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.float32)
                * vals[..., None],
                axis=1,
            )  # [T, X]
            out = out + (w @ lp["be_down"].astype(jnp.float32)).astype(out_dt)
    else:
        out = _moe_dense_dispatch(lp, cfg, x)
    if "shared_gate" in lp:
        out = out + _shared_expert(lp, x)
    return out


def _shared_expert(lp: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Shared-expert contribution: DeepSeek's is always-on; Qwen2-MoE
    gates it per token with sigmoid(x @ shared_expert_gate)."""
    shared = swiglu(x, lp["shared_gate"], lp["shared_up"], lp["shared_down"])
    if "shared_egate" in lp:
        g = jax.nn.sigmoid(
            x.astype(jnp.float32) @ lp["shared_egate"].astype(jnp.float32)
        )
        shared = shared * g.astype(shared.dtype)
    return shared


def _moe_dense_dispatch(lp: dict, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    """Dense dispatch: every expert computes every token, the routing
    matrix (zero except each token's top-k) selects at combine. X/k times
    the ragged path's expert-GEMM FLOPs, but fully GSPMD-shardable — the
    equivalence ground truth for tests and the mesh fallback for shapes
    the shard_map ragged path can't cover."""
    vals, idx = _route_topk(lp, cfg, x)  # [T, k]
    w = jnp.sum(
        jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.float32)
        * vals[..., None],
        axis=1,
    )  # [T, X] routing weights
    g = _dense_expert_mm(x, lp["we_gate"], "te,xef->txf")
    u = _dense_expert_mm(x, lp["we_up"], "te,xef->txf")
    if "be_gate" in lp:  # gpt-oss per-expert projection biases
        g = g + lp["be_gate"][None]
        u = u + lp["be_up"][None]
    y = _dense_expert_mm(_expert_act(cfg, g, u), lp["we_down"], "txf,xfe->txe")
    if "be_down" in lp:
        y = y + lp["be_down"][None]
    return jnp.einsum("txe,tx->te", y, w.astype(x.dtype))


def moe_ffn_dense(lp: dict, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    """Full dense-dispatch reference (incl. shared experts) for tests."""
    out = _moe_dense_dispatch(lp, cfg, x)
    if "shared_gate" in lp:
        out = out + _shared_expert(lp, x)
    return out


def _moe_can_shard(mesh, cfg: ModelConfig) -> bool:
    if not {"ep", "tp"} <= set(mesh.axis_names):
        return False
    fm = cfg.moe_intermediate_size or cfg.intermediate_size
    return (
        cfg.num_experts % mesh.shape["ep"] == 0 and fm % mesh.shape["tp"] == 0
    )


def _moe_ragged_sharded(lp: dict, cfg: ModelConfig, x: jnp.ndarray, mesh,
                        use_pallas: bool = False, interpret: bool = False):
    """shard_map body for ragged MoE over (ep, tp); other axes stay auto.

    gpt-oss rides this path too: the router LOGIT bias is replicated into
    the routing computation, and the per-expert gate/up projection biases
    are ep×tp-sharded with their weights and indexed by each window row's
    LOCAL expert id (recovered from the cumulative local group sizes).
    The down bias is the caller's job (moe_ffn adds it outside — inside,
    the tp psum would multiply it)."""
    from jax.sharding import PartitionSpec as P

    T = x.shape[0]
    X = cfg.num_experts
    R = T * cfg.num_experts_per_tok
    ep = mesh.shape["ep"]
    Xl = X // ep
    out_dt = x.dtype
    has_eb = "be_gate" in lp

    def body(x, moe_gate, gate_bias, router_bias, we_gate, we_up, we_down,
             be_gate, be_up):
        t_sorted, w_sorted, _e_sorted, group_sizes = _moe_route(
            {"moe_gate": moe_gate, "moe_gate_bias": gate_bias,
             "moe_router_bias": router_bias}, cfg, x
        )
        first = lax.axis_index("ep") * Xl
        gs_local = lax.dynamic_slice_in_dim(group_sizes, first, Xl)
        start = jnp.sum(jnp.where(jnp.arange(X) < first, group_sizes, 0))
        count = jnp.sum(gs_local)
        # static [R]-row window at this device's traced offset; rows past
        # ``count`` belong to other devices' experts and are masked out
        xs = jnp.concatenate([x[t_sorted], jnp.zeros_like(x[t_sorted])], 0)
        xs = lax.dynamic_slice_in_dim(xs, start, R)
        t_l = lax.dynamic_slice_in_dim(
            jnp.concatenate([t_sorted, jnp.full((R,), T, t_sorted.dtype)]),
            start, R,
        )
        w_l = lax.dynamic_slice_in_dim(
            jnp.concatenate([w_sorted, jnp.zeros((R,), w_sorted.dtype)]),
            start, R,
        )
        valid = jnp.arange(R) < count
        t_l = jnp.where(valid, t_l, T)  # sacrificial combine row
        w_l = jnp.where(valid, w_l, 0.0)
        g = _ragged_mm(xs, we_gate, gs_local, use_pallas, interpret)
        u = _ragged_mm(xs, we_up, gs_local, use_pallas, interpret)
        if has_eb:
            # window row r's LOCAL expert: first local group whose
            # cumulative size exceeds r (masked tail rows clamp to the
            # last expert; their combine weight is already zero)
            e_l = jnp.searchsorted(
                jnp.cumsum(gs_local), jnp.arange(R), side="right"
            )
            e_l = jnp.minimum(e_l, Xl - 1)
            g = g + be_gate[e_l]
            u = u + be_up[e_l]
        o = _ragged_mm(_expert_act(cfg, g, u), we_down, gs_local,
                       use_pallas, interpret)
        out = _moe_combine(o, t_l, w_l, T, out_dt)
        return lax.psum(out, ("ep", "tp"))

    def _z(key, shape):  # uniform operand pytree for the shard_map
        v = lp.get(key)
        return v if v is not None else jnp.zeros(shape, jnp.float32)

    def _wspec(w, spec: P) -> object:
        # quantized stacks ({"q", "s"}) shard q like the plain weight
        # and s with the contraction axis dropped (mirrors
        # parallel/mesh._spec_for's derivation for the placed pytree)
        if isinstance(w, dict):
            ps = tuple(spec)
            return {"q": spec, "s": P(*ps[:-2], ps[-1])}
        return spec

    wg, wu, wd = lp["we_gate"], lp["we_up"], lp["we_down"]
    Fm = (wg["q"] if isinstance(wg, dict) else wg).shape[-1]
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(),  # x replicated (batch inputs are replicated engine-side)
            P(),  # router weights replicated
            P(),  # V3 no-aux gate bias (zeros when absent)
            P(),  # gpt-oss router logit bias (zeros when absent)
            _wspec(wg, P("ep", None, "tp")),  # we_gate [X, E, Fm]
            _wspec(wu, P("ep", None, "tp")),  # we_up
            _wspec(wd, P("ep", "tp", None)),  # we_down [X, Fm, E]
            P("ep", "tp"),  # be_gate [X, Fm] (zeros when absent)
            P("ep", "tp"),  # be_up
        ),
        out_specs=P(),
        check_vma=False,
    )(x, lp["moe_gate"], _z("moe_gate_bias", (X,)),
      _z("moe_router_bias", (X,)), wg, wu, wd,
      _z("be_gate", (X, Fm)), _z("be_up", (X, Fm)))


def _ffn(lp: dict, cfg: ModelConfig, h: jnp.ndarray, mesh=None,
         use_pallas: bool = False, interpret: bool = False) -> jnp.ndarray:
    # branch on the GROUP's own leaves, not cfg.is_moe: DeepSeek's
    # first_k_dense_replace layers are dense inside an MoE model
    if "moe_gate" in lp:
        return moe_ffn(lp, cfg, h, mesh=mesh, use_pallas=use_pallas,
                       interpret=interpret)
    return swiglu(h, lp["w_gate"], lp["w_up"], lp["w_down"], cfg.hidden_act)


def _logits(params: dict, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    return att.softcap((x @ head).astype(jnp.float32), cfg.final_softcap)


def _qkv(lp: dict, cfg: ModelConfig, x: jnp.ndarray, lora_l=None,
         lora_ids=None, lora_grouped: bool = False):
    q = _mm(x, lp["wq"])
    k = _mm(x, lp["wk"])
    v = _mm(x, lp["wv"])
    if lora_l is not None:
        # per-row LoRA deltas land on the FLAT projections, before bias
        # and qk-norm (norms see base+delta exactly as a merged-weight
        # forward would); rows with id -1 get an exact +0.0
        from ..ops.lora import lora_delta

        q = q + lora_delta(x, lora_l["qa"], lora_l["qb"], lora_ids,
                           lora_grouped)
        k = k + lora_delta(x, lora_l["ka"], lora_l["kb"], lora_ids,
                           lora_grouped)
        v = v + lora_delta(x, lora_l["va"], lora_l["vb"], lora_ids,
                           lora_grouped)
    if "bq" in lp:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    if cfg.qk_norm_full:  # olmo-2: norm the FLAT projection pre-reshape
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    # head counts derive from the projection width, not cfg: under a
    # manual-tp shard_map (parallel/pp.py) lp holds per-device column
    # shards, so this one function serves both global and tp-local views
    D = cfg.head_dim
    q = q.reshape(x.shape[:-1] + (q.shape[-1] // D, D))
    k = k.reshape(x.shape[:-1] + (k.shape[-1] // D, D))
    v = v.reshape(x.shape[:-1] + (v.shape[-1] // D, D))
    if cfg.qk_norm and not cfg.qk_norm_full:
        # qwen3: per-head RMS norm before rope, weight [D]
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _wo_proj(lp: dict, o_flat: jnp.ndarray, lora_l=None, lora_ids=None,
             lora_grouped: bool = False) -> jnp.ndarray:
    """Attention output projection (+ optional per-row LoRA delta on the
    flat [R, H*D] rows, mirroring ``_qkv``'s q/k/v deltas)."""
    p = _mm_b(o_flat, lp, "wo", "bo")
    if lora_l is not None:
        from ..ops.lora import lora_delta

        p = p + lora_delta(o_flat, lora_l["oa"], lora_l["ob"], lora_ids,
                           lora_grouped)
    return p


# ---------------- prefill (one sequence, chunked) ----------------


@partial(
    jax.jit,
    static_argnames=("cfg", "use_pallas", "mesh", "use_ring"),
    donate_argnames=("k_cache", "v_cache"),
)
def prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [T] padded chunk
    block_table: jnp.ndarray,  # [M] covers history + padded chunk
    history_len: jnp.ndarray,  # scalar int32: tokens already cached
    valid_len: jnp.ndarray,  # scalar int32: real tokens in this chunk
    k_cache: jnp.ndarray,  # [L, N, bs, Hkv, D] (donated)
    v_cache: jnp.ndarray,
    use_pallas: bool = False,
    mesh=None,
    use_ring: bool = False,
    # int8-with-scales device cache: per-page f32 scale planes [L, N]
    # (NOT donated — the engine diffs them for gauges). When present the
    # chunk lands quantized and the return grows to
    # (logits, k_cache, v_cache, k_scales, v_scales).
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
    # multi-LoRA lane: stacked {qa,qb,ka,kb,va,vb,oa,ob} [L, NA, ...]
    # adapter pytree + this sequence's adapter slot (scalar int32; -1 =
    # base model — the deltas are then an exact +0.0). The return shape
    # is unchanged; lora forces the unrolled layer loop.
    lora=None,
    adapter_id: Optional[jnp.ndarray] = None,
):
    """Process one (chunk of a) prompt; returns (last_hidden_logits, caches).

    Supports chunked prefill and prefix-cache hits: ``history_len`` tokens
    are already in the cache and are attended to but not recomputed
    (the reference gets this from vLLM's chunked-prefill scheduler patch).

    On a pp>1 mesh (dense models, divisible shapes) the layer loop runs
    as a STAGED PIPELINE: microbatches flow through the pp stages via
    ppermute so stages compute concurrently (parallel/pp.py), instead of
    the scan all-gathering one stage's weights per step.

    ``use_ring`` (static; history-free chunks only — the ENGINE gates it
    on history == 0, an sp>1 mesh, T % sp == 0, prompt length >= its
    ring threshold, full attention) routes the chunk's self-attention
    through sequence-parallel ring attention over the sp axis
    (parallel/ring_attention.py) instead of the dense score matrix:
    each device holds T/sp query rows and the KV shards — or, for MLA,
    the far smaller compressed (c_kv, k_pe) latent shards — rotate the
    ICI ring. Cache writes are unchanged, so decode and later chunked
    prefill continue through the paged path.
    """
    quantized = k_scales is not None
    if quantized:
        # scale planes thread per layer, so: no staged pipeline (stage
        # movers don't carry planes), no ring (ring writes full-width),
        # no MLA (the engine gates MLA+int8 loudly at init)
        assert not use_ring and not cfg.is_mla
    if lora is not None:
        # adapters slice per layer (unrolled loop), don't ride the
        # staged pipeline, and MLA/ring are gated at engine init
        assert not use_ring and not cfg.is_mla
        lora_ids = jnp.full((tokens.shape[0],), adapter_id, jnp.int32)
    else:
        lora_ids = None
    if mesh is not None and not use_ring and not quantized and lora is None:
        from ..parallel.pp import can_pipeline, pick_n_micro, pipelined_prefill

        n_micro = pick_n_micro(mesh, tokens.shape[0])
        if can_pipeline(mesh, cfg, tokens.shape[0], n_micro):
            return pipelined_prefill(
                params, cfg, tokens, block_table, history_len, valid_len,
                k_cache, v_cache, mesh, n_micro, use_pallas=use_pallas,
            )
    if use_ring:
        assert mesh is not None and mesh.shape.get("sp", 1) > 1
        assert cfg.sliding_window == 0 and not cfg.layer_windows
        assert not cfg.attn_sinks
    T = tokens.shape[0]
    x = _embed(params, cfg, tokens)  # [T, E]
    positions = history_len + jnp.arange(T)
    if cfg.is_mla:
        from . import mla

        inv_freq, msc = mla.mla_rope_freqs(cfg)
        scale = cfg.mla_softmax_scale()
    else:
        inv_freq = _rope_freqs(cfg)
        rope_msc = _rope_attention_scaling(cfg)
        scale = attn_query_scale(cfg)

    inv_local = _rope_freqs_local(cfg)

    def body(carry, layer_in, window=cfg.sliding_window, freqs=None,
             scales=None, lora_l=None):
        x = carry
        lp, kc, vc = layer_in
        h = pre_norm(lp, "attn_norm", x, cfg)
        if cfg.is_mla:
            from . import mla

            q_eff, q_pe, c_kv, k_pe = mla.mla_q_and_latent(
                lp, cfg, h, positions, inv_freq, msc
            )
            kc = att.write_chunk_to_cache(
                kc, c_kv[:, None, :], block_table, history_len
            )
            vc = att.write_chunk_to_cache(
                vc, k_pe[:, None, :], block_table, history_len
            )
            if use_ring:
                # sequence-parallel exact attention over the sp ring,
                # rotating the COMPRESSED latents (C+R elements/token of
                # ICI traffic instead of 2*H*D of repeated K/V)
                from ..parallel.ring_attention import (
                    mla_ring_attention_sharded,
                )

                out_lat = mla_ring_attention_sharded(
                    q_eff, q_pe, c_kv, k_pe, mesh, scale
                )
            elif use_pallas and mesh is not None:
                from ..ops import mla_attention_pallas as _mla_ops

                out_lat = _mla_ops.mla_paged_prefill_attention_sharded(
                    q_eff, q_pe, kc, vc, block_table, history_len, scale,
                    mesh,
                )
            elif use_pallas:
                from ..ops import mla_attention_pallas as _mla_ops

                out_lat = _mla_ops.mla_paged_prefill_attention(
                    q_eff, q_pe, kc, vc, block_table, history_len, scale,
                )
            else:
                out_lat = mla.mla_prefill_attention_xla(
                    q_eff, q_pe, kc, vc, block_table, history_len,
                    valid_len, scale,
                )
            o = mla._o_proj(lp, cfg, out_lat).astype(x.dtype)
            x = x + _mm(o, lp["wo"])
        else:
            q, k, v = _qkv(lp, cfg, h, lora_l, lora_ids)
            fr = inv_freq if freqs is None else freqs
            q = apply_rope(q, positions, fr, rope_msc)
            k = apply_rope(k, positions, fr, rope_msc)
            if scales is None:
                ks_l = vs_l = None
                kc = att.write_chunk_to_cache(kc, k, block_table, history_len)
                vc = att.write_chunk_to_cache(vc, v, block_table, history_len)
            else:
                ks_l, vs_l = scales
                kc, ks_l = att.write_chunk_to_cache_quantized(
                    kc, ks_l, k, block_table, history_len, valid_len
                )
                vc, vs_l = att.write_chunk_to_cache_quantized(
                    vc, vs_l, v, block_table, history_len, valid_len
                )
            if use_ring:
                from ..parallel.ring_attention import ring_attention_sharded

                H = q.shape[1]
                o = ring_attention_sharded(
                    q, att.repeat_kv(k, H // k.shape[1], axis=1),
                    att.repeat_kv(v, H // v.shape[1], axis=1),
                    mesh, scale,
                )
            else:
                o = att.chunk_attention_with_cache(
                    q, k, v, kc, vc, block_table, history_len, valid_len,
                    scale, use_pallas=use_pallas, mesh=mesh,
                    window=window, sinks=lp.get("sinks"),
                    cap=cfg.attn_softcap,
                    k_scales=ks_l, v_scales=vs_l,
                )
            x = x + post_norm(
                lp, "attn_post_norm",
                _wo_proj(lp, o.reshape(T, -1), lora_l, lora_ids), cfg,
            )
        h = pre_norm(lp, "mlp_norm", x, cfg)
        x = x + post_norm(
            lp, "mlp_post_norm",
            _ffn(lp, cfg, h, mesh=mesh, use_pallas=use_pallas), cfg,
        )
        if scales is not None:
            return x, (kc, vc, ks_l, vs_l)
        return x, (kc, vc)

    def lora_for_layer(l):
        return (
            None if lora is None
            else jax.tree.map(lambda arr: arr[l], lora)
        )

    if quantized:
        # per-layer scale-plane slices must thread through every write,
        # so the layer loop unrolls (the scan body cannot in-place
        # scatter the planes without a full re-stack copy per layer)
        for lps, n, off in layer_groups(params, cfg):
            for li in range(n):
                l = off + li
                lp = jax.tree.map(lambda a: a[li], lps)
                x, (kc_l, vc_l, ks_l, vs_l) = body(
                    x, (lp, k_cache[l], v_cache[l]),
                    window=window_for_layer(cfg, l),
                    freqs=rope_freqs_for_layer(cfg, l, inv_freq, inv_local),
                    scales=(k_scales[l], v_scales[l]),
                    lora_l=lora_for_layer(l),
                )
                k_cache = k_cache.at[l].set(kc_l)
                v_cache = v_cache.at[l].set(vc_l)
                k_scales = k_scales.at[l].set(ks_l)
                v_scales = v_scales.at[l].set(vs_l)
    elif cfg.layer_windows or lora is not None:
        # heterogeneous attention (gpt-oss alternating sliding/full):
        # the window width is trace-static PER LAYER, so the layer loop
        # unrolls — a lax.scan body cannot carry a per-layer mask shape.
        # LoRA rides the same unrolled loop: adapter stacks slice per
        # layer with a static index (quantized-KV precedent).
        for lps, n, off in layer_groups(params, cfg):
            for li in range(n):
                l = off + li
                lp = jax.tree.map(lambda a: a[li], lps)
                x, (kc_l, vc_l) = body(
                    x, (lp, k_cache[l], v_cache[l]),
                    window=window_for_layer(cfg, l),
                    freqs=rope_freqs_for_layer(cfg, l, inv_freq, inv_local),
                    lora_l=lora_for_layer(l),
                )
                k_cache = k_cache.at[l].set(kc_l)
                v_cache = v_cache.at[l].set(vc_l)
    else:
        x, k_cache, v_cache = _scan_groups(
            body, x, params, cfg, k_cache, v_cache
        )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # logits for the last *real* token of the chunk
    last = jnp.clip(valid_len - 1, 0, T - 1)
    logits = _logits(params, cfg, x[last])
    if quantized:
        return logits, k_cache, v_cache, k_scales, v_scales
    return logits, k_cache, v_cache


# ---------------- batched decode step ----------------


def _decode_body(
    params, cfg, tokens, positions, block_tables, seq_lens,
    k_cache, v_cache, use_pallas, mesh=None, unroll=True, interpret=False,
    merged=True, k_scales=None, v_scales=None, lora=None, adapter_ids=None,
):
    """Shared un-jitted decode forward (one token per sequence).

    ``unroll=True`` (default) runs an UNROLLED python loop over layers
    with static layer indices: the caches are updated by tiny in-place
    scatters on the donated stacked arrays and read by static slices.
    The scan variant threads the caches as scan xs/ys, and XLA
    materializes the re-stacked ys — a full extra cache copy per decode
    step (measured: a 2.15GB cache pair costs ~2.5GB of temp and
    dominates step time; decode is supposed to stream WEIGHTS, not
    copy the KV pool). Scan remains for compile-time-sensitive very
    deep models (EngineConfig.decode_layer_scan).

    ``k_scales``/``v_scales`` ([L, N] f32, int8-with-scales device cache)
    thread through every write (scale growth + page requant) and attention
    read (fused dequant); when present the return grows to
    (logits, k_cache, v_cache, k_scales, v_scales, n_requants)."""
    quantized = k_scales is not None
    if quantized:
        if cfg.is_mla:
            raise ValueError("int8 device KV scales: MLA is gated at "
                             "engine init (absorbed-matmul latents)")
        if not unroll:
            raise ValueError("int8 device KV scales need the unrolled "
                             "decode (decode_layer_scan cannot carry "
                             "per-layer plane scatters in place)")
        k_scales0, v_scales0 = k_scales, v_scales
    if lora is not None:
        if cfg.is_mla:
            raise ValueError("LoRA adapters: MLA is gated at engine init "
                             "(deltas attach to the GQA projections)")
        if not unroll:
            raise ValueError("LoRA adapters need the unrolled decode "
                             "(decode_layer_scan cannot slice per-layer "
                             "adapter stacks)")
    B = tokens.shape[0]
    x = _embed(params, cfg, tokens)  # [B, E]
    if cfg.is_mla:
        from . import mla as _mla
        from ..ops import mla_attention_pallas as _mla_ops

        inv_freq, msc = _mla.mla_rope_freqs(cfg)
        scale = cfg.mla_softmax_scale()
    else:
        inv_freq = _rope_freqs(cfg)
        rope_msc = _rope_attention_scaling(cfg)
        scale = attn_query_scale(cfg)

    def layer_tail(x, lp, o, lora_l=None):
        x = x + post_norm(
            lp, "attn_post_norm",
            _wo_proj(lp, o.reshape(B, -1), lora_l, adapter_ids), cfg,
        )
        h = pre_norm(lp, "mlp_norm", x, cfg)
        return x + post_norm(
            lp, "mlp_post_norm",
            _ffn(lp, cfg, h, mesh=mesh, use_pallas=use_pallas,
                 interpret=interpret), cfg,
        )

    inv_local_dec = _rope_freqs_local(cfg)

    def layer_qkv(x, lp, freqs=None, lora_l=None):
        h = pre_norm(lp, "attn_norm", x, cfg)
        # q: [B, H, D], k/v: [B, Hkv, D]
        q, k, v = _qkv(lp, cfg, h, lora_l, adapter_ids)
        fr = inv_freq if freqs is None else freqs
        q = apply_rope(q, positions, fr, rope_msc)
        k = apply_rope(k, positions, fr, rope_msc)
        return q, k, v

    def lora_for_layer(l):
        return (
            None if lora is None
            else jax.tree.map(lambda arr: arr[l], lora)
        )

    def mla_layer(x, lp, kc_l, vc_l):
        """One MLA decode layer against full cache layers kc_l/vc_l:
        write the token's latent, absorbed attention (latent kernel when
        use_pallas, XLA gather otherwise), output fold."""
        h = pre_norm(lp, "attn_norm", x, cfg)
        q_eff, q_pe, c_kv, k_pe = _mla.mla_q_and_latent(
            lp, cfg, h, positions, inv_freq, msc
        )
        # ADJACENT advanced indices (blk, off) stay in place (unlike the
        # non-MLA [l, :, blk, off] form where the scalar l separates
        # them): the slice is [1, B, D], so the update is value[None]
        kc_l = kc_l.at[:, blk, off].set(c_kv[None].astype(kc_l.dtype))
        vc_l = vc_l.at[:, blk, off].set(k_pe[None].astype(vc_l.dtype))
        if use_pallas and mesh is not None:
            o = _mla_ops.mla_paged_decode_attention_sharded(
                q_eff, q_pe, kc_l, vc_l, block_tables, seq_lens, scale,
                mesh, interpret=interpret,
            )
        elif use_pallas:
            o = _mla_ops.mla_paged_decode_attention(
                q_eff, q_pe, kc_l, vc_l, block_tables, seq_lens, scale,
                interpret=interpret,
            )
        else:
            o = _mla.mla_decode_attention_xla(
                q_eff, q_pe, kc_l, vc_l, block_tables, seq_lens, scale
            )
        o = _mla._o_proj(lp, cfg, o).astype(x.dtype)
        return layer_tail(x, lp, o), kc_l, vc_l

    # slot indices are used by the unrolled paths AND the MLA scan body
    blk, off = att.decode_slot_indices(
        block_tables, positions, k_cache.shape[3]
    )
    mla_merged = merged and unroll and use_pallas and cfg.is_mla
    # sinks join the flash-merge denominator and per-layer windows are
    # static per (unrolled) layer call, so gpt-oss runs the merged
    # one-write path like every other GQA family
    merged = (
        merged and unroll and use_pallas and not cfg.is_mla
        and not cfg.attn_softcap  # gemma-2 caps live in the XLA paths
    )
    if mla_merged:
        # MERGED one-write path, MLA flavor: the latent kernel scores
        # history with stats, the current token's (c_kv, k_pe) folds in
        # via the flash merge, and ALL layers' latent writes batch into
        # one in-place Pallas append — same 2L-scatters-to-1-append trick
        # as the GQA merged branch below. On a mesh the query heads are
        # the parallel axis and the latent cache replicates (MQA shape —
        # see parallel/mesh.cache_sharding), so attention shard_maps over
        # tp and every device RMWs its cache replica.
        from ..ops.kv_cache_update_pallas import (
            kv_cache_append,
            kv_cache_append_replicated,
        )

        hist_lens = seq_lens - 1  # cache contents EXCLUDE the new token
        c_news, pe_news = [], []
        for lps, n, goff in layer_groups(params, cfg):
            for li in range(n):
                l = goff + li
                lp = jax.tree.map(lambda a: a[li], lps)
                h = pre_norm(lp, "attn_norm", x, cfg)
                q_eff, q_pe, c_kv, k_pe = _mla.mla_q_and_latent(
                    lp, cfg, h, positions, inv_freq, msc
                )
                c_news.append(c_kv)
                pe_news.append(k_pe)
                if mesh is None:
                    o_lat = _mla_ops.mla_decode_attention_merged(
                        q_eff, q_pe, c_kv, k_pe, k_cache[l], v_cache[l],
                        block_tables, hist_lens, scale, interpret=interpret,
                    )
                else:
                    o_lat = _mla_ops.mla_decode_attention_merged_sharded(
                        q_eff, q_pe, c_kv, k_pe, k_cache[l], v_cache[l],
                        block_tables, hist_lens, scale, mesh,
                        interpret=interpret,
                    )
                o = _mla._o_proj(lp, cfg, o_lat).astype(x.dtype)
                x = layer_tail(x, lp, o)
        c_stack = jnp.stack(c_news)[:, :, None, :]  # [L, B, 1, C]
        pe_stack = jnp.stack(pe_news)[:, :, None, :]  # [L, B, 1, R]
        if mesh is None:
            k_cache, v_cache = kv_cache_append(
                c_stack, pe_stack, k_cache, v_cache, blk, off,
                interpret=interpret,
            )
        else:
            k_cache, v_cache = kv_cache_append_replicated(
                c_stack, pe_stack, k_cache, v_cache, blk, off, mesh,
                interpret=interpret,
            )
    elif cfg.is_mla and unroll:
        for lps, n, goff in layer_groups(params, cfg):
            for li in range(n):
                l = goff + li
                lp = jax.tree.map(lambda a: a[li], lps)
                x, kc_l, vc_l = mla_layer(x, lp, k_cache[l], v_cache[l])
                k_cache = k_cache.at[l].set(kc_l)
                v_cache = v_cache.at[l].set(vc_l)
    elif cfg.is_mla:
        def mla_body(carry, layer_in):
            x = carry
            lp, kc, vc = layer_in
            x, kc, vc = mla_layer(x, lp, kc, vc)
            return x, (kc, vc)

        x, k_cache, v_cache = _scan_groups(
            mla_body, x, params, cfg, k_cache, v_cache
        )
    elif merged:
        # MERGED one-write path (TPU): attention handles the current token
        # out-of-cache (flash merge over the stats-emitting paged kernel),
        # so the cache sees ONE in-place Pallas append per step instead of
        # 2L XLA scatters — XLA will not update scatters of this shape in
        # place; each one copied the full cache (measured ~0.55 GB/copy on
        # the 1B bench config; the reference's equivalent split is vLLM's
        # reshape_and_cache + paged attention). On a mesh, every piece is
        # kv-head-parallel and runs under shard_map over tp (the engine
        # only sets use_pallas when tp divides the kv heads).
        from ..ops.kv_cache_update_pallas import (
            kv_cache_append,
            kv_cache_append_quantized,
            kv_cache_append_quantized_sharded,
            kv_cache_append_sharded,
        )

        hist_lens = seq_lens - 1  # cache contents EXCLUDE the new token
        k_news, v_news = [], []
        for lps, n, goff in layer_groups(params, cfg):
            for li in range(n):
                l = goff + li
                lp = jax.tree.map(lambda a: a[li], lps)
                lora_l = lora_for_layer(l)
                q, k, v = layer_qkv(
                    x, lp,
                    rope_freqs_for_layer(cfg, l, inv_freq, inv_local_dec),
                    lora_l=lora_l,
                )
                k_news.append(k)
                v_news.append(v)
                # history pages dequantize through the step-entry scale
                # planes — consistent: the batched append below is what
                # mutates pages/scales, and it runs after attention
                ks_l = k_scales[l] if quantized else None
                vs_l = v_scales[l] if quantized else None
                if mesh is None:
                    o = att.decode_attention_merged(
                        q, k, v, k_cache[l], v_cache[l], block_tables,
                        hist_lens, scale, window=window_for_layer(cfg, l),
                        sinks=lp.get("sinks"), interpret=interpret,
                        k_scales=ks_l, v_scales=vs_l,
                    )
                else:
                    o = att.decode_attention_merged_sharded(
                        q, k, v, k_cache[l], v_cache[l], block_tables,
                        hist_lens, scale, mesh,
                        window=window_for_layer(cfg, l),
                        sinks=lp.get("sinks"), interpret=interpret,
                        k_scales=ks_l, v_scales=vs_l,
                    )
                x = layer_tail(x, lp, o, lora_l=lora_l)
        k_new, v_new = jnp.stack(k_news), jnp.stack(v_news)
        if quantized:
            if mesh is None:
                k_cache, v_cache, k_scales, v_scales, _ = (
                    kv_cache_append_quantized(
                        k_new, v_new, k_cache, v_cache, k_scales, v_scales,
                        blk, off, interpret=interpret,
                    )
                )
            else:
                k_cache, v_cache, k_scales, v_scales, _ = (
                    kv_cache_append_quantized_sharded(
                        k_new, v_new, k_cache, v_cache, k_scales, v_scales,
                        blk, off, mesh, interpret=interpret,
                    )
                )
        elif mesh is None:
            k_cache, v_cache = kv_cache_append(
                k_new, v_new, k_cache, v_cache, blk, off,
                interpret=interpret,
            )
        else:
            k_cache, v_cache = kv_cache_append_sharded(
                k_new, v_new, k_cache, v_cache, blk, off, mesh,
                interpret=interpret,
            )
    elif unroll:
        for lps, n, goff in layer_groups(params, cfg):
            for li in range(n):
                l = goff + li
                lp = jax.tree.map(lambda a: a[li], lps)
                lora_l = lora_for_layer(l)
                q, k, v = layer_qkv(
                    x, lp,
                    rope_freqs_for_layer(cfg, l, inv_freq, inv_local_dec),
                    lora_l=lora_l,
                )
                ks_l = vs_l = None
                if quantized:
                    # write-before-attend: the row quantizes against the
                    # (possibly grown) page scale, then attention
                    # dequantizes through the SAME updated plane slice
                    kc_l, ks_l = att.write_decode_token_to_cache_quantized(
                        k_cache[l], k_scales[l], k, block_tables, positions
                    )
                    vc_l, vs_l = att.write_decode_token_to_cache_quantized(
                        v_cache[l], v_scales[l], v, block_tables, positions
                    )
                    k_cache = k_cache.at[l].set(kc_l)
                    v_cache = v_cache.at[l].set(vc_l)
                    k_scales = k_scales.at[l].set(ks_l)
                    v_scales = v_scales.at[l].set(vs_l)
                else:
                    # mixed basic+advanced indexing puts the advanced axes
                    # (blk, off) in front: the update value is [B, Hkv, D]
                    k_cache = k_cache.at[l, :, blk, off].set(
                        k.astype(k_cache.dtype)
                    )
                    v_cache = v_cache.at[l, :, blk, off].set(
                        v.astype(v_cache.dtype)
                    )
                o = att.decode_attention(
                    q, k_cache[l], v_cache[l], block_tables, seq_lens, scale,
                    use_pallas=use_pallas, mesh=mesh,
                    window=window_for_layer(cfg, l), sinks=lp.get("sinks"),
                    cap=cfg.attn_softcap,
                    k_scales=ks_l, v_scales=vs_l,
                )
                x = layer_tail(x, lp, o, lora_l=lora_l)
    else:
        if cfg.layer_windows:
            raise ValueError(
                "decode_layer_scan cannot serve per-layer-window models "
                "(the scan body would need a per-layer static mask "
                "shape) — use the default unrolled decode"
            )

        def body(carry, layer_in):
            x = carry
            lp, kc, vc = layer_in
            q, k, v = layer_qkv(x, lp)
            kc = att.write_decode_token_to_cache(kc, k, block_tables, positions)
            vc = att.write_decode_token_to_cache(vc, v, block_tables, positions)
            o = att.decode_attention(
                q, kc, vc, block_tables, seq_lens, scale,
                use_pallas=use_pallas, mesh=mesh, window=cfg.sliding_window,
                sinks=lp.get("sinks"), cap=cfg.attn_softcap,
            )
            x = layer_tail(x, lp, o)
            return x, (kc, vc)

        x, k_cache, v_cache = _scan_groups(
            body, x, params, cfg, k_cache, v_cache
        )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _logits(params, cfg, x)  # [B, V]
    if quantized:
        # scales only grow within a step, so plane entries above their
        # step-entry value count exactly the pages requantized this step
        n_requants = (
            jnp.sum(k_scales > k_scales0) + jnp.sum(v_scales > v_scales0)
        ).astype(jnp.int32)
        return logits, k_cache, v_cache, k_scales, v_scales, n_requants
    return logits, k_cache, v_cache


@partial(
    jax.jit,
    static_argnames=("cfg", "use_pallas", "mesh", "unroll", "interpret", "merged"),
    donate_argnames=("k_cache", "v_cache"),
)
def decode_step(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] last sampled token per sequence
    positions: jnp.ndarray,  # [B] absolute position of that token
    block_tables: jnp.ndarray,  # [B, M]
    seq_lens: jnp.ndarray,  # [B] length including the new token
    k_cache: jnp.ndarray,  # donated
    v_cache: jnp.ndarray,
    use_pallas: bool = False,
    mesh=None,
    unroll: bool = True,
    interpret: bool = False,
    merged: bool = True,
    k_scales: Optional[jnp.ndarray] = None,  # [L, N] f32, NOT donated
    v_scales: Optional[jnp.ndarray] = None,
    lora=None,                                # stacked adapter pytree
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] int32; -1 = base
):
    """One continuous-batching decode step for all active sequences.

    ``merged=False`` opts out of the one-write merged path back to the
    per-layer write-then-attend kernels (escape hatch for Mosaic
    regressions; bench.py falls back through it). With scale planes the
    return grows to (logits, k_cache, v_cache, k_scales, v_scales,
    n_requants) — see ``_decode_body``."""
    return _decode_body(
        params, cfg, tokens, positions, block_tables, seq_lens,
        k_cache, v_cache, use_pallas, mesh, unroll, interpret, merged,
        k_scales=k_scales, v_scales=v_scales, lora=lora,
        adapter_ids=adapter_ids,
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "use_pallas", "mesh", "unroll",
                     "interpret", "merged", "with_logprobs"),
    donate_argnames=("k_cache", "v_cache", "counts"),
)
def decode_window(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] last sampled token per sequence
    positions: jnp.ndarray,  # [B]
    block_tables: jnp.ndarray,  # [B, M]
    seq_lens: jnp.ndarray,  # [B]
    seeds: jnp.ndarray,  # [B] int32 sampling seeds
    steps: jnp.ndarray,  # [B] int32 per-request generation counters
    temps: jnp.ndarray,  # [B] float32
    top_ks: jnp.ndarray,  # [B] int32
    top_ps: jnp.ndarray,  # [B] float32
    k_cache: jnp.ndarray,  # donated
    v_cache: jnp.ndarray,
    n_steps: int = 1,
    use_pallas: bool = False,
    mesh=None,
    unroll: bool = True,
    interpret: bool = False,
    merged: bool = True,
    # sampling penalties (all-or-nothing per program: the engine compiles
    # the penalized variant only when some active request asks for one)
    freq_pens: Optional[jnp.ndarray] = None,  # [B] f32
    pres_pens: Optional[jnp.ndarray] = None,  # [B] f32
    rep_pens: Optional[jnp.ndarray] = None,  # [B] f32 (1.0 = off)
    counts: Optional[jnp.ndarray] = None,  # [B, V] i32 output-token counts, donated
    prompt_mask: Optional[jnp.ndarray] = None,  # [B, V] bool
    with_logprobs: bool = False,  # also emit per-step top-k logprobs
    # int8-with-scales device cache planes ([L, N] f32, NOT donated);
    # they ride the scan carry, and the output grows by
    # (k_scales, v_scales, n_requants) right after v_cache
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
    # multi-LoRA: step-invariant (closure constants, not scan carry)
    lora=None,
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] int32; -1 = base
):
    """``n_steps`` fused decode+sample steps in ONE dispatch (lax.scan):
    the sampled token of step i feeds step i+1 entirely on device, so the
    host syncs once per window instead of once per token (SURVEY §7
    "per-token latency floor"; VERDICT round-1 weak #4). Returns
    (tokens [n_steps, B], k_cache, v_cache[, counts]) — counts only when
    penalties are active. The host discards any tail tokens of sequences
    that hit a stop condition mid-window; callers must pre-allocate KV
    blocks for ``n_steps`` new tokens per sequence."""
    from ..ops.sampling import (
        apply_penalties,
        bump_counts,
        make_keys,
        sample_tokens,
        token_logprobs,
    )

    penalized = counts is not None
    quantized = k_scales is not None

    def body(carry, _):
        tokens, positions, seq_lens, steps, k_cache, v_cache = carry[:6]
        rest = list(carry[6:])
        if quantized:
            ks, vs, nreq = rest[:3]
            del rest[:3]
        cnt = rest[0] if penalized else None
        if quantized:
            logits, k_cache, v_cache, ks, vs, nr = _decode_body(
                params, cfg, tokens, positions, block_tables, seq_lens,
                k_cache, v_cache, use_pallas, mesh, unroll, interpret,
                merged, k_scales=ks, v_scales=vs, lora=lora,
                adapter_ids=adapter_ids,
            )
            nreq = nreq + nr
        else:
            logits, k_cache, v_cache = _decode_body(
                params, cfg, tokens, positions, block_tables, seq_lens,
                k_cache, v_cache, use_pallas, mesh, unroll, interpret,
                merged, lora=lora, adapter_ids=adapter_ids,
            )
        raw_logits = logits  # reported logprobs are the model's own dist
        if penalized:
            logits = apply_penalties(
                logits, cnt, prompt_mask, freq_pens, pres_pens, rep_pens
            )
        keys = make_keys(seeds, steps)
        nxt = sample_tokens.__wrapped__(logits, keys, temps, top_ks, top_ps)
        ys = (nxt, *token_logprobs(raw_logits, nxt)) if with_logprobs else nxt
        tail = (ks, vs, nreq) if quantized else ()
        if penalized:
            tail = tail + (bump_counts(cnt, nxt),)
        return (nxt, positions + 1, seq_lens + 1, steps + 1,
                k_cache, v_cache) + tail, ys

    carry = (tokens, positions, seq_lens, steps, k_cache, v_cache)
    if quantized:
        carry = carry + (k_scales, v_scales, jnp.zeros((), jnp.int32))
    if penalized:
        carry = carry + (counts,)
    fin, ys = lax.scan(body, carry, None, length=n_steps)
    k_cache, v_cache = fin[4], fin[5]
    rest = list(fin[6:])
    toks = ys[0] if with_logprobs else ys
    lps = ys[1:] if with_logprobs else None
    out = (toks, k_cache, v_cache)
    if quantized:
        out = out + tuple(rest[:3])  # (k_scales, v_scales, n_requants)
        del rest[:3]
    if penalized:
        out = out + (rest[0],)
    return out + (lps,) if with_logprobs else out


# ---------------- fused mixed prefill+decode step ----------------


def _mixed_fused_forward(
    params, cfg, d_tokens, d_positions, d_tables, d_seq_lens,
    p_tokens, p_tables, p_hists, p_valids, k_cache, v_cache,
    mesh=None, interpret=False, k_scales=None, v_scales=None,
    lora=None, d_adapter_ids=None, p_adapter_ids=None,
):
    """The FULLY-fused mixed forward (TPU/Pallas path): embeddings and
    every projection/FFN/logits GEMM run over the combined [B + MP*T]
    row axis — the weight stream amortizes across the decode rows and
    every prefill segment (the mixed-batch MFU win) — and attention is
    ONE ragged paged-attention kernel invocation per layer covering all
    parts (ops/ragged_paged_attention_pallas). Write-before-attend
    throughout.

    Combined-row GEMMs reassociate reductions vs the unfused [B]- and
    [T]-row programs, so this path matches them only to kernel-grade
    tolerance (greedy streams preserved except at exact logit ties —
    the same contract as the Pallas-vs-XLA kernel pairs and spec
    decode). The bit-exact twin for the XLA path lives in mixed_step's
    other branch. GQA families only; MLA and softcap models take the
    per-part branch.

    Returns (decode_logits [B, V] f32, p_logits [MP, V] f32, k_cache,
    v_cache).
    """
    from ..ops.ragged_paged_attention_pallas import (
        ragged_mixed_attention,
        ragged_mixed_attention_sharded,
    )

    B = d_tokens.shape[0]
    MP, T = p_tokens.shape
    x = _embed(
        params, cfg, jnp.concatenate([d_tokens, p_tokens.reshape(-1)])
    )  # [B + MP*T, E]
    p_positions = (p_hists[:, None] + jnp.arange(T)[None, :]).reshape(-1)
    positions_all = jnp.concatenate([d_positions, p_positions])
    inv_freq = _rope_freqs(cfg)
    rope_msc = _rope_attention_scaling(cfg)
    scale = attn_query_scale(cfg)
    inv_local = _rope_freqs_local(cfg)
    if lora is not None:
        # one adapter-id per combined row: decode rows carry theirs,
        # every row of segment m carries segment m's. The deltas run
        # GROUPED here — rows stable-sorted by adapter, two ragged-dot
        # passes (the MoE grouped-GMM shape) — so a batch mixing k
        # adapters costs one low-rank dispatch, not k (ops/lora.py).
        ids_all = jnp.concatenate(
            [d_adapter_ids.astype(jnp.int32),
             jnp.repeat(p_adapter_ids.astype(jnp.int32), T)]
        )
    else:
        ids_all = None

    def layer_tail(x, lp, o_flat, lora_l=None):
        x = x + post_norm(
            lp, "attn_post_norm",
            _wo_proj(lp, o_flat, lora_l, ids_all, lora_grouped=True), cfg,
        )
        h = pre_norm(lp, "mlp_norm", x, cfg)
        return x + post_norm(
            lp, "mlp_post_norm",
            _ffn(lp, cfg, h, mesh=mesh, use_pallas=True,
                 interpret=interpret), cfg,
        )

    # UNROLLED layer loop (per-layer windows / local rope stay
    # trace-static; program count bounded by the prefill buckets)
    for lps, n, goff in layer_groups(params, cfg):
        for li in range(n):
            l = goff + li
            lp = jax.tree.map(lambda a: a[li], lps)
            lora_l = (
                None if lora is None
                else jax.tree.map(lambda arr: arr[l], lora)
            )
            h = pre_norm(lp, "attn_norm", x, cfg)
            w = window_for_layer(cfg, l)
            kc_l, vc_l = k_cache[l], v_cache[l]
            # [B+MP*T, H/Hkv, D]
            q, k, v = _qkv(lp, cfg, h, lora_l, ids_all, lora_grouped=True)
            fr = rope_freqs_for_layer(cfg, l, inv_freq, inv_local)
            q = apply_rope(q, positions_all, fr, rope_msc)
            k = apply_rope(k, positions_all, fr, rope_msc)
            # write-before-attend for EVERY part (distinct pages: no
            # prefill sequence is in the decode batch and segments are
            # distinct sequences; padded/dead segment rows land in
            # reserved trash page 0 through their zero table entries)
            ks_l = vs_l = None
            if k_scales is not None:
                ks_l, vs_l = k_scales[l], v_scales[l]
                kc_l, ks_l = att.write_decode_token_to_cache_quantized(
                    kc_l, ks_l, k[:B], d_tables, d_positions
                )
                vc_l, vs_l = att.write_decode_token_to_cache_quantized(
                    vc_l, vs_l, v[:B], d_tables, d_positions
                )
                for m in range(MP):
                    sl = slice(B + m * T, B + (m + 1) * T)
                    kc_l, ks_l = att.write_chunk_to_cache_quantized(
                        kc_l, ks_l, k[sl], p_tables[m], p_hists[m],
                        p_valids[m],
                    )
                    vc_l, vs_l = att.write_chunk_to_cache_quantized(
                        vc_l, vs_l, v[sl], p_tables[m], p_hists[m],
                        p_valids[m],
                    )
            else:
                kc_l = att.write_decode_token_to_cache(
                    kc_l, k[:B], d_tables, d_positions
                )
                vc_l = att.write_decode_token_to_cache(
                    vc_l, v[:B], d_tables, d_positions
                )
                for m in range(MP):
                    sl = slice(B + m * T, B + (m + 1) * T)
                    kc_l = att.write_chunk_to_cache(
                        kc_l, k[sl], p_tables[m], p_hists[m]
                    )
                    vc_l = att.write_chunk_to_cache(
                        vc_l, v[sl], p_tables[m], p_hists[m]
                    )
            Hq, Dh = q.shape[1], q.shape[2]
            q_chunks = q[B:].reshape(MP, T, Hq, Dh)
            if mesh is not None:
                o_dec, o_chunks = ragged_mixed_attention_sharded(
                    q[:B], q_chunks, kc_l, vc_l, d_tables, d_seq_lens,
                    p_tables, p_hists, p_valids, scale, mesh, window=w,
                    sinks=lp.get("sinks"), interpret=interpret,
                    k_scales=ks_l, v_scales=vs_l,
                )
            else:
                o_dec, o_chunks = ragged_mixed_attention(
                    q[:B], q_chunks, kc_l, vc_l, d_tables, d_seq_lens,
                    p_tables, p_hists, p_valids, scale, window=w,
                    sinks=lp.get("sinks"), interpret=interpret,
                    k_scales=ks_l, v_scales=vs_l,
                )
            k_cache = k_cache.at[l].set(kc_l)
            v_cache = v_cache.at[l].set(vc_l)
            if k_scales is not None:
                k_scales = k_scales.at[l].set(ks_l)
                v_scales = v_scales.at[l].set(vs_l)
            o = jnp.concatenate(
                [o_dec.reshape(B, -1), o_chunks.reshape(MP * T, -1)]
            )
            x = layer_tail(x, lp, o, lora_l=lora_l)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits_d = _logits(params, cfg, x[:B])  # [B, V] f32
    # each segment's last REAL row only (the unfused prefill computes
    # the same single row — full [T, V] head matmuls would be pure waste)
    last = B + jnp.arange(MP) * T + jnp.clip(p_valids - 1, 0, T - 1)
    p_logits = _logits(params, cfg, x[last])  # [MP, V] f32
    if k_scales is not None:
        return logits_d, p_logits, k_cache, v_cache, k_scales, v_scales
    return logits_d, p_logits, k_cache, v_cache


@partial(
    jax.jit,
    static_argnames=("cfg", "use_pallas", "mesh", "unroll", "merged",
                     "interpret", "with_logprobs"),
    donate_argnames=("k_cache", "v_cache", "counts"),
)
def mixed_step(
    params: dict,
    cfg: ModelConfig,
    # decode side (same conventions as decode_window at n_steps=1)
    d_tokens: jnp.ndarray,  # [B] last sampled token per sequence
    d_positions: jnp.ndarray,  # [B] absolute position of that token
    d_tables: jnp.ndarray,  # [B, M]
    d_seq_lens: jnp.ndarray,  # [B] length including the new token
    seeds: jnp.ndarray,  # [B] int32 sampling seeds
    steps: jnp.ndarray,  # [B] int32 per-request generation counters
    temps: jnp.ndarray,  # [B] float32
    top_ks: jnp.ndarray,  # [B] int32
    top_ps: jnp.ndarray,  # [B] float32
    # prefill side (same conventions as prefill's chunk args, stacked
    # over M in-flight prompts; dead pad segments have valid 0 + zero
    # tables and their logits row is garbage the engine never reads)
    p_tokens: jnp.ndarray,  # [MP, T] padded chunks of in-flight prompts
    p_tables: jnp.ndarray,  # [MP, M] the prefill sequences' block tables
    p_hists: jnp.ndarray,  # [MP] int32: tokens already cached per prompt
    p_valids: jnp.ndarray,  # [MP] int32: real tokens in each chunk
    k_cache: jnp.ndarray,  # donated
    v_cache: jnp.ndarray,
    use_pallas: bool = False,
    mesh=None,
    unroll: bool = True,
    merged: bool = True,
    interpret: bool = False,
    # sampling penalties (compiled in only when some request asks)
    freq_pens: Optional[jnp.ndarray] = None,  # [B] f32
    pres_pens: Optional[jnp.ndarray] = None,  # [B] f32
    rep_pens: Optional[jnp.ndarray] = None,  # [B] f32 (1.0 = off)
    counts: Optional[jnp.ndarray] = None,  # [B, V] i32, donated
    prompt_mask: Optional[jnp.ndarray] = None,  # [B, V] bool
    with_logprobs: bool = False,
    # int8-with-scales device cache planes ([L, N] f32, NOT donated);
    # output grows by (k_scales, v_scales, n_requants) after v_cache
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
    # multi-LoRA lane: stacked adapter pytree + per-row slot ids
    # (-1 = base). The Pallas flavor runs GROUPED deltas over the
    # combined rows; the XLA flavor threads the same lora through the
    # unfused prefill/decode calls (per-adapter loop — bit-identical
    # to solo dispatch). Output shape unchanged.
    lora=None,
    d_adapter_ids: Optional[jnp.ndarray] = None,  # [B] int32
    p_adapter_ids: Optional[jnp.ndarray] = None,  # [MP] int32
):
    """ONE device dispatch fusing M prefill chunks into a decode step.

    Two forward flavors behind one dispatch boundary:

      * **Pallas (TPU) path** — `_mixed_fused_forward`: combined-row
        GEMMs over the decode rows + every segment, plus one ragged
        paged-attention kernel invocation per layer (the full
        mixed-batch MFU win). Matches the unfused paths to kernel-grade
        tolerance; greedy streams preserved except at exact logit ties
        — the standing contract for every Pallas-vs-XLA pairing in this
        repo. MLA and softcap families on this path fall through to the
        per-part flavor below (MLA's latent decode+prefill kernel pair
        runs inside the same dispatch, once per segment; there is no
        latent ragged kernel yet).
      * **XLA path** (CPU, quantized-KV, softcap) — per-part structural
        identity: each segment runs through EXACTLY the unfused prefill
        forward (``prefill.__wrapped__``: same scan/unrolled layer
        loop, same [T]-row GEMMs), in admission order, and the decode
        batch through EXACTLY ``_decode_body`` with the engine's own
        ``unroll``/``merged`` flags — so tokens AND logprobs are
        BIT-IDENTICAL to the alternating scheduler (the
        tests/test_mixed_batch.py contract; restructured GEMMs would
        reassociate bf16 reductions and flip sampled tokens). All parts
        are computationally independent (no prefill sequence is in the
        decode batch; segments are distinct sequences with disjoint
        pages), so fusing them into one program cannot change any.

    The segment count MP and padded length T are static shape keys —
    the engine buckets both (segment-count buckets x prefill buckets),
    so the compiled program count is bounded by the bucket grid, never
    the per-step segment-length mixture.

    Sampling mirrors decode_window's body exactly (penalties on the
    sampled distribution, raw logits for reported logprobs).

    Returns (next_tokens [B], p_logits [MP, V] f32 — each segment's
    last-real-row logits, for host-side first-token sampling on a
    prompt's final chunk —, k_cache, v_cache[, counts]
    [, (chosen_lp [B], top_ids [B, K], top_lps [B, K])]).
    """
    from ..ops.sampling import (
        apply_penalties,
        bump_counts,
        make_keys,
        sample_tokens,
        token_logprobs,
    )

    MP = p_tokens.shape[0]
    quantized = k_scales is not None
    if quantized:
        # scales only grow within a step — plane entries above their
        # step-entry value count the pages requantized this dispatch
        k_scales0, v_scales0 = k_scales, v_scales
    if use_pallas and not cfg.is_mla and not cfg.attn_softcap:
        if quantized:
            logits_d, p_logits, k_cache, v_cache, k_scales, v_scales = (
                _mixed_fused_forward(
                    params, cfg, d_tokens, d_positions, d_tables,
                    d_seq_lens, p_tokens, p_tables, p_hists, p_valids,
                    k_cache, v_cache, mesh=mesh, interpret=interpret,
                    k_scales=k_scales, v_scales=v_scales, lora=lora,
                    d_adapter_ids=d_adapter_ids,
                    p_adapter_ids=p_adapter_ids,
                )
            )
        else:
            logits_d, p_logits, k_cache, v_cache = _mixed_fused_forward(
                params, cfg, d_tokens, d_positions, d_tables, d_seq_lens,
                p_tokens, p_tables, p_hists, p_valids, k_cache, v_cache,
                mesh=mesh, interpret=interpret, lora=lora,
                d_adapter_ids=d_adapter_ids, p_adapter_ids=p_adapter_ids,
            )
    else:
        # chunks first (admission order), then decode — order is
        # numerically irrelevant (independent parts) and matches the
        # admission-then-decode order of the alternating scheduler
        p_logit_rows = []
        for m in range(MP):
            aid = None if lora is None else p_adapter_ids[m]
            if quantized:
                lg, k_cache, v_cache, k_scales, v_scales = (
                    prefill.__wrapped__(
                        params, cfg, p_tokens[m], p_tables[m], p_hists[m],
                        p_valids[m], k_cache, v_cache,
                        use_pallas=use_pallas, mesh=mesh,
                        k_scales=k_scales, v_scales=v_scales,
                        lora=lora, adapter_id=aid,
                    )
                )
            else:
                lg, k_cache, v_cache = prefill.__wrapped__(
                    params, cfg, p_tokens[m], p_tables[m], p_hists[m],
                    p_valids[m], k_cache, v_cache, use_pallas=use_pallas,
                    mesh=mesh, lora=lora, adapter_id=aid,
                )
            p_logit_rows.append(lg)
        p_logits = jnp.stack(p_logit_rows)  # [MP, V]
        if quantized:
            logits_d, k_cache, v_cache, k_scales, v_scales, _ = _decode_body(
                params, cfg, d_tokens, d_positions, d_tables, d_seq_lens,
                k_cache, v_cache, use_pallas, mesh, unroll, interpret,
                merged, k_scales=k_scales, v_scales=v_scales,
                lora=lora, adapter_ids=d_adapter_ids,
            )
        else:
            logits_d, k_cache, v_cache = _decode_body(
                params, cfg, d_tokens, d_positions, d_tables, d_seq_lens,
                k_cache, v_cache, use_pallas, mesh, unroll, interpret,
                merged, lora=lora, adapter_ids=d_adapter_ids,
            )

    raw_logits = logits_d
    penalized = counts is not None
    if penalized:
        logits_d = apply_penalties(
            logits_d, counts, prompt_mask, freq_pens, pres_pens, rep_pens
        )
    keys = make_keys(seeds, steps)
    nxt = sample_tokens.__wrapped__(logits_d, keys, temps, top_ks, top_ps)
    result = [nxt, p_logits, k_cache, v_cache]
    if quantized:
        n_requants = (
            jnp.sum(k_scales > k_scales0) + jnp.sum(v_scales > v_scales0)
        ).astype(jnp.int32)
        result += [k_scales, v_scales, n_requants]
    if penalized:
        result.append(bump_counts(counts, nxt))
    if with_logprobs:
        result.append(token_logprobs(raw_logits, nxt))
    return tuple(result)


# ---------------- speculative verify (prompt-lookup decoding) ----------------


def _verify_forward(
    params, cfg, tokens, positions, block_tables, seq_lens,
    k_cache, v_cache, n_spec, use_pallas=False, mesh=None, interpret=False,
):
    """The fused multi-token forward of the speculative verify: logits
    for T = n_spec+1 in-flight tokens per sequence in one pass (the
    weight stream amortizes over T — the whole point of speculation),
    with all T rows' K/V appended to the cache in place. Rows past the
    accepted run hold rejected proposals' K/V, which live above the
    commit horizon and are overwritten before any read (same invariant
    as a discarded decode-window tail)."""
    from ..ops.kv_cache_update_pallas import (
        kv_cache_append_tokens,
        kv_cache_append_tokens_sharded,
    )

    T = n_spec + 1
    B, E = tokens.shape[0], cfg.hidden_size
    pos_bt = positions[:, None] + jnp.arange(T)[None, :]  # [B, T]
    hist_lens = seq_lens - 1  # cache rows before the in-flight window
    x = _embed(params, cfg, tokens.reshape(-1)).reshape(B, T, E)
    # write slots of the T in-flight rows (one slot-mapping convention)
    bs = k_cache.shape[3]
    blk = jnp.take_along_axis(block_tables, pos_bt // bs, axis=1)
    off = pos_bt % bs

    if cfg.is_mla:
        # MLA verify: absorbed multi-token attention with the in-flight
        # window OUT of the cache (ops/mla_attention_pallas
        # .mla_verify_attention), so all layers' latent writes batch
        # into ONE append instead of 2L cache-copying scatters. Rows
        # past the accepted run live above the commit horizon and are
        # overwritten before any read (same invariant as below).
        from . import mla as _mla
        from ..ops import mla_attention_pallas as _mla_ops

        inv_freq, msc = _mla.mla_rope_freqs(cfg)
        scale = cfg.mla_softmax_scale()
        c_news, pe_news = [], []
        for lps, ng, goff in layer_groups(params, cfg):
            for li in range(ng):
                l = goff + li
                lp = jax.tree.map(lambda a: a[li], lps)
                h = pre_norm(lp, "attn_norm", x, cfg)
                q_eff, q_pe, c_kv, k_pe = _mla.mla_q_and_latent(
                    lp, cfg, h, pos_bt, inv_freq, msc
                )
                c_news.append(c_kv)
                pe_news.append(k_pe)
                o = _mla_ops.mla_verify_attention(
                    q_eff, q_pe, c_kv, k_pe, k_cache[l], v_cache[l],
                    block_tables, hist_lens, scale,
                    use_pallas=use_pallas and mesh is None,
                    interpret=interpret,
                )
                o = _mla._o_proj(lp, cfg, o).astype(x.dtype)
                x = x + _mm(o.reshape(B * T, -1), lp["wo"]).reshape(B, T, E)
                h = pre_norm(lp, "mlp_norm", x, cfg)
                x = x + _ffn(
                    lp, cfg, h.reshape(B * T, E), mesh=mesh,
                    use_pallas=use_pallas, interpret=interpret,
                ).reshape(B, T, E)
        x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        logits = _logits(params, cfg, x.reshape(B * T, E)).reshape(B, T, -1)
        k_cache, v_cache = kv_cache_append_tokens(
            jnp.stack(c_news)[:, :, :, None, :],  # [L, B, T, 1, C]
            jnp.stack(pe_news)[:, :, :, None, :],  # [L, B, T, 1, R]
            k_cache, v_cache, blk, off,
            interpret=interpret or not use_pallas or mesh is not None,
        )
        return logits, k_cache, v_cache

    inv_freq = _rope_freqs(cfg)
    inv_local = _rope_freqs_local(cfg)
    rope_msc = _rope_attention_scaling(cfg)
    scale = attn_query_scale(cfg)

    k_news, v_news = [], []
    for lps, ng, goff in layer_groups(params, cfg):
        for li in range(ng):
            l = goff + li
            lp = jax.tree.map(lambda a: a[li], lps)
            h = pre_norm(lp, "attn_norm", x, cfg)
            q, k, v = _qkv(lp, cfg, h)  # [B, T, H/Hkv, D]
            fr = rope_freqs_for_layer(cfg, l, inv_freq, inv_local)
            q = apply_rope(q, pos_bt, fr, rope_msc)
            k = apply_rope(k, pos_bt, fr, rope_msc)
            k_news.append(k)
            v_news.append(v)
            if use_pallas and mesh is not None:
                o = att.verify_attention_sharded(
                    q, k, v, k_cache[l], v_cache[l], block_tables, hist_lens,
                    scale, mesh, use_pallas=True,
                    window=window_for_layer(cfg, l), sinks=lp.get("sinks"),
                    interpret=interpret,
                )
            else:
                # the layer loop is unrolled, so per-layer windows and
                # sinks (gpt-oss) thread straight through the XLA verify
                o = att.verify_attention(
                    q, k, v, k_cache[l], v_cache[l], block_tables, hist_lens,
                    scale, use_pallas=use_pallas,
                    window=window_for_layer(cfg, l), sinks=lp.get("sinks"),
                    cap=cfg.attn_softcap, interpret=interpret,
                )
            x = x + post_norm(
                lp, "attn_post_norm",
                _mm_b(o.reshape(B * T, -1), lp, "wo", "bo").reshape(B, T, E),
                cfg,
            )
            h = pre_norm(lp, "mlp_norm", x, cfg)
            x = x + post_norm(
                lp, "mlp_post_norm",
                _ffn(lp, cfg, h.reshape(B * T, E), mesh=mesh,
                     use_pallas=use_pallas, interpret=interpret,
                     ).reshape(B, T, E),
                cfg,
            )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _logits(params, cfg, x.reshape(B * T, E)).reshape(B, T, -1)

    if use_pallas and mesh is not None:
        k_cache, v_cache = kv_cache_append_tokens_sharded(
            jnp.stack(k_news), jnp.stack(v_news), k_cache, v_cache, blk,
            off, mesh, interpret=interpret,
        )
    else:
        k_cache, v_cache = kv_cache_append_tokens(
            jnp.stack(k_news), jnp.stack(v_news), k_cache, v_cache, blk, off,
            interpret=interpret or not use_pallas,
        )
    return logits, k_cache, v_cache


@partial(
    jax.jit,
    static_argnames=("cfg", "n_spec", "use_pallas", "mesh", "interpret",
                     "with_logprobs"),
    donate_argnames=("k_cache", "v_cache", "counts"),
)
def verify_window(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T]: t=0 last accepted token, t>=1 proposals
    proposals: jnp.ndarray,  # [B, n_spec] int32, -1 = no proposal
    positions: jnp.ndarray,  # [B] absolute position of tokens[:, 0]
    block_tables: jnp.ndarray,  # [B, M]
    seq_lens: jnp.ndarray,  # [B] length INCLUDING tokens[:, 0]
    seeds: jnp.ndarray,  # [B] int32 sampling seeds
    steps: jnp.ndarray,  # [B] int32 per-request generation counters
    temps: jnp.ndarray,  # [B] float32; 0 = greedy row
    top_ks: jnp.ndarray,  # [B] int32
    top_ps: jnp.ndarray,  # [B] float32
    k_cache: jnp.ndarray,  # donated; holds history only (rows < seq_len-1)
    v_cache: jnp.ndarray,
    n_spec: int,
    use_pallas: bool = False,
    mesh=None,
    interpret: bool = False,
    # sampling penalties (compiled in only when some request asks)
    freq_pens: Optional[jnp.ndarray] = None,  # [B] f32
    pres_pens: Optional[jnp.ndarray] = None,  # [B] f32
    rep_pens: Optional[jnp.ndarray] = None,  # [B] f32 (1.0 = off)
    counts: Optional[jnp.ndarray] = None,  # [B, V] i32, donated
    prompt_mask: Optional[jnp.ndarray] = None,  # [B, V] bool
    with_logprobs: bool = False,
):
    """Speculative verify + acceptance (greedy AND sampled rows):

      * greedy rows accept proposals matching the argmax chain;
      * sampled rows use rejection sampling against the deterministic
        draft (ops/sampling.speculative_accept) — lossless in
        distribution; accept draws come from a tweaked seed stream
        (seed ^ 0x5EC) so emitted-token keys stay identical to the
        plain decode stream (replay-exactness of resumed requests).

    Penalties (when ``counts`` is given) model the SEQUENTIAL semantics
    of plain decode inside the joint verify: position t's distribution is
    penalized by the base counts plus the window's own tokens before t
    (accepted proposals bump as they would had they been emitted one by
    one), and the returned counts include every emitted token (the
    accepted run + correction/bonus). Acceptance and greedy argmax run on
    the PENALIZED logits — exactly the distribution the plain sampler
    would have used — while reported logprobs stay the model's own raw
    distribution (same convention as decode_window).

    Returns (out_tokens [B, T], n_acc [B], k_cache, v_cache[, counts]
    [, (chosen_lp [B,T], top_ids [B,T,K], top_lps [B,T,K])]): the caller
    emits out_tokens[:, :n_acc+1] — accepted run + correction/bonus.
    """
    from ..ops.sampling import (
        apply_penalties,
        make_keys,
        speculative_accept,
        token_logprobs,
    )

    T = n_spec + 1
    B = tokens.shape[0]
    logits, k_cache, v_cache = _verify_forward(
        params, cfg, tokens, positions, block_tables, seq_lens,
        k_cache, v_cache, n_spec, use_pallas, mesh, interpret,
    )
    raw_logits = logits.astype(jnp.float32)
    penalized = counts is not None
    if penalized:
        V = raw_logits.shape[-1]
        d = jnp.maximum(proposals, 0)
        valid = proposals >= 0
        # window-token bumps BEFORE each position: one_hot of V (the
        # invalid sentinel) is all-zeros, so unproposed slots bump nothing
        oh = jax.nn.one_hot(
            jnp.where(valid, d, V), V, dtype=jnp.int32
        )  # [B, g, V]
        cum = jnp.cumsum(oh, axis=1)
        cnt_t = counts[:, None] + jnp.concatenate(
            [jnp.zeros((B, 1, V), jnp.int32), cum], axis=1
        )  # [B, T, V]
        sample_logits = apply_penalties(
            raw_logits.reshape(B * T, V),
            cnt_t.reshape(B * T, V),
            jnp.repeat(prompt_mask, T, axis=0),
            jnp.repeat(freq_pens, T),
            jnp.repeat(pres_pens, T),
            jnp.repeat(rep_pens, T),
        ).reshape(B, T, V)
    else:
        sample_logits = raw_logits
    keys_accept = jnp.stack(
        [make_keys(seeds ^ 0x5EC, steps + t) for t in range(n_spec)], axis=1
    ) if n_spec else jnp.zeros((tokens.shape[0], 0, 2), jnp.uint32)
    keys_sample = jnp.stack(
        [make_keys(seeds, steps + t) for t in range(T)], axis=1
    )
    out, n_acc = speculative_accept(
        sample_logits, proposals, keys_accept, keys_sample,
        temps, top_ks, top_ps,
    )
    result = [out, n_acc, k_cache, v_cache]
    if penalized:
        # count every emitted token (t <= n_acc); others drop via index V
        emitted = jnp.arange(T)[None, :] <= n_acc[:, None]
        ids = jnp.where(emitted, out, raw_logits.shape[-1])
        counts = counts.at[jnp.arange(B)[:, None], ids].add(1, mode="drop")
        result.append(counts)
    if with_logprobs:
        chosen_lp, top_ids, top_lps = token_logprobs(
            raw_logits.reshape(B * T, -1), out.reshape(-1)
        )
        K = top_ids.shape[-1]
        result.append((
            chosen_lp.reshape(B, T),
            top_ids.reshape(B, T, K),
            top_lps.reshape(B, T, K),
        ))
    return tuple(result)


# ---------------- reference dense forward (tests) ----------------


def dense_forward(params: dict, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Straight full-attention forward [T] -> logits [T, V]; ground truth
    for paged-path equivalence tests. MLA models run the NAIVE
    (non-absorbed) formulation — reconstruct per-head K/V from latents —
    which the absorbed paged path must match."""
    T = tokens.shape[0]
    x = _embed(params, cfg, tokens)
    positions = jnp.arange(T)
    if cfg.is_mla:
        from . import mla as _mla

        inv_freq, msc = _mla.mla_rope_freqs(cfg)
        scale = cfg.mla_softmax_scale()
    else:
        inv_freq = _rope_freqs(cfg)
        rope_msc = _rope_attention_scaling(cfg)
        scale = attn_query_scale(cfg)

    inv_local = _rope_freqs_local(cfg)

    def body(x, lp, window=cfg.sliding_window, freqs=None):
        h = pre_norm(lp, "attn_norm", x, cfg)
        if cfg.is_mla:
            # DELIBERATELY independent of mla.mla_q_and_latent: this is
            # the ground-truth NAIVE formulation (reconstruct full K/V,
            # no absorption) the absorbed paged path is validated
            # against — sharing the projection code would make the
            # equivalence tests circular. External anchor: the HF parity
            # tests (tests/test_hf_parity.py deepseek v2/v3).
            from . import mla as _mla

            H, dn, dr = cfg.num_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
            if cfg.q_lora_rank:
                q = _mm(rms_norm(_mm(h, lp["wq_a"]), lp["q_norm"],
                                 cfg.rms_norm_eps), lp["wq_b"])
            else:
                q = _mm(h, lp["wq"])
            q = q.reshape(T, H, dn + dr)
            q_nope, q_pe = q[..., :dn], q[..., dn:]
            q_pe = _mla.rope_rotate(q_pe, positions, inv_freq, msc)
            kv = _mm(h, lp["wkv_a"])
            c_kv = rms_norm(kv[..., : cfg.kv_lora_rank], lp["kv_norm"],
                            cfg.rms_norm_eps)
            k_pe = _mla.rope_rotate(
                kv[..., cfg.kv_lora_rank:][:, None, :], positions,
                inv_freq, msc,
            )[:, 0, :]
            w_kc, w_vc = _mla._wkv_b_parts(lp, cfg)
            # naive reconstruction: per-head K/V from the latent
            k_nope = jnp.einsum("tc,chd->thd", c_kv.astype(jnp.float32),
                                w_kc.astype(jnp.float32))
            v = jnp.einsum("tc,chd->thd", c_kv.astype(jnp.float32),
                           w_vc.astype(jnp.float32))
            qf = jnp.concatenate(
                [q_nope.astype(jnp.float32),
                 q_pe.astype(jnp.float32)], axis=-1,
            )
            kf = jnp.concatenate(
                [k_nope,
                 jnp.broadcast_to(k_pe[:, None, :].astype(jnp.float32),
                                  (T, H, dr))], axis=-1,
            )
            s = jnp.einsum("thd,shd->hts", qf * scale, kf)
            causal = positions[:, None] >= positions[None, :]
            s = jnp.where(causal[None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("hts,shd->thd", p, v)
            o = o.reshape(T, -1).astype(x.dtype)
            x = x + _mm(o, lp["wo"])
        else:
            q, k, v = _qkv(lp, cfg, h)
            fr = inv_freq if freqs is None else freqs
            q = apply_rope(q, positions, fr, rope_msc)
            k = apply_rope(k, positions, fr, rope_msc)
            o = att.prefill_attention_xla(
                q, k, v, positions, jnp.int32(T), scale,
                window=window, sinks=lp.get("sinks"), cap=cfg.attn_softcap,
            )
            x = x + post_norm(
                lp, "attn_post_norm",
                _mm_b(o.reshape(T, -1), lp, "wo", "bo"), cfg,
            )
        h = pre_norm(lp, "mlp_norm", x, cfg)
        x = x + post_norm(lp, "mlp_post_norm", _ffn(lp, cfg, h), cfg)
        return x, None

    if cfg.layer_windows:  # per-layer static windows: unrolled
        for lps, n, off in layer_groups(params, cfg):
            for li in range(n):
                lp = jax.tree.map(lambda a: a[li], lps)
                l = off + li
                x, _ = body(
                    x, lp, window=window_for_layer(cfg, l),
                    freqs=rope_freqs_for_layer(cfg, l, inv_freq, inv_local),
                )
    else:
        for lps, _n, _off in layer_groups(params, cfg):
            x, _ = lax.scan(body, x, lps)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return _logits(params, cfg, x)

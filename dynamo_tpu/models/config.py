"""Model architecture config (llama-family superset + MoE fields).

Parsed from HF ``config.json`` (the reference reads the same artifact via
its ModelDeploymentCard, model_card/create.rs). Covers Llama 2/3,
DeepSeek-R1-Distill-Llama, Qwen2 (bias variant), Mistral, Gemma
(GeGLU/(1+w)-norm/scaled-embedding variants), and Mixtral/DeepSeek-style
MoE.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional


def is_yarn(rope_scaling: dict) -> bool:
    return (
        rope_scaling.get("type") == "yarn"
        or rope_scaling.get("rope_type") == "yarn"
    )


def yarn_mscale(factor: float, mscale: float) -> float:
    """DeepSeek's YaRN attention-scale correction (ONE copy — the rope
    cos/sin correction in models/mla.py uses the same formula)."""
    import math

    if factor <= 1.0 or mscale == 0:
        return 1.0
    return 0.1 * mscale * math.log(factor) + 1.0


@dataclass(eq=False)  # identity hash/eq: used as a jit static arg
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 0  # 0 -> hidden_size // num_heads
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = False
    attention_bias: bool = False
    # qwen3: per-head RMS norm on q and k after projection, before rope
    qk_norm: bool = False
    # olmo-2: q/k RMS norm over the FULL projection width (pre-reshape)
    qk_norm_full: bool = False
    # olmo-2: NO input/pre-FFN norms — normalization applies to the
    # sublayer OUTPUT (post_norms) only
    norm_after: bool = False
    # MoE (0 experts = dense)
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 0
    num_shared_experts: int = 0  # DeepSeek-style always-on experts
    # Qwen2-MoE: one shared expert of its OWN width whose contribution
    # is gated by sigmoid(x @ shared_expert_gate) instead of always-on
    shared_expert_size: int = 0  # 0 = moe_intermediate * num_shared
    shared_expert_gate: bool = False
    first_dense_layers: int = 0  # DeepSeek first_k_dense_replace
    norm_topk_prob: bool = True  # Mixtral renormalizes top-k gate probs
    # DeepSeek-V2/V3 routing variants (ref patch:3548-3560 deepseek_v2;
    # BASELINE config 5 names DeepSeek-R1 = the V3 architecture)
    moe_scoring: str = "softmax"  # "softmax" (V2) | "sigmoid" (V3)
    moe_gate_bias: bool = False  # V3 e_score_correction_bias (topk only)
    routed_scaling_factor: float = 1.0
    n_group: int = 0  # group-limited routing (0 = off)
    topk_group: int = 0
    # group score: V2 group_limited_greedy uses the group MAX, V3
    # noaux_tc the sum of the group's top-2
    moe_group_score: str = "max"
    # Multi-Latent Attention (DeepSeek-V2/V3; kv_lora_rank > 0 enables).
    # The KV cache stores the COMPRESSED latent per token: c_kv
    # [kv_lora_rank] in the k-cache slot and the shared rotated k_pe
    # [qk_rope_head_dim] in the v-cache slot, both single-"head" paged
    # arrays — attention runs ABSORBED (q_nope folded through the
    # kv_b up-projection), so per-token cache bytes are
    # kv_lora_rank + qk_rope_head_dim instead of 2*Hkv*head_dim.
    q_lora_rank: int = 0  # 0 = direct q projection (V2-Lite)
    kv_lora_rank: int = 0  # 0 = regular attention
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # DeepSeek checkpoints store rope dims interleaved (GPT-J pairs);
    # weights.py de-interleaves at load so the runtime rotation stays the
    # fast half-split form — this flag records the CHECKPOINT convention
    rope_interleave: bool = False
    # sliding-window attention (mistral v0.1-style; 0 = full attention).
    # Enforced by masking in the XLA paths and by per-row window floors
    # in the in-repo Pallas kernels (the jax library decode kernel has
    # no window support and is skipped when a window is set).
    # Speculative decoding composes (exact per-row floors via the
    # kernel's ``group`` row mapping).
    sliding_window: int = 0
    # gpt-oss: layers ALTERNATE sliding/full attention. When set, entry
    # l is layer l's window (0 = full) and the GLOBAL sliding_window is
    # forced to 0 — per-layer entries are the only source of widths, so
    # homogeneous gates never window every layer. Such models run the
    # unrolled layer paths (a lax.scan body cannot carry a per-layer
    # static mask shape).
    layer_windows: tuple = ()
    # gpt-oss attention sinks: a learnable per-head logit joins every
    # softmax's normalization (no value row) — attention mass can park
    # on the sink instead of real tokens. Folded into the denominator
    # in the XLA attention paths.
    attn_sinks: bool = False
    # gpt-oss expert FFN: fused clamped SwiGLU — gate clamped at +limit,
    # up at +-limit, glu = gate*sigmoid(alpha*gate), out = (up+1)*glu —
    # with per-expert biases on both projections
    moe_act: str = "swiglu"  # "swiglu" | "gptoss_clamp"
    # o_proj bias (gpt-oss: every attention projection carries bias)
    o_bias: bool = False
    # gemma-family variants
    hidden_act: str = "silu"  # "silu" | "gelu_tanh" (gemma GeGLU)
    rms_add_unit: bool = False  # gemma RMSNorm scales by (1 + w)
    scale_embed: bool = False  # gemma multiplies embeddings by sqrt(E)
    # gemma-2: tanh caps on attention scores / final logits, sandwich
    # (post-attention + post-FFN) norms, and a fixed query scale from
    # query_pre_attn_scalar instead of head_dim
    attn_softcap: float = 0.0  # 0 = off
    final_softcap: float = 0.0
    post_norms: bool = False
    attn_scale_base: int = 0  # 0 = use head_dim
    # gemma-3: sliding layers rope at their own LOCAL base frequency
    # (rope_local_base_freq); full layers use rope_theta (+scaling)
    rope_local_theta: float = 0.0  # 0 = single rope for all layers
    # partial rotary (Phi-4-mini, GLM, persimmon): only the first
    # head_dim * rope_partial_factor dims of each head rotate
    # (rope_partial_dim derives in __post_init__ once head_dim resolves)
    rope_partial_factor: float = 1.0
    rope_partial_dim: int = 0
    # runtime
    dtype: str = "bfloat16"

    def __post_init__(self):
        if self.layer_windows:
            self.layer_windows = tuple(self.layer_windows)
            if len(self.layer_windows) != self.num_layers:
                raise ValueError(
                    f"layer_windows has {len(self.layer_windows)} entries "
                    f"for {self.num_layers} layers"
                )
        if self.head_dim == 0:
            self.head_dim = self.hidden_size // self.num_heads
        if self.rope_partial_factor != 1.0 and not self.rope_partial_dim:
            self.rope_partial_dim = int(self.head_dim * self.rope_partial_factor)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    def mla_softmax_scale(self) -> float:
        """qk_head_dim^-0.5 times the YaRN mscale^2 correction DeepSeek
        applies when rope_scaling.mscale_all_dim is set."""
        scale = self.qk_head_dim**-0.5
        rs = self.rope_scaling or {}
        if is_yarn(rs):
            m = yarn_mscale(rs.get("factor", 1.0),
                            rs.get("mscale_all_dim", 0.0) or 0.0)
            scale = scale * m * m
        return scale

    @staticmethod
    def from_hf_config(cfg: dict) -> "ModelConfig":
        archs = cfg.get("architectures") or []
        if isinstance(cfg.get("text_config"), dict) and any(
            a.startswith("Gemma3") for a in archs
        ):
            # gemma-3 multimodal checkpoints nest the language model
            # under text_config; serve that (the vision tower has no
            # TPU serving path here)
            merged = {**cfg["text_config"], "architectures": archs}
            if cfg.get("torch_dtype") and "torch_dtype" not in merged:
                merged["torch_dtype"] = cfg["torch_dtype"]
            cfg = merged
        # Qwen2 has qkv bias baked into the architecture; its HF config
        # carries no attention_bias field
        qkv_bias = cfg.get("attention_bias", False) or any(
            a.startswith("Qwen2") for a in archs
        )
        # gemma: GeGLU activation, (1+w) norms, sqrt(E)-scaled embeddings
        is_gemma = any(a.startswith("Gemma") for a in archs) or (
            cfg.get("model_type", "").startswith("gemma")
        )
        is_gptoss = any(a.startswith("GptOss") for a in archs)
        is_gemma2 = any(a.startswith("Gemma2") for a in archs) or (
            cfg.get("model_type") == "gemma2"
        )
        is_gemma3 = any(a.startswith("Gemma3") for a in archs) or (
            cfg.get("model_type") in ("gemma3", "gemma3_text")
        )
        # EXACT arch matching: Glm4Moe (qk-norm MoE) and Glm4v
        # (multimodal, text under text_config) have different layer
        # anatomy — reject them rather than mis-serve (the file's
        # standing reject-over-wrong-logits rule)
        glm_archs = {a for a in archs if a.startswith("Glm")}
        if glm_archs - {"GlmForCausalLM", "Glm4ForCausalLM"}:
            raise ValueError(
                f"unsupported GLM variant {sorted(glm_archs)} — only "
                "GlmForCausalLM / Glm4ForCausalLM are implemented"
            )
        is_glm = bool(glm_archs) or cfg.get("model_type") in ("glm", "glm4")
        is_olmo2 = any(a.startswith("Olmo2") for a in archs) or (
            cfg.get("model_type") == "olmo2"
        )
        is_glm4 = "Glm4ForCausalLM" in glm_archs or (
            cfg.get("model_type") == "glm4"
        )
        # qwen2moe: gated shared expert; interleaved dense layers are
        # not implemented — reject rather than serve wrong logits
        is_qwen2moe = any(a.startswith("Qwen2Moe") for a in archs)
        if is_qwen2moe and (
            cfg.get("decoder_sparse_step", 1) != 1
            or cfg.get("mlp_only_layers")
        ):
            raise ValueError(
                "qwen2moe with decoder_sparse_step != 1 or mlp_only_layers "
                "is not supported (interleaved dense/sparse layers)"
            )
        # layer_types: per-layer sliding/full alternation (gpt-oss,
        # gemma-2/3 style)
        layer_windows: tuple = ()
        if (is_gptoss or is_gemma2 or is_gemma3) and cfg.get("layer_types"):
            sw = cfg.get("sliding_window") or 0
            layer_windows = tuple(
                sw if t == "sliding_attention" else 0
                for t in cfg["layer_types"]
            )
        elif is_gemma3 and cfg.get("sliding_window") and cfg.get(
            "sliding_window_pattern"
        ):
            # original gemma-3 uploads predate layer_types: every Nth
            # layer is full attention (HF: sliding iff (i+1) % N != 0)
            sw, n = cfg["sliding_window"], cfg["sliding_window_pattern"]
            layer_windows = tuple(
                sw if (i + 1) % n else 0
                for i in range(cfg.get("num_hidden_layers", 32))
            )
        elif is_gemma3 and cfg.get("sliding_window"):
            raise ValueError(
                "gemma-3 config has sliding_window but neither "
                "layer_types nor sliding_window_pattern — cannot "
                "recover the sliding/full alternation; refusing to "
                "serve wrong attention"
            )
        elif is_gemma2 and cfg.get("sliding_window"):
            # original gemma-2 uploads predate the layer_types key: the
            # architecture alternates sliding on EVEN layers
            # (modeling_gemma2: sliding iff layer_idx % 2 == 0) — a bare
            # global window would wrongly mask the full-attention layers
            sw = cfg["sliding_window"]
            layer_windows = tuple(
                sw if i % 2 == 0 else 0
                for i in range(cfg.get("num_hidden_layers", 32))
            )

        # Phi-3 keeps original_max_position_embeddings at the TOP level
        # of config.json; the longrope math needs it inside the scaling
        # dict (where yarn/llama3 checkpoints put theirs)
        rope_scaling = cfg.get("rope_scaling")
        if (
            rope_scaling
            and (rope_scaling.get("rope_type") or rope_scaling.get("type"))
            == "longrope"
            and "original_max_position_embeddings" not in rope_scaling
            and cfg.get("original_max_position_embeddings")
        ):
            rope_scaling = dict(
                rope_scaling,
                original_max_position_embeddings=cfg[
                    "original_max_position_embeddings"
                ],
            )
        act = cfg.get("hidden_act") or cfg.get("hidden_activation") or "silu"
        if act in ("gelu", "gelu_pytorch_tanh", "gelu_tanh"):
            act = "gelu_tanh"
        return ModelConfig(
            vocab_size=cfg.get("vocab_size", 32000),
            hidden_size=cfg.get("hidden_size", 4096),
            intermediate_size=cfg.get("intermediate_size", 11008),
            num_layers=cfg.get("num_hidden_layers", 32),
            num_heads=cfg.get("num_attention_heads", 32),
            num_kv_heads=cfg.get("num_key_value_heads", cfg.get("num_attention_heads", 32)),
            head_dim=cfg.get("head_dim", 0) or 0,
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_partial_factor=cfg.get("partial_rotary_factor") or 1.0,
            rope_scaling=rope_scaling,
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position_embeddings=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", is_gemma),
            attention_bias=qkv_bias,
            # qwen3 (dense and MoE): per-head q/k RMS norm, no qkv bias
            qk_norm=any(a.startswith("Qwen3") for a in archs) or is_gemma3
            or is_olmo2,
            layer_windows=layer_windows,
            attn_sinks=is_gptoss,
            moe_act="gptoss_clamp" if is_gptoss else "swiglu",
            o_bias=is_gptoss and bool(cfg.get("attention_bias")),
            # mixtral: num_local_experts; deepseek: n_routed_experts;
            # qwen2moe/qwen3moe: the bare num_experts key
            num_experts=cfg.get(
                "num_local_experts",
                cfg.get(
                    "n_routed_experts",
                    cfg.get("num_experts", 0)
                    if any(a.startswith(("Qwen3", "Qwen2Moe"))
                           for a in archs) else 0,
                ),
            ) or 0,
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
            moe_intermediate_size=cfg.get("moe_intermediate_size", 0) or 0,
            # qwen2moe: ONE gated shared expert of its own width
            num_shared_experts=cfg.get("n_shared_experts", 0) or (
                1 if is_qwen2moe else 0
            ),
            shared_expert_size=(
                cfg.get("shared_expert_intermediate_size", 0) or 0
            ) if is_qwen2moe else 0,
            shared_expert_gate=is_qwen2moe,
            first_dense_layers=cfg.get("first_k_dense_replace", 0) or 0,
            norm_topk_prob=cfg.get("norm_topk_prob", True),
            # deepseek_v2/v3 (R1 = V3): sigmoid scoring + gate bias and
            # group-limited top-k arrive with topk_method "noaux_tc"
            moe_scoring=cfg.get("scoring_func", "softmax"),
            moe_gate_bias=cfg.get("topk_method") == "noaux_tc",
            moe_group_score=(
                "top2" if cfg.get("topk_method") == "noaux_tc" else "max"
            ),
            routed_scaling_factor=cfg.get("routed_scaling_factor", 1.0),
            n_group=cfg.get("n_group", 0) or 0,
            topk_group=cfg.get("topk_group", 0) or 0,
            q_lora_rank=cfg.get("q_lora_rank") or 0,
            kv_lora_rank=cfg.get("kv_lora_rank") or 0,
            qk_nope_head_dim=cfg.get("qk_nope_head_dim") or 0,
            qk_rope_head_dim=cfg.get("qk_rope_head_dim") or 0,
            v_head_dim=cfg.get("v_head_dim") or 0,
            # interleaved (GPT-J-pair) rope storage: MLA checkpoints
            # interleave the TRAILING rope dims, GLM the LEADING partial
            # dims — both de-interleave at load so the runtime rotation
            # stays the fast half-split form
            rope_interleave=cfg.get(
                "rope_interleave",
                (cfg.get("model_type", "").startswith("deepseek")
                 and bool(cfg.get("kv_lora_rank"))) or is_glm,
            ),
            # with per-layer windows the GLOBAL width stays 0 — the
            # homogeneous paths/gates must not window every layer
            sliding_window=(
                0 if layer_windows else (cfg.get("sliding_window") or 0)
            ),
            hidden_act=act if act != "silu" else "silu",
            rms_add_unit=is_gemma,
            attn_softcap=(cfg.get("attn_logit_softcapping") or 0.0)
            if is_gemma2 else 0.0,
            final_softcap=(cfg.get("final_logit_softcapping") or 0.0)
            if is_gemma2 else 0.0,
            post_norms=is_gemma2 or is_gemma3 or is_glm4 or is_olmo2,
            norm_after=is_olmo2,
            qk_norm_full=is_olmo2,
            attn_scale_base=(cfg.get("query_pre_attn_scalar") or 0)
            if (is_gemma2 or is_gemma3) else 0,
            rope_local_theta=(cfg.get("rope_local_base_freq") or 0.0)
            if is_gemma3 else 0.0,
            scale_embed=is_gemma,
            dtype=cfg.get("torch_dtype") or "bfloat16",
        )

    @staticmethod
    def from_local_path(path: str) -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            return ModelConfig.from_hf_config(json.load(f))

    @staticmethod
    def tiny(**overrides) -> "ModelConfig":
        """A small config for tests/benches."""
        base = dict(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            max_position_embeddings=512,
        )
        base.update(overrides)
        return ModelConfig(**base)

    @staticmethod
    def tiny_mla(**overrides) -> "ModelConfig":
        """A small DeepSeek-shaped MLA config (compressed latent cache,
        absorbed attention) for tests/benches — ONE definition so shape
        tweaks can't drift between the many tests that need it."""
        base = dict(
            num_heads=4, num_kv_heads=4, kv_lora_rank=32,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            q_lora_rank=24, num_layers=2,
        )
        base.update(overrides)
        return ModelConfig.tiny(**base)

    # llama-3-8b-ish for benches
    @staticmethod
    def llama3_8b(**overrides) -> "ModelConfig":
        base = dict(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
            rope_theta=500000.0, max_position_embeddings=8192,
        )
        base.update(overrides)
        return ModelConfig(**base)

    # llama-3-70b (BASELINE config 4: the disagg + router north star)
    @staticmethod
    def llama3_70b(**overrides) -> "ModelConfig":
        base = dict(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_layers=80, num_heads=64, num_kv_heads=8, head_dim=128,
            rope_theta=500000.0, max_position_embeddings=8192,
        )
        base.update(overrides)
        return ModelConfig(**base)

    # mixtral-8x22b (BASELINE config 5 alternative: classic EP decode)
    @staticmethod
    def mixtral_8x22b(**overrides) -> "ModelConfig":
        base = dict(
            vocab_size=32768, hidden_size=6144, intermediate_size=16384,
            num_layers=56, num_heads=48, num_kv_heads=8, head_dim=128,
            rope_theta=1000000.0, max_position_embeddings=65536,
            num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=16384, norm_topk_prob=True,
        )
        base.update(overrides)
        return ModelConfig(**base)

    # gpt-oss (published shapes): alternating sliding/full attention,
    # sinks, biased clamped-SwiGLU MoE, head_dim 64. 120b: 36 layers /
    # 128 experts; 20b: 24 layers / 32 experts — both top-4.
    @staticmethod
    def gptoss_120b(**overrides) -> "ModelConfig":
        base = dict(
            vocab_size=201088, hidden_size=2880, intermediate_size=2880,
            num_layers=36, num_heads=64, num_kv_heads=8, head_dim=64,
            rope_theta=150000.0, max_position_embeddings=131072,
            num_experts=128, num_experts_per_tok=4,
            moe_intermediate_size=2880, moe_act="gptoss_clamp",
            attn_sinks=True, o_bias=True, attention_bias=True,
            layer_windows=tuple(128 if i % 2 == 0 else 0
                                for i in range(36)),
            # the published YaRN extension (4k→128k): llama._rope_freqs
            # implements this ruleset (incl. the fractional correction
            # range gpt-oss's truncate=False keeps) — required for
            # correct logits past ~4k when real weights load through
            # this preset
            rope_scaling=dict(
                rope_type="yarn", factor=32.0, beta_fast=32.0,
                beta_slow=1.0, original_max_position_embeddings=4096,
                truncate=False,
            ),
        )
        base.update(overrides)
        return ModelConfig(**base)

    @staticmethod
    def gptoss_20b(**overrides) -> "ModelConfig":
        base = dict(num_layers=24, num_experts=32,
                    layer_windows=tuple(128 if i % 2 == 0 else 0
                                        for i in range(24)))
        base.update(overrides)
        return ModelConfig.gptoss_120b(**base)

    # deepseek-r1 = the DeepSeek-V3 architecture (BASELINE config 5
    # flagship: MLA latent cache + 256-expert sigmoid-scored MoE).
    # Shape fields follow the published V3 config.json.
    @staticmethod
    def deepseek_r1(**overrides) -> "ModelConfig":
        base = dict(
            vocab_size=129280, hidden_size=7168, intermediate_size=18432,
            num_layers=61, num_heads=128, num_kv_heads=128,
            rope_theta=10000.0, max_position_embeddings=163840,
            num_experts=256, num_experts_per_tok=8,
            moe_intermediate_size=2048, num_shared_experts=1,
            first_dense_layers=3, norm_topk_prob=True,
            moe_scoring="sigmoid", moe_gate_bias=True,
            routed_scaling_factor=2.5, n_group=8, topk_group=4,
            moe_group_score="top2",
            q_lora_rank=1536, kv_lora_rank=512,
            qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
            # the published config.json's YaRN extension (4k→160k) and
            # GPT-J-interleaved rope storage — required for correct
            # logits when real R1 weights load through this preset
            rope_scaling=dict(
                type="yarn", factor=40.0, beta_fast=32.0, beta_slow=1.0,
                mscale=1.0, mscale_all_dim=1.0,
                original_max_position_embeddings=4096,
            ),
            rope_interleave=True,
        )
        base.update(overrides)
        return ModelConfig(**base)

"""HF checkpoint loading: safetensors -> the engine's param pytree.

Replaces the reference's delegation of weight loading to its engines (plus
hub download, launch/dynamo-run/src/hub.rs — here models are local paths;
fetching is an operator concern). Loads sharded ``*.safetensors`` files
lazily, maps HF llama naming onto the stacked-layer pytree, and can place
each tensor directly onto its mesh sharding to avoid a full host copy of
the model per process.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

logger = logging.getLogger(__name__)


def _np_dtype(dtype: str):
    return {"bfloat16": jnp.bfloat16, "float32": np.float32, "float16": np.float16}[dtype]


def load_llama_params(
    path: str,
    cfg: ModelConfig,
    mesh=None,
    dtype: Optional[str] = None,
) -> dict:
    """Load a HF llama-family checkpoint directory into the stacked pytree
    used by dynamo_tpu.models.llama."""
    if cfg.is_moe and cfg.first_dense_layers:
        # DeepSeek first_k_dense_replace: leading dense layers in an
        # otherwise-MoE stack. The stacked-scan pytree is homogeneous;
        # heterogeneous stacks need the split-scan model variant
        # (tracked follow-up) — fail loudly instead of KeyError soup.
        raise NotImplementedError(
            f"checkpoint has {cfg.first_dense_layers} leading dense "
            "layers (first_k_dense_replace); mixed dense/MoE stacks "
            "are not supported yet"
        )
    from safetensors import safe_open

    dt = _np_dtype(dtype or str(cfg.dtype))
    files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {path}")

    # build tensor name -> file map (honors index.json if present)
    index_file = os.path.join(path, "model.safetensors.index.json")
    name_to_file: dict[str, str] = {}
    if os.path.exists(index_file):
        with open(index_file) as f:
            name_to_file = json.load(f)["weight_map"]
    else:
        for fname in files:
            with safe_open(os.path.join(path, fname), framework="numpy") as f:
                for name in f.keys():
                    name_to_file[name] = fname

    handles: dict[str, object] = {}

    def get(name: str) -> np.ndarray:
        fname = name_to_file[name]
        if fname not in handles:
            handles[fname] = safe_open(os.path.join(path, fname), framework="numpy")
        t = handles[fname].get_tensor(name)
        return t

    L = cfg.num_layers

    def stack(fmt: str, transpose: bool = True) -> np.ndarray:
        mats = []
        for i in range(L):
            t = get(fmt.format(i=i))
            mats.append(t.T if transpose else t)
        return np.stack(mats)

    layers: dict = {
        "attn_norm": stack("model.layers.{i}.input_layernorm.weight", transpose=False),
        "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
        "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
        "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
        "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
        "mlp_norm": stack("model.layers.{i}.post_attention_layernorm.weight", transpose=False),
    }
    if cfg.is_moe:
        X = cfg.num_experts

        def has(name: str) -> bool:
            return name in name_to_file

        def stack_experts(mix_fmt: str, ds_fmt: str) -> np.ndarray:
            """[L, X, in, out] from per-expert tensors; supports Mixtral
            (block_sparse_moe.experts.N.w1/w3/w2) and DeepSeek/Qwen-MoE
            (mlp.experts.N.gate/up/down_proj) naming."""
            out = []
            for i in range(L):
                fmt = mix_fmt if has(mix_fmt.format(i=i, x=0)) else ds_fmt
                out.append(
                    np.stack([get(fmt.format(i=i, x=x)).T for x in range(X)])
                )
            return np.stack(out)

        gate_mix = "model.layers.{i}.block_sparse_moe.gate.weight"
        gate_ds = "model.layers.{i}.mlp.gate.weight"
        layers["moe_gate"] = np.stack(
            [
                get((gate_mix if has(gate_mix.format(i=i)) else gate_ds).format(i=i)).T
                for i in range(L)
            ]
        )
        layers["we_gate"] = stack_experts(
            "model.layers.{i}.block_sparse_moe.experts.{x}.w1.weight",
            "model.layers.{i}.mlp.experts.{x}.gate_proj.weight",
        )
        layers["we_up"] = stack_experts(
            "model.layers.{i}.block_sparse_moe.experts.{x}.w3.weight",
            "model.layers.{i}.mlp.experts.{x}.up_proj.weight",
        )
        layers["we_down"] = stack_experts(
            "model.layers.{i}.block_sparse_moe.experts.{x}.w2.weight",
            "model.layers.{i}.mlp.experts.{x}.down_proj.weight",
        )
        if cfg.num_shared_experts:
            layers["shared_gate"] = stack(
                "model.layers.{i}.mlp.shared_experts.gate_proj.weight"
            )
            layers["shared_up"] = stack(
                "model.layers.{i}.mlp.shared_experts.up_proj.weight"
            )
            layers["shared_down"] = stack(
                "model.layers.{i}.mlp.shared_experts.down_proj.weight"
            )
    else:
        layers["w_gate"] = stack("model.layers.{i}.mlp.gate_proj.weight")
        layers["w_up"] = stack("model.layers.{i}.mlp.up_proj.weight")
        layers["w_down"] = stack("model.layers.{i}.mlp.down_proj.weight")
    params: dict = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
        "layers": layers,
    }
    if cfg.attention_bias:
        params["layers"]["bq"] = stack("model.layers.{i}.self_attn.q_proj.bias", transpose=False)
        params["layers"]["bk"] = stack("model.layers.{i}.self_attn.k_proj.bias", transpose=False)
        params["layers"]["bv"] = stack("model.layers.{i}.self_attn.v_proj.bias", transpose=False)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight").T

    if cfg.rms_add_unit:
        # gemma checkpoints store norm weights as offsets (the model
        # scales by 1 + w); folding the +1 here keeps every runtime
        # rms_norm call family-agnostic
        layers["attn_norm"] = layers["attn_norm"] + 1.0
        layers["mlp_norm"] = layers["mlp_norm"] + 1.0
        params["final_norm"] = params["final_norm"] + 1.0

    # cast + (optionally) place on mesh shard-by-shard
    if mesh is not None:
        from ..parallel.mesh import shard_params

        params = jax.tree.map(lambda x: jnp.asarray(x, dt), params)
        params = shard_params(params, mesh)
    else:
        params = jax.tree.map(lambda x: jnp.asarray(x, dt), params)
    for h in handles.values():
        del h
    return params


def save_llama_params(path: str, params: dict, cfg=None) -> None:
    """Write params back out as a single safetensors file (testing and
    fixture generation)."""
    from safetensors.numpy import save_file

    flat: dict[str, np.ndarray] = {}
    L = params["layers"]["wq"].shape[0]
    lay = dict(params["layers"])
    final_norm = params["final_norm"]
    if cfg is not None and getattr(cfg, "rms_add_unit", False):
        # inverse of the load-time (1 + w) fold: gemma checkpoints store
        # norm OFFSETS
        lay["attn_norm"] = lay["attn_norm"] - 1.0
        lay["mlp_norm"] = lay["mlp_norm"] - 1.0
        final_norm = final_norm - 1.0
    flat["model.embed_tokens.weight"] = np.asarray(params["embed"], np.float32)
    flat["model.norm.weight"] = np.asarray(final_norm, np.float32)
    names = {
        "attn_norm": ("model.layers.{i}.input_layernorm.weight", False),
        "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
        "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
        "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
        "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
        "mlp_norm": ("model.layers.{i}.post_attention_layernorm.weight", False),
        "w_gate": ("model.layers.{i}.mlp.gate_proj.weight", True),
        "w_up": ("model.layers.{i}.mlp.up_proj.weight", True),
        "w_down": ("model.layers.{i}.mlp.down_proj.weight", True),
    }
    for key, (fmt, transpose) in names.items():
        if key not in lay:
            continue
        for i in range(L):
            t = np.asarray(lay[key][i], np.float32)
            flat[fmt.format(i=i)] = t.T.copy() if transpose else t
    if "we_gate" in lay:  # MoE: Mixtral naming (shared experts: DeepSeek's)
        X = lay["we_gate"].shape[1]
        expert_names = {
            "we_gate": "model.layers.{i}.block_sparse_moe.experts.{x}.w1.weight",
            "we_up": "model.layers.{i}.block_sparse_moe.experts.{x}.w3.weight",
            "we_down": "model.layers.{i}.block_sparse_moe.experts.{x}.w2.weight",
        }
        shared_names = {
            "shared_gate": "model.layers.{i}.mlp.shared_experts.gate_proj.weight",
            "shared_up": "model.layers.{i}.mlp.shared_experts.up_proj.weight",
            "shared_down": "model.layers.{i}.mlp.shared_experts.down_proj.weight",
        }
        for i in range(L):
            flat[f"model.layers.{i}.block_sparse_moe.gate.weight"] = np.asarray(
                lay["moe_gate"][i], np.float32
            ).T.copy()
            for key, fmt in expert_names.items():
                for x in range(X):
                    flat[fmt.format(i=i, x=x)] = np.asarray(
                        lay[key][i, x], np.float32
                    ).T.copy()
            for key, fmt in shared_names.items():
                if key in lay:
                    flat[fmt.format(i=i)] = np.asarray(
                        lay[key][i], np.float32
                    ).T.copy()
    if "lm_head" in params:
        flat["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T.copy()
    save_file(flat, os.path.join(path, "model.safetensors"))

"""HF checkpoint loading: safetensors -> the engine's param pytree.

Replaces the reference's delegation of weight loading to its engines (plus
hub download, launch/dynamo-run/src/hub.rs — here models are local paths;
fetching is an operator concern). Loads sharded ``*.safetensors`` files
lazily, maps HF llama naming onto the stacked-layer pytree, and can place
each tensor directly onto its mesh sharding to avoid a full host copy of
the model per process.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

logger = logging.getLogger(__name__)


def _np_dtype(dtype: str):
    return {"bfloat16": jnp.bfloat16, "float32": np.float32, "float16": np.float16}[dtype]


def load_llama_params(
    path: str,
    cfg: ModelConfig,
    mesh=None,
    dtype: Optional[str] = None,
) -> dict:
    """Load a HF llama-family or DeepSeek-MLA checkpoint directory into
    the stacked pytree used by dynamo_tpu.models.llama. DeepSeek's
    first_k_dense_replace leading dense layers land in a second stacked
    group (``dense_layers``) that the forward scans separately."""
    from safetensors import safe_open

    dt = _np_dtype(dtype or str(cfg.dtype))
    files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {path}")

    # build tensor name -> file map (honors index.json if present)
    index_file = os.path.join(path, "model.safetensors.index.json")
    name_to_file: dict[str, str] = {}
    if os.path.exists(index_file):
        with open(index_file) as f:
            name_to_file = json.load(f)["weight_map"]
    else:
        for fname in files:
            with safe_open(os.path.join(path, fname), framework="numpy") as f:
                for name in f.keys():
                    name_to_file[name] = fname

    handles: dict[str, object] = {}

    # multimodal checkpoints (gemma-3 conditional generation et al.)
    # nest the language model: weights live under language_model.model.*
    # (or model.language_model.* in newer transformers) instead of the
    # bare model.* this loader's name table uses — resolve the prefix
    # once from wherever the embedding actually lives
    _prefix = ""
    if "model.embed_tokens.weight" not in name_to_file:
        for cand in ("language_model.", "model.language_model."):
            if (
                cand + "model.embed_tokens.weight" in name_to_file
                or cand + "embed_tokens.weight" in name_to_file
            ):
                _prefix = cand
                break

    def _resolve(name: str) -> str:
        """Bare llama-family name -> this checkpoint's actual key.
        Tries, in order: the bare name (lm_head etc. stay top-level in
        multimodal checkpoints), prefix+name, and prefix replacing the
        leading "model." segment."""
        if not _prefix or name in name_to_file:
            return name
        full = _prefix + name
        if full in name_to_file:
            return full
        if name.startswith("model."):
            alt = _prefix + name[len("model."):]
            if alt in name_to_file:
                return alt
        return name

    def get(name: str) -> np.ndarray:
        name = _resolve(name)
        fname = name_to_file[name]
        if fname not in handles:
            handles[fname] = safe_open(os.path.join(path, fname), framework="numpy")
        t = handles[fname].get_tensor(name)
        return t

    L = cfg.num_layers

    def stack(fmt: str, rng, transpose: bool = True) -> np.ndarray:
        mats = []
        for i in rng:
            t = get(fmt.format(i=i))
            mats.append(t.T if transpose else t)
        return np.stack(mats)

    def has(name: str) -> bool:
        return _resolve(name) in name_to_file

    def deinterleave_rope(w: np.ndarray, n_head: int, d_head: int,
                          d_rope: int, leading: bool = False) -> np.ndarray:
        """GPT-J-pair rope columns -> the half-split layout the runtime
        rotation uses, for a [..., n_head*d_head] projection (or stacked
        bias). DeepSeek/MLA interleaves the TRAILING d_rope dims of each
        head; GLM (``leading=True``) the LEADING ones."""
        if not cfg.rope_interleave:
            return w
        v = w.reshape(*w.shape[:-1], n_head, d_head)
        perm = np.concatenate(
            [np.arange(0, d_rope, 2), np.arange(1, d_rope, 2)]
        )
        if leading:
            v = np.concatenate([v[..., :d_rope][..., perm],
                                v[..., d_rope:]], -1)
        else:
            v = np.concatenate([v[..., : d_head - d_rope],
                                v[..., d_head - d_rope:][..., perm]], -1)
        return v.reshape(w.shape)

    def attn_leaves(rng) -> dict:
        out = {}
        if not cfg.norm_after:  # olmo-2 has no input norms at all
            out["attn_norm"] = stack(
                "model.layers.{i}.input_layernorm.weight",
                rng, transpose=False,
            )
        glm4_norms = cfg.post_norms and has(
            "model.layers.{}.post_self_attn_layernorm.weight"
            .format(next(iter(rng)))
        )
        if cfg.norm_after:
            # olmo-2: ONLY output norms exist — post_attention on the
            # attention output, post_feedforward on the MLP output
            out["attn_post_norm"] = stack(
                "model.layers.{i}.post_attention_layernorm.weight",
                rng, transpose=False,
            )
            out["mlp_post_norm"] = stack(
                "model.layers.{i}.post_feedforward_layernorm.weight",
                rng, transpose=False,
            )
        elif glm4_norms:
            # glm-4 sandwich naming: post_self_attn / post_mlp norms,
            # with post_attention_layernorm keeping its llama meaning
            # (the pre-FFN norm)
            out["attn_post_norm"] = stack(
                "model.layers.{i}.post_self_attn_layernorm.weight",
                rng, transpose=False,
            )
            out["mlp_norm"] = stack(
                "model.layers.{i}.post_attention_layernorm.weight",
                rng, transpose=False,
            )
            out["mlp_post_norm"] = stack(
                "model.layers.{i}.post_mlp_layernorm.weight",
                rng, transpose=False,
            )
        elif cfg.post_norms:
            # gemma-2 sandwich norms: post_attention_layernorm is the
            # ATTENTION OUTPUT norm here (not the pre-FFN norm it names
            # in llama-family checkpoints)
            out["attn_post_norm"] = stack(
                "model.layers.{i}.post_attention_layernorm.weight",
                rng, transpose=False,
            )
            out["mlp_norm"] = stack(
                "model.layers.{i}.pre_feedforward_layernorm.weight",
                rng, transpose=False,
            )
            out["mlp_post_norm"] = stack(
                "model.layers.{i}.post_feedforward_layernorm.weight",
                rng, transpose=False,
            )
        else:
            out["mlp_norm"] = stack(
                "model.layers.{i}.post_attention_layernorm.weight",
                rng, transpose=False,
            )
        if cfg.is_mla:
            dqk, dr = cfg.qk_head_dim, cfg.qk_rope_head_dim
            H = cfg.num_heads
            if cfg.q_lora_rank:
                out["wq_a"] = stack(
                    "model.layers.{i}.self_attn.q_a_proj.weight", rng
                )
                out["q_norm"] = stack(
                    "model.layers.{i}.self_attn.q_a_layernorm.weight",
                    rng, transpose=False,
                )
                wq_b = stack("model.layers.{i}.self_attn.q_b_proj.weight", rng)
                out["wq_b"] = np.stack(
                    [deinterleave_rope(w, H, dqk, dr) for w in wq_b]
                )
            else:
                wq = stack("model.layers.{i}.self_attn.q_proj.weight", rng)
                out["wq"] = np.stack(
                    [deinterleave_rope(w, H, dqk, dr) for w in wq]
                )
            wkv_a = stack(
                "model.layers.{i}.self_attn.kv_a_proj_with_mqa.weight", rng
            )
            # rope dims are the trailing d_rope columns (one "head")
            out["wkv_a"] = np.stack(
                [
                    deinterleave_rope(w, 1, cfg.kv_lora_rank + dr, dr)
                    for w in wkv_a
                ]
            )
            out["kv_norm"] = stack(
                "model.layers.{i}.self_attn.kv_a_layernorm.weight",
                rng, transpose=False,
            )
            out["wkv_b"] = stack(
                "model.layers.{i}.self_attn.kv_b_proj.weight", rng
            )
            out["wo"] = stack("model.layers.{i}.self_attn.o_proj.weight", rng)
        elif has(f"model.layers.{next(iter(rng))}.self_attn.qkv_proj.weight"):
            # Phi-3 fuses q/k/v into one projection ([Hq+2Hkv]*D rows,
            # q first) and gate/up likewise — split to our leaves
            qkv = stack("model.layers.{i}.self_attn.qkv_proj.weight", rng)
            dq = cfg.num_heads * cfg.head_dim
            dkv = cfg.num_kv_heads * cfg.head_dim
            out["wq"] = qkv[..., :dq]
            out["wk"] = qkv[..., dq : dq + dkv]
            out["wv"] = qkv[..., dq + dkv :]
            out["wo"] = stack("model.layers.{i}.self_attn.o_proj.weight", rng)
        else:
            out["wq"] = stack("model.layers.{i}.self_attn.q_proj.weight", rng)
            out["wk"] = stack("model.layers.{i}.self_attn.k_proj.weight", rng)
            out["wv"] = stack("model.layers.{i}.self_attn.v_proj.weight", rng)
            out["wo"] = stack("model.layers.{i}.self_attn.o_proj.weight", rng)
            if cfg.attention_bias:
                out["bq"] = stack("model.layers.{i}.self_attn.q_proj.bias",
                                  rng, transpose=False)
                out["bk"] = stack("model.layers.{i}.self_attn.k_proj.bias",
                                  rng, transpose=False)
                out["bv"] = stack("model.layers.{i}.self_attn.v_proj.bias",
                                  rng, transpose=False)
            if cfg.rope_interleave:
                # GLM: the LEADING partial-rotary dims of every head are
                # stored as GPT-J pairs; permuting q AND k the same way
                # leaves attention scores identical while the runtime
                # keeps the fast half-split rotation
                rot = cfg.rope_partial_dim or cfg.head_dim
                for key, n_head in (("wq", cfg.num_heads),
                                    ("wk", cfg.num_kv_heads),
                                    ("bq", cfg.num_heads),
                                    ("bk", cfg.num_kv_heads)):
                    if key in out:
                        out[key] = deinterleave_rope(
                            out[key], n_head, cfg.head_dim, rot,
                            leading=True,
                        )
            if cfg.qk_norm:  # qwen3 per-head q/k norms, weight [head_dim]
                out["q_norm"] = stack(
                    "model.layers.{i}.self_attn.q_norm.weight", rng,
                    transpose=False,
                )
                out["k_norm"] = stack(
                    "model.layers.{i}.self_attn.k_norm.weight", rng,
                    transpose=False,
                )
            if cfg.o_bias:
                out["bo"] = stack("model.layers.{i}.self_attn.o_proj.bias",
                                  rng, transpose=False)
            if cfg.attn_sinks:
                out["sinks"] = stack(
                    "model.layers.{i}.self_attn.sinks", rng, transpose=False
                ).astype(np.float32)
        return out

    def dense_ffn_leaves(rng) -> dict:
        if has(f"model.layers.{next(iter(rng))}.mlp.gate_up_proj.weight"):
            # Phi-3 fused gate_up ([2F, E] rows: gate then up)
            gu = stack("model.layers.{i}.mlp.gate_up_proj.weight", rng)
            F2 = gu.shape[-1] // 2
            return {
                "w_gate": gu[..., :F2],
                "w_up": gu[..., F2:],
                "w_down": stack("model.layers.{i}.mlp.down_proj.weight", rng),
            }
        return {
            "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight", rng),
            "w_up": stack("model.layers.{i}.mlp.up_proj.weight", rng),
            "w_down": stack("model.layers.{i}.mlp.down_proj.weight", rng),
        }

    def gptoss_moe_leaves(rng) -> dict:
        """gpt-oss expert tensors are FUSED per layer (not per expert):
        gate_up_proj [X, E, 2F] with gate/up INTERLEAVED on the last
        axis (gate = [..., ::2], up = [..., 1::2]) plus bias [X, 2F];
        down_proj [X, F, E] (+bias [X, E]) is already in our we_down
        orientation; the router is mlp.router with a LOGIT bias."""
        gu = np.stack(
            [get(f"model.layers.{i}.mlp.experts.gate_up_proj") for i in rng]
        )  # [L, X, E, 2F]
        gub = np.stack(
            [get(f"model.layers.{i}.mlp.experts.gate_up_proj_bias")
             for i in rng]
        )  # [L, X, 2F]
        return {
            "moe_gate": stack("model.layers.{i}.mlp.router.weight", rng),
            "moe_router_bias": stack(
                "model.layers.{i}.mlp.router.bias", rng, transpose=False
            ).astype(np.float32),
            "we_gate": gu[..., ::2],
            "we_up": gu[..., 1::2],
            "be_gate": gub[..., ::2],
            "be_up": gub[..., 1::2],
            "we_down": np.stack(
                [get(f"model.layers.{i}.mlp.experts.down_proj") for i in rng]
            ),
            "be_down": np.stack(
                [get(f"model.layers.{i}.mlp.experts.down_proj_bias")
                 for i in rng]
            ),
        }

    def moe_ffn_leaves(rng) -> dict:
        if cfg.moe_act == "gptoss_clamp":
            return gptoss_moe_leaves(rng)
        X = cfg.num_experts

        def stack_experts(mix_fmt: str, ds_fmt: str) -> np.ndarray:
            """[L, X, in, out] from per-expert tensors; supports Mixtral
            (block_sparse_moe.experts.N.w1/w3/w2) and DeepSeek/Qwen-MoE
            (mlp.experts.N.gate/up/down_proj) naming."""
            out = []
            for i in rng:
                fmt = mix_fmt if has(mix_fmt.format(i=i, x=0)) else ds_fmt
                out.append(
                    np.stack([get(fmt.format(i=i, x=x)).T for x in range(X)])
                )
            return np.stack(out)

        gate_mix = "model.layers.{i}.block_sparse_moe.gate.weight"
        gate_ds = "model.layers.{i}.mlp.gate.weight"
        out = {
            "moe_gate": np.stack(
                [
                    get((gate_mix if has(gate_mix.format(i=i))
                         else gate_ds).format(i=i)).T
                    for i in rng
                ]
            ),
            "we_gate": stack_experts(
                "model.layers.{i}.block_sparse_moe.experts.{x}.w1.weight",
                "model.layers.{i}.mlp.experts.{x}.gate_proj.weight",
            ),
            "we_up": stack_experts(
                "model.layers.{i}.block_sparse_moe.experts.{x}.w3.weight",
                "model.layers.{i}.mlp.experts.{x}.up_proj.weight",
            ),
            "we_down": stack_experts(
                "model.layers.{i}.block_sparse_moe.experts.{x}.w2.weight",
                "model.layers.{i}.mlp.experts.{x}.down_proj.weight",
            ),
        }
        if cfg.moe_gate_bias:
            out["moe_gate_bias"] = stack(
                "model.layers.{i}.mlp.gate.e_score_correction_bias",
                rng, transpose=False,
            ).astype(np.float32)
        if cfg.num_shared_experts:
            # DeepSeek writes plural "shared_experts", Qwen2-MoE writes
            # singular "shared_expert" — same tensors either way
            plural = "model.layers.{i}.mlp.shared_experts.gate_proj.weight"
            base = (
                "model.layers.{i}.mlp.shared_experts"
                if has(plural.format(i=next(iter(rng))))
                else "model.layers.{i}.mlp.shared_expert"
            )
            out["shared_gate"] = stack(base + ".gate_proj.weight", rng)
            out["shared_up"] = stack(base + ".up_proj.weight", rng)
            out["shared_down"] = stack(base + ".down_proj.weight", rng)
            if cfg.shared_expert_gate:  # qwen2moe: [1, E] -> [E, 1]
                out["shared_egate"] = stack(
                    "model.layers.{i}.mlp.shared_expert_gate.weight", rng
                )
        return out

    kd = cfg.first_dense_layers if cfg.is_moe else 0
    layers: dict = attn_leaves(range(kd, L))
    layers.update(
        moe_ffn_leaves(range(kd, L)) if cfg.is_moe
        else dense_ffn_leaves(range(kd, L))
    )
    params: dict = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
        "layers": layers,
    }
    if kd:
        dense = attn_leaves(range(0, kd))
        dense.update(dense_ffn_leaves(range(0, kd)))
        params["dense_layers"] = dense
    if not cfg.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight").T

    if cfg.rms_add_unit:
        # gemma checkpoints store norm weights as offsets (the model
        # scales by 1 + w); folding the +1 here keeps every runtime
        # rms_norm call family-agnostic (incl. gemma-3's per-head q/k
        # norms, which share the convention)
        for key in ("attn_norm", "mlp_norm", "attn_post_norm",
                    "mlp_post_norm", "q_norm", "k_norm"):
            if key in layers:
                layers[key] = layers[key] + 1.0
        params["final_norm"] = params["final_norm"] + 1.0

    # cast + (optionally) place on mesh shard-by-shard
    if mesh is not None:
        from ..parallel.mesh import shard_params

        params = jax.tree.map(lambda x: jnp.asarray(x, dt), params)
        params = shard_params(params, mesh)
    else:
        params = jax.tree.map(lambda x: jnp.asarray(x, dt), params)
    for h in handles.values():
        del h
    return params


def save_llama_params(path: str, params: dict, cfg=None) -> None:
    """Write params back out as a single safetensors file (testing and
    fixture generation)."""
    from safetensors.numpy import save_file

    if cfg is not None and getattr(cfg, "rope_interleave", False):
        raise NotImplementedError(
            "saving back to the interleaved-rope checkpoint convention "
            "is not supported (the loader de-interleaved at load)"
        )
    flat: dict[str, np.ndarray] = {}
    flat["model.embed_tokens.weight"] = np.asarray(params["embed"], np.float32)
    final_norm = params["final_norm"]
    if cfg is not None and getattr(cfg, "rms_add_unit", False):
        # inverse of the load-time (1 + w) fold: gemma checkpoints store
        # norm OFFSETS
        final_norm = final_norm - 1.0
    flat["model.norm.weight"] = np.asarray(final_norm, np.float32)
    names = {
        "attn_norm": ("model.layers.{i}.input_layernorm.weight", False),
        "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
        "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
        "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
        "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
        "mlp_norm": ("model.layers.{i}.post_attention_layernorm.weight", False),
        "w_gate": ("model.layers.{i}.mlp.gate_proj.weight", True),
        "w_up": ("model.layers.{i}.mlp.up_proj.weight", True),
        "w_down": ("model.layers.{i}.mlp.down_proj.weight", True),
        # MLA (models/mla.py)
        "wq_a": ("model.layers.{i}.self_attn.q_a_proj.weight", True),
        "q_norm": ("model.layers.{i}.self_attn.q_a_layernorm.weight", False),
        "wq_b": ("model.layers.{i}.self_attn.q_b_proj.weight", True),
        "wkv_a": ("model.layers.{i}.self_attn.kv_a_proj_with_mqa.weight", True),
        "kv_norm": ("model.layers.{i}.self_attn.kv_a_layernorm.weight", False),
        "wkv_b": ("model.layers.{i}.self_attn.kv_b_proj.weight", True),
        "moe_gate_bias": (
            "model.layers.{i}.mlp.gate.e_score_correction_bias", False
        ),
        "k_norm": ("model.layers.{i}.self_attn.k_norm.weight", False),
    }
    if cfg is not None and getattr(cfg, "qk_norm", False):
        # "q_norm" is shared between two checkpoint conventions: the MLA
        # q_a_layernorm (default above) and qwen3's per-head q_norm
        names["q_norm"] = (
            "model.layers.{i}.self_attn.q_norm.weight", False
        )
    if cfg is not None and getattr(cfg, "post_norms", False):
        # gemma-2 sandwich norms: post_attention_layernorm is the attn
        # OUTPUT norm; the pre-FFN norm gets its own name
        names["mlp_norm"] = (
            "model.layers.{i}.pre_feedforward_layernorm.weight", False
        )
        names["attn_post_norm"] = (
            "model.layers.{i}.post_attention_layernorm.weight", False
        )
        names["mlp_post_norm"] = (
            "model.layers.{i}.post_feedforward_layernorm.weight", False
        )

    def save_group(lay: dict, n: int, off: int) -> None:
        lay = dict(lay)
        if cfg is not None and getattr(cfg, "rms_add_unit", False):
            for key in ("attn_norm", "mlp_norm", "attn_post_norm",
                        "mlp_post_norm"):
                if key in lay:
                    lay[key] = lay[key] - 1.0
        for key, (fmt, transpose) in names.items():
            if key not in lay:
                continue
            for li in range(n):
                t = np.asarray(lay[key][li], np.float32)
                flat[fmt.format(i=off + li)] = t.T.copy() if transpose else t
        if "we_gate" in lay:  # MoE: Mixtral naming (shared: DeepSeek's)
            X = lay["we_gate"].shape[1]
            expert_names = {
                "we_gate": "model.layers.{i}.block_sparse_moe.experts.{x}.w1.weight",
                "we_up": "model.layers.{i}.block_sparse_moe.experts.{x}.w3.weight",
                "we_down": "model.layers.{i}.block_sparse_moe.experts.{x}.w2.weight",
            }
            # plural = DeepSeek convention; singular + gate = Qwen2-MoE
            sbase = (
                "model.layers.{i}.mlp.shared_expert"
                if "shared_egate" in lay
                else "model.layers.{i}.mlp.shared_experts"
            )
            shared_names = {
                "shared_gate": sbase + ".gate_proj.weight",
                "shared_up": sbase + ".up_proj.weight",
                "shared_down": sbase + ".down_proj.weight",
                "shared_egate":
                    "model.layers.{i}.mlp.shared_expert_gate.weight",
            }
            for li in range(n):
                i = off + li
                flat[f"model.layers.{i}.block_sparse_moe.gate.weight"] = (
                    np.asarray(lay["moe_gate"][li], np.float32).T.copy()
                )
                for key, fmt in expert_names.items():
                    for x in range(X):
                        flat[fmt.format(i=i, x=x)] = np.asarray(
                            lay[key][li, x], np.float32
                        ).T.copy()
                for key, fmt in shared_names.items():
                    if key in lay:
                        flat[fmt.format(i=i)] = np.asarray(
                            lay[key][li], np.float32
                        ).T.copy()

    def n_layers(group: dict) -> int:
        # attn_norm is absent for norm-after (olmo-2) params — count
        # from any leaf (all are layer-stacked on axis 0)
        return next(iter(group.values())).shape[0]

    kd = 0
    if "dense_layers" in params:
        kd = n_layers(params["dense_layers"])
        save_group(params["dense_layers"], kd, 0)
    save_group(params["layers"], n_layers(params["layers"]), kd)
    if "lm_head" in params:
        flat["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T.copy()
    save_file(flat, os.path.join(path, "model.safetensors"))

"""Model families for the native TPU engine (pure-JAX functional models).

The reference delegates model code to wrapped engines (vLLM/SGLang/TRT-LLM);
this framework owns its models natively: functional JAX forward passes over
a paged KV cache, sharded via jax.sharding over a device mesh.
"""

from .config import ModelConfig

__all__ = ["ModelConfig"]

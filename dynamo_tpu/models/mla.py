"""Multi-Latent Attention (DeepSeek-V2/V3/R1) over the paged cache.

The reference serves DeepSeek through its wrapped engines (README
workloads; the vLLM patch carries a deepseek_v2 tweak, patch:3548-3560).
Here MLA is TPU-native and built around the COMPRESSED cache from the
start:

  * per token the cache stores the kv_lora_rank latent ``c_kv`` (k-cache
    slot) and the head-shared rotated ``k_pe`` (v-cache slot) — a
    single-"head" paged layout ``[L, 1, N, bs, D]`` that rides the
    existing block tables / allocator / offload / transfer machinery
    unchanged (the two caches just have different trailing dims);
  * attention runs ABSORBED: q_nope is folded through the kv_b
    up-projection once per layer (``q_eff = q_nope @ w_kc``), scores are
    ``q_eff . c_kv + q_pe . k_pe`` against raw latents, and the output
    latent folds back through ``w_vc`` — no per-token reconstruction of
    full K/V, so HBM reads per step stay at
    ``kv_lora_rank + qk_rope_head_dim`` bytes/token (the entire point of
    MLA; 576 vs 2*128*Hkv for V3);
  * the XLA paths here (dense einsums over gathered pages, MQA-shaped:
    one shared KV stream, H query heads) are the correctness baseline
    and serve CPU/meshes; single-host TPU decode runs the Pallas latent
    kernel + merged one-write append (ops/mla_attention_pallas) — no
    per-step page gather, one cache write for all layers.

RoPE uses DeepSeek's YaRN variant over the qk_rope dims, with the
mscale cos/sin correction and the mscale_all_dim softmax-scale
correction (ModelConfig.mla_softmax_scale).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .config import ModelConfig, is_yarn, yarn_mscale

NEG_INF = -1e30


def mla_rope_freqs(cfg: ModelConfig) -> tuple[jnp.ndarray, float]:
    """(inv_freq over qk_rope_head_dim, cos/sin mscale ratio).

    YaRN per DeepSeek-V2: interpolate low-frequency dims by ``factor``,
    extrapolate high-frequency dims, linear-ramp between the correction
    range derived from beta_fast/beta_slow."""
    D = cfg.qk_rope_head_dim
    base = cfg.rope_theta
    inv = 1.0 / (base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    rs = cfg.rope_scaling or {}
    if is_yarn(rs):
        factor = rs.get("factor", 1.0)
        beta_fast = rs.get("beta_fast", 32)
        beta_slow = rs.get("beta_slow", 1)
        orig = rs.get("original_max_position_embeddings", 4096)

        def corr_dim(n_rot):
            return (D * math.log(orig / (n_rot * 2 * math.pi))) / (
                2 * math.log(base)
            )

        low = max(math.floor(corr_dim(beta_fast)), 0)
        high = min(math.ceil(corr_dim(beta_slow)), D - 1)
        ramp = jnp.clip(
            (jnp.arange(D // 2, dtype=jnp.float32) - low)
            / max(high - low, 0.001),
            0.0, 1.0,
        )
        extrap_mask = 1.0 - ramp
        inv = (inv / factor) * (1 - extrap_mask) + inv * extrap_mask
        msc = yarn_mscale(factor, rs.get("mscale", 1.0)) / yarn_mscale(
            factor, rs.get("mscale_all_dim", 0.0) or 0.0
        )
        return inv, msc
    return inv, 1.0


def rope_rotate(x: jnp.ndarray, positions: jnp.ndarray,
                inv_freq: jnp.ndarray, mscale: float = 1.0) -> jnp.ndarray:
    """Half-split rotation (same convention as llama.apply_rope) over the
    trailing rope dims; x: [..., T, Hx, Dr], positions: [..., T].

    DeepSeek checkpoints store rope dims INTERLEAVED (pairs); weights.py
    de-interleaves q_b/kv_a at load so runtime rotation stays the fast
    half-split form."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    cos = jnp.cos(angles)[..., None, :] * mscale
    sin = jnp.sin(angles)[..., None, :] * mscale
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _wkv_b_parts(lp: dict, cfg: ModelConfig):
    """Split the kv_b up-projection [kv_lora, H*(nope+v)] into
    w_kc [kv_lora, H, nope] and w_vc [kv_lora, H, v]."""
    H, dn, dv = cfg.num_heads, cfg.qk_nope_head_dim, cfg.v_head_dim
    w = lp["wkv_b"]
    if isinstance(w, dict):  # quantized {"q", "s"}: dequant for the fold
        w = w["q"].astype(jnp.bfloat16) * w["s"].astype(jnp.bfloat16)
    w = w.reshape(w.shape[0], H, dn + dv)
    return w[:, :, :dn], w[:, :, dn:]


def mla_q_and_latent(lp: dict, cfg: ModelConfig, x: jnp.ndarray,
                     positions: jnp.ndarray, inv_freq: jnp.ndarray,
                     mscale: float):
    """Shared projection head for prefill, decode, and verify.

    x: [..., E] with arbitrary leading batch dims; positions broadcasts
    against them (prefill [T]/[T,E], decode [B]/[B,E], verify [B,T]/
    [B,T,E]).
    Returns (q_eff [..., H, C], q_pe [..., H, R], c_kv [..., C],
    k_pe [..., R])
    with C = kv_lora_rank, R = qk_rope_head_dim. q_eff is the ABSORBED
    query (q_nope @ w_kc) scoring directly against cache latents."""
    from .llama import _mm, rms_norm

    H = cfg.num_heads
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    if cfg.q_lora_rank:
        q = _mm(rms_norm(_mm(x, lp["wq_a"]), lp["q_norm"],
                         cfg.rms_norm_eps), lp["wq_b"])
    else:
        q = _mm(x, lp["wq"])
    q = q.reshape(x.shape[:-1] + (H, dn + dr))
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    q_pe = rope_rotate(q_pe, positions, inv_freq, mscale)

    kv = _mm(x, lp["wkv_a"])  # [T, C + R]
    c_kv = rms_norm(kv[..., : cfg.kv_lora_rank], lp["kv_norm"],
                    cfg.rms_norm_eps)
    k_pe = kv[..., cfg.kv_lora_rank:]
    k_pe = rope_rotate(k_pe[..., None, :], positions, inv_freq,
                       mscale)[..., 0, :]

    w_kc, _ = _wkv_b_parts(lp, cfg)
    # fold q_nope through the k up-projection: [T, H, dn] x [C, H, dn]
    q_eff = jnp.einsum(
        "...hd,chd->...hc", q_nope.astype(jnp.float32),
        w_kc.astype(jnp.float32),
    ).astype(x.dtype)
    return q_eff, q_pe, c_kv, k_pe


def _o_proj(lp: dict, cfg: ModelConfig, out_lat: jnp.ndarray) -> jnp.ndarray:
    """Fold the attention's latent output back through w_vc and flatten
    heads: [.., H, C] f32 -> [.., H*v_head_dim]."""
    _, w_vc = _wkv_b_parts(lp, cfg)
    o = jnp.einsum("...hc,chd->...hd", out_lat, w_vc.astype(jnp.float32))
    return o.reshape(o.shape[:-2] + (-1,))


def mla_prefill_attention_xla(
    q_eff: jnp.ndarray,  # [T, H, C]
    q_pe: jnp.ndarray,  # [T, H, R]
    c_cache_layer: jnp.ndarray,  # [1, N, bs, C] — chunk ALREADY written
    pe_cache_layer: jnp.ndarray,  # [1, N, bs, R]
    block_table: jnp.ndarray,  # [M]
    history_len: jnp.ndarray,  # scalar
    valid_len: jnp.ndarray,  # scalar: real tokens in this chunk
    scale: float,
) -> jnp.ndarray:  # [T, H, C] latent output (caller folds through w_vc)
    """Write-before-attend chunked prefill over latents: every query row
    attends cached history plus the causal prefix of its own chunk, all
    read back through the block table."""
    T, H, C = q_eff.shape
    M = block_table.shape[0]
    bs = c_cache_layer.shape[2]
    ck = jnp.take(c_cache_layer[0], block_table, axis=0).reshape(M * bs, C)
    kp = jnp.take(pe_cache_layer[0], block_table, axis=0).reshape(M * bs, -1)
    s = (
        jnp.einsum("thc,sc->ths", q_eff.astype(jnp.float32) * scale,
                   ck.astype(jnp.float32))
        + jnp.einsum("thr,sr->ths", q_pe.astype(jnp.float32) * scale,
                     kp.astype(jnp.float32))
    )
    q_pos = history_len + jnp.arange(T)  # absolute positions of queries
    s_pos = jnp.arange(M * bs)
    valid = s_pos[None, :] <= q_pos[:, None]  # causal incl. self
    valid &= s_pos[None, :] < history_len + valid_len  # real rows only
    s = jnp.where(valid[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("ths,sc->thc", p, ck.astype(jnp.float32))


def mla_decode_attention_xla(
    q_eff: jnp.ndarray,  # [B, H, C]
    q_pe: jnp.ndarray,  # [B, H, R]
    c_cache_layer: jnp.ndarray,  # [1, N, bs, C] — current token written
    pe_cache_layer: jnp.ndarray,  # [1, N, bs, R]
    block_tables: jnp.ndarray,  # [B, M]
    seq_lens: jnp.ndarray,  # [B] incl. the current token
    scale: float,
) -> jnp.ndarray:  # [B, H, C] latent output
    B, H, C = q_eff.shape
    M = block_tables.shape[1]
    bs = c_cache_layer.shape[2]
    ck = jnp.take(c_cache_layer[0], block_tables, axis=0).reshape(B, M * bs, C)
    kp = jnp.take(pe_cache_layer[0], block_tables, axis=0).reshape(
        B, M * bs, -1
    )
    s = (
        jnp.einsum("bhc,bsc->bhs", q_eff.astype(jnp.float32) * scale,
                   ck.astype(jnp.float32))
        + jnp.einsum("bhr,bsr->bhs", q_pe.astype(jnp.float32) * scale,
                     kp.astype(jnp.float32))
    )
    mask = jnp.arange(M * bs)[None, :] < seq_lens[:, None]
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bsc->bhc", p, ck.astype(jnp.float32))
